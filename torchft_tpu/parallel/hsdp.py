"""HSDP composition: FSDP/TP over ICI inside a replica × FT-DDP over DCN.

The reference composes FSDP2 ``fully_shard`` inside each replica with a
torchft allreduce hook on the replica dimension
(``fsdp_test.py:55-73``, torchtitan per ``README.md:62-69``).  The jax-native
equivalent:

- **inner**: parameters/optimizer state sharded with ``NamedSharding`` over
  the replica group's mesh axes (``fsdp``/``tp``); XLA SPMD inserts the
  all-gathers/reduce-scatters over ICI.
- **outer**: after the compiled grad step, the Manager averages gradients
  across replica groups host-side over DCN — the replica count never enters
  the compiled program, so elastic membership can't trigger recompilation
  (SURVEY.md §7 hard part 1).

Multi-host note: when a replica group spans hosts (one process per host,
``group_rank`` = host index), gradients are non-fully-addressable jax
Arrays.  ``ddp._host_contribution`` ships only this host's unique
addressable shards over the per-``group_rank`` DCN ring (host h of every
replica group addresses the same logical region, so shard-local averaging
is exact) and rebuilds results with
``jax.make_array_from_single_device_arrays`` — the global array is never
materialized on one host.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.ddp import ft_allreduce
from torchft_tpu.manager import Manager


def fsdp_shardings(
    model: Any, mesh: Mesh
) -> Tuple[Any, Any]:
    """(param shardings, batch shardings) for a model exposing
    ``param_specs()`` / ``batch_specs()`` (e.g. :class:`models.llama.Llama`).

    Also attaches ``mesh`` to the model (last call wins): every HSDP entry
    point (``shard_init``/``make_grad_step``/``HSDPTrainer``) funnels
    through here, and the model's attention needs the mesh to dispatch the
    shard_map flash variant instead of silently taking the naive path.
    Consequence: one model object serves one mesh at a time — rebuild (or
    re-enter through this function) when the mesh changes, and don't drive
    a shared model over two meshes concurrently."""
    model.mesh = mesh
    param_specs = model.param_specs()
    params_sh = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    tok_spec, tgt_spec = model.batch_specs()
    batch_sh = (NamedSharding(mesh, tok_spec), NamedSharding(mesh, tgt_spec))
    return params_sh, batch_sh


def shard_init(model: Any, key: jax.Array, mesh: Mesh) -> Any:
    """Initialize params directly into their HSDP layout (jit + out_shardings
    so big models never materialize unsharded)."""
    params_sh, _ = fsdp_shardings(model, mesh)
    with mesh:
        return jax.jit(model.init, out_shardings=params_sh)(key)


def make_grad_step(
    model: Any, mesh: Mesh
) -> Callable[[Any, Any], Tuple[jax.Array, Any]]:
    """Compile ``(params, batch) → (loss, grads)`` with grads sharded like
    params (the FSDP reduce-scatter happens inside via XLA SPMD)."""
    params_sh, batch_sh = fsdp_shardings(model, mesh)

    def _step(params: Any, batch: Any) -> Tuple[jax.Array, Any]:
        return jax.value_and_grad(model.loss)(params, batch)

    with mesh:
        return jax.jit(
            _step,
            in_shardings=(params_sh, batch_sh),
            out_shardings=(NamedSharding(mesh, P()), params_sh),
        )


def match_param_by_suffix(
    path: Tuple, shape: Tuple[int, ...], params_paths: Dict[Tuple, Tuple]
) -> Any:
    """Find the parameter entry whose key-path is a suffix of ``path`` with
    a matching shape — optax embeds the params tree verbatim in every
    params-mirroring opt-state subtree (momentum, Adam mu/nu, ...), so the
    suffix+shape rule maps an opt-state leaf back to its parameter.
    ``params_paths``: ``{path-tuple: (shape-tuple, value)}``; returns the
    matched value or None.  Shared by :func:`sharded_opt_init` (value =
    sharding) and ``parallel.rehearsal`` (value = PartitionSpec)."""
    path = tuple(path)
    for start in range(len(path)):
        hit = params_paths.get(path[start:])
        if hit is not None and hit[0] == tuple(shape):
            return hit[1]
    return None


def sharded_opt_init(tx: Any, params: Any) -> Any:
    """Initialize optimizer state with correct shardings on multi-host.

    ``jax.jit(tx.init)(params)`` is NOT sharding-safe: optimizer-state
    leaves depend only on param *shapes*, so XLA dead-code-eliminates the
    value dependence and is free to pick arbitrary (e.g. single-device)
    output layouts — on a multi-host mesh that makes heal/update layouts
    diverge between hosts.  This pins every params-mirroring leaf (momentum,
    Adam mu/nu, ...) to its param's sharding, matched by key-path suffix
    (optax embeds the params tree verbatim in those subtrees), and
    replicates everything else (step counts etc.).
    """
    params_paths = {
        tuple(path): (tuple(leaf.shape), leaf.sharding)
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
        if isinstance(leaf, jax.Array)
    }
    mesh = None
    for _shape, sharding in params_paths.values():
        if isinstance(sharding, NamedSharding):
            mesh = sharding.mesh
            break

    shapes = jax.eval_shape(tx.init, params)

    def _sharding_for(path: Tuple, shape_struct: Any) -> Any:
        sharding = match_param_by_suffix(
            path, shape_struct.shape, params_paths
        )
        if sharding is not None:
            return sharding
        if mesh is not None:
            return NamedSharding(mesh, P())  # replicated (counts, scalars)
        return None

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    out_shardings = jax.tree_util.tree_unflatten(
        treedef, [_sharding_for(p, s) for p, s in leaves_with_paths]
    )
    return jax.jit(tx.init, out_shardings=out_shardings)(params)


def make_update_step(
    model: Any, tx: Any, mesh: Mesh
) -> Callable[[Any, Any, Any], Tuple[Any, Any]]:
    """Compile the optax update with params/grads/opt_state in HSDP layout."""
    import optax

    params_sh, _ = fsdp_shardings(model, mesh)

    def _update(params: Any, opt_state: Any, grads: Any) -> Tuple[Any, Any]:
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    with mesh:
        return jax.jit(_update, donate_argnums=(0, 1))


class HSDPTrainer:
    """Fault-tolerant HSDP training driver (BASELINE config 3).

    Per step: quorum (async, overlapped) → compiled grad step (FSDP/TP over
    ICI) → replica-dim gradient average (Manager over DCN) → commit-gated
    compiled update.

    **Why the DCN ring sits on the per-step critical path.** The commit
    vote must fence every in-flight collective (a late failure after the
    vote would commit unaveraged gradients — ``Manager.should_commit``;
    the reference synchronizes its accelerator stream at the same point,
    ``torchft/manager.py:888-893``), the update needs the averaged
    gradients, and the next forward needs the update.  Overlapping the
    ring with the next step's compute therefore requires either stale
    gradients or unfenced commits — both unsound for per-step DDP.  The
    framework's levers instead:

    - ``quantize_outer=True``: the int8 wire format (native host kernels +
      windowed wire/reduce pipelining, ``collectives.py``) cuts ring bytes
      4x and round-2 wall time ~2.4x.  Every replica applies the identical
      requantized stream, so replicas stay bit-identical; accuracy vs the
      f32 ring is rowwise-int8 (the reference ships fp8 outer syncs with
      the same caveat).
    - bucket-level pipelining inside ``allreduce_pytree``: D2H of bucket
      k+1 overlaps the ring of bucket k.
    - for delay-tolerant training, :class:`~torchft_tpu.local_sgd.DiLoCo`
      (and streaming fragments) moves the outer sync fully off the
      critical path with its τ-delay worker — that is the sanctioned
      ring/compute-overlap mode, as in the reference.
    """

    def __init__(
        self,
        model: Any,
        tx: Any,
        mesh: Mesh,
        manager: Manager,
        key: Optional[jax.Array] = None,
        params: Optional[Any] = None,
        quantize_outer: bool = False,
    ) -> None:
        self.model = model
        self.tx = tx
        self.mesh = mesh
        self.manager = manager
        self.quantize_outer = quantize_outer
        if params is None:
            assert key is not None, "need key or params"
            params = shard_init(model, key, mesh)
        with mesh:
            opt_state = sharded_opt_init(tx, params)
        self.holder: Dict[str, Any] = {"params": params, "opt_state": opt_state}
        self._grad_step = make_grad_step(model, mesh)
        self._update_step = make_update_step(model, tx, mesh)

        manager.register_state_dict_fn(
            "hsdp", self._load_state, self._save_state
        )

    def _save_state(self) -> Dict[str, Any]:
        return dict(self.holder)

    def _load_state(self, state: Dict[str, Any]) -> None:
        # restore placement: healing delivers host arrays (or per-shard
        # ShardedHostArray bundles from a multi-host sender); put them back
        # into the HSDP layout of the existing values
        from torchft_tpu.ddp import restore_tree_like

        self.holder["params"] = restore_tree_like(
            state["params"], self.holder["params"]
        )
        self.holder["opt_state"] = restore_tree_like(
            state["opt_state"], self.holder["opt_state"]
        )

    def relower(
        self, surviving_devices: Any, plan: Any = None
    ) -> Any:
        """Degraded-mode re-lower onto the surviving devices (device loss
        WITHOUT replica death): rebuild the mesh, reshard params +
        optimizer state, recompile the steps, and fence the commit vote
        across the transition via ``Manager.begin_relower`` /
        ``complete_relower`` — a crash mid-reshard reads as "never voted
        commit".  Returns the applied
        :class:`~torchft_tpu.parallel.degraded.DegradedPlan` (whose
        ``capacity`` the manager now advertises on the wire-v5 tail)."""
        from torchft_tpu.parallel.degraded import relower_hsdp_trainer

        self.manager.begin_relower()
        plan = relower_hsdp_trainer(self, surviving_devices, plan)
        self.manager.complete_relower(plan.capacity)
        return plan

    def train_step(self, batch: Any) -> Tuple[float, bool]:
        """One fault-tolerant step; returns (loss, committed)."""
        self.manager.start_quorum()
        loss, grads = self._grad_step(self.holder["params"], batch)
        grads = ft_allreduce(
            self.manager, grads, should_quantize=self.quantize_outer
        )
        committed = self.manager.should_commit()
        if committed:
            params, opt_state = self._update_step(
                self.holder["params"], self.holder["opt_state"], grads
            )
            self.holder["params"] = params
            self.holder["opt_state"] = opt_state
        return float(loss), committed
