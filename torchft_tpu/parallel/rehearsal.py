"""Scale dress-rehearsal: validate pod-scale configs without the pod.

The reference claims Llama-3 8B/70B fault-tolerant HSDP at cluster scale
(``/root/reference/README.md:62-69``) but has no way to check a config
short of burning the cluster.  On TPU the XLA compilation model lets us do
better: ``jax.jit(...).trace(...).lower(lowering_platforms=("tpu",))`` over
a :class:`jax.sharding.AbstractMesh` traces and SPMD-partitions the REAL
train step for the REAL pod shape on any host, with zero devices — the
full v5p-256 70B program is validated (tracing, sharding propagation,
divisibility, collective layout) in seconds on a CPU box.

What :func:`rehearse` checks per config:

1. **Axis divisibility** — every sharded parameter dim must divide by the
   product of the mesh axes on it (a violation compiles into padded
   shards or fails partitioning at cluster bring-up time).
2. **HBM fit** — per-device bytes for params + grads + optimizer state
   (sharding-aware, optimizer leaves inherit their param's spec exactly
   like ``hsdp.sharded_opt_init``) + a documented activation estimate,
   against the chip's HBM capacity.
3. **Lowering** — the HSDP grad step and optax update step actually
   trace + SPMD-lower for the TPU platform over the abstract mesh.

Run ``python -m torchft_tpu.parallel.rehearsal`` to print the BASELINE
config 2/3/5 table (the one recorded in ``docs/SCALE_REHEARSAL.md``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import AbstractMesh, NamedSharding, PartitionSpec as P

# Per-chip HBM capacity (bytes).  v5p: 95 GB HBM2e per chip; v5e: 16 GB;
# v4: 32 GB; v6e: 32 GB.  Source: public TPU system documentation.
CHIP_HBM_BYTES: Dict[str, float] = {
    "v5p": 95e9,
    "v5e": 16e9,
    "v4": 32e9,
    "v6e": 32e9,
}


@dataclass
class RehearsalReport:
    name: str
    mesh_axes: Dict[str, int]
    n_devices: int
    chip: str
    ok: bool
    divisibility_errors: List[str] = field(default_factory=list)
    bytes_per_device: Dict[str, float] = field(default_factory=dict)
    hbm_bytes: float = 0.0
    hbm_frac: float = 0.0
    lowered_grad: bool = False
    lowered_update: bool = False
    remat: str = "none"
    error: Optional[str] = None

    def summary(self) -> str:
        gb = {k: f"{v / 1e9:.1f}" for k, v in self.bytes_per_device.items()}
        status = "OK" if self.ok else "FAIL"
        return (
            f"{self.name}: {status} mesh={self.mesh_axes} "
            f"({self.n_devices} {self.chip} chips) "
            f"GB/device: params={gb.get('params')} grads={gb.get('grads')} "
            f"opt={gb.get('opt_state')} acts~={gb.get('activations_est')} "
            f"total={gb.get('total')} of {self.hbm_bytes / 1e9:.0f} "
            f"({self.hbm_frac:.0%})"
            + (f" error={self.error}" if self.error else "")
            + (
                f" divisibility={self.divisibility_errors}"
                if self.divisibility_errors
                else ""
            )
        )


def _axes_of(spec_entry: Any) -> Tuple[str, ...]:
    """Mesh axes named by one PartitionSpec dim entry (str | tuple | None)."""
    if spec_entry is None:
        return ()
    if isinstance(spec_entry, str):
        return (spec_entry,)
    return tuple(spec_entry)


def _leaf_report(
    path: str,
    shape: Tuple[int, ...],
    itemsize: int,
    spec: P,
    mesh_axes: Dict[str, int],
    errors: List[str],
) -> float:
    """Per-device bytes for one leaf; records divisibility violations."""
    denom = 1
    for d, entry in enumerate(spec):
        factor = 1
        for axis in _axes_of(entry):
            factor *= mesh_axes.get(axis, 1)
        if factor > 1:
            if d >= len(shape) or shape[d] % factor:
                errors.append(
                    f"{path}: dim {d} ({shape[d] if d < len(shape) else '?'})"
                    f" not divisible by {entry}={factor}"
                )
                continue
            denom *= factor
    return float(np.prod(shape)) * itemsize / denom


def _spec_tree(model: Any) -> Any:
    return model.param_specs()


def _opt_specs(
    params_shapes: Any, param_specs: Any, tx: Any
) -> Tuple[Any, Any]:
    """(opt_state eval_shapes, opt_state PartitionSpecs).  Leaves mirroring
    a parameter (matched by the shared ``hsdp.match_param_by_suffix`` rule)
    inherit its spec; the rest replicate."""
    from torchft_tpu.parallel.hsdp import match_param_by_suffix

    param_paths = {
        tuple(p): (tuple(l.shape), s)
        for (p, l), s in zip(
            jax.tree_util.tree_flatten_with_path(params_shapes)[0],
            jax.tree_util.tree_leaves(
                param_specs, is_leaf=lambda x: isinstance(x, P)
            ),
        )
    }
    opt_shapes = jax.eval_shape(tx.init, params_shapes)

    def _spec_for(path, leaf):
        spec = match_param_by_suffix(path, leaf.shape, param_paths)
        return spec if spec is not None else P()

    leaves, treedef = jax.tree_util.tree_flatten_with_path(opt_shapes)
    specs = jax.tree_util.tree_unflatten(
        treedef, [_spec_for(p, l) for p, l in leaves]
    )
    return opt_shapes, specs


def _activation_estimate(
    config: Any, batch: int, seq: int, mesh_axes: Dict[str, int]
) -> float:
    """Rough per-device activation bytes for the train step.

    With per-layer remat (``config.remat``) the backward keeps (a) the
    residual stream at every layer boundary (``n_layers × B_loc × S_loc ×
    dim``, bf16) and (b) one layer's recompute working set (qkv/o
    projections + ffn intermediates).  Without remat, every layer's
    intermediates stay live for the backward.  Logits (``B_loc × S_loc ×
    vocab_loc``, fp32) dominate the loss head either way.  Assumes flash
    attention (no materialized ``B×H×S×S`` score matrices).  This is an
    estimate — treat < 80% HBM as "fits".
    """
    # batch shards over BOTH dp and fsdp (see ``Llama.batch_specs``)
    bp = mesh_axes.get("dp", 1) * mesh_axes.get("fsdp", 1)
    sp = mesh_axes.get("sp", 1)
    tp = mesh_axes.get("tp", 1)
    b_loc = max(1, batch // bp)
    s_loc = max(1, seq // sp)
    bf16 = 2
    L = config.n_layers
    boundaries = L * b_loc * s_loc * config.dim * bf16
    qkv = 4 * b_loc * s_loc * (config.n_heads // tp) * config.head_dim * bf16
    ffn = 3 * b_loc * s_loc * (config.ffn_hidden // tp) * bf16
    logits = b_loc * s_loc * (config.vocab_size // tp) * 4
    # per remat policy (Llama.effective_remat_mode — the remat_mode knob,
    # not just the legacy bool): which per-layer tensors stay live for the
    # backward vs one recompute working set
    mode = getattr(config, "effective_remat_mode", None) or (
        "layer" if getattr(config, "remat", False) else "none"
    )
    live = {
        "none": L * (qkv + ffn),
        "layer": 2 * (qkv + ffn),
        "attn": L * ffn + 2 * qkv,  # attention side recomputed
        "ffn": L * qkv + 2 * ffn,  # FFN side recomputed
    }[mode]
    return float(boundaries + live + logits)


def rehearse(
    model: Any,
    tx: Any,
    mesh_axes: Dict[str, int],
    batch: int,
    seq: int,
    name: str = "config",
    chip: str = "v5p",
    lower: bool = True,
) -> RehearsalReport:
    """Validate one (model, optimizer, mesh, workload) config abstractly."""
    n_devices = int(np.prod(list(mesh_axes.values())))
    report = RehearsalReport(
        name=name,
        mesh_axes=dict(mesh_axes),
        n_devices=n_devices,
        chip=chip,
        ok=False,
        hbm_bytes=CHIP_HBM_BYTES[chip],
        remat=getattr(model.config, "effective_remat_mode", "none"),
    )
    cfg = model.config
    errors = report.divisibility_errors

    # batch/seq divisibility over data axes (batch shards over dp × fsdp)
    bp = mesh_axes.get("dp", 1) * mesh_axes.get("fsdp", 1)
    if batch % bp:
        errors.append(f"batch {batch} % dp*fsdp {bp}")
    if seq % mesh_axes.get("sp", 1):
        errors.append(f"seq {seq} % sp {mesh_axes['sp']}")

    params_shapes = jax.eval_shape(
        lambda k: model.init(k), jax.ShapeDtypeStruct((2,), jnp.uint32)
    )
    param_specs = _spec_tree(model)

    # params + grads, sharding-aware
    p_leaves = list(
        zip(
            [
                "/".join(str(getattr(k, "key", k)) for k in p)
                for p, _ in jax.tree_util.tree_flatten_with_path(params_shapes)[0]
            ],
            jax.tree_util.tree_leaves(params_shapes),
            jax.tree_util.tree_leaves(
                param_specs, is_leaf=lambda x: isinstance(x, P)
            ),
        )
    )
    params_b = sum(
        _leaf_report(
            path, tuple(l.shape), l.dtype.itemsize, spec, mesh_axes, errors
        )
        for path, l, spec in p_leaves
    )
    opt_shapes, opt_specs = _opt_specs(params_shapes, param_specs, tx)
    opt_errors: List[str] = []
    opt_b = sum(
        _leaf_report(
            "opt", tuple(l.shape), l.dtype.itemsize, spec, mesh_axes, opt_errors
        )
        for l, spec in zip(
            jax.tree_util.tree_leaves(opt_shapes),
            jax.tree_util.tree_leaves(
                opt_specs, is_leaf=lambda x: isinstance(x, P)
            ),
        )
    )
    acts_b = _activation_estimate(cfg, batch, seq, mesh_axes)
    total = params_b * 2 + opt_b + acts_b  # grads mirror params
    report.bytes_per_device = {
        "params": params_b,
        "grads": params_b,
        "opt_state": opt_b,
        "activations_est": acts_b,
        "total": total,
    }
    report.hbm_frac = total / report.hbm_bytes

    if lower and not errors:
        import os

        prev_mesh = getattr(model, "mesh", None)
        prev_env = os.environ.get("TORCHFT_FLASH_PLATFORM")
        try:
            mesh = AbstractMesh(
                tuple(mesh_axes.values()), tuple(mesh_axes.keys())
            )
            # lower the program that will RUN on the pod: attach the mesh
            # and assume the TPU platform so kernel dispatch picks the
            # sharded Mosaic flash path, not the host's naive fallback
            model.mesh = mesh
            os.environ["TORCHFT_FLASH_PLATFORM"] = "tpu"
            params_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s),
                param_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            params_in = jax.tree_util.tree_map(
                lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh),
                params_shapes,
                params_sh,
            )
            tok_spec, _ = model.batch_specs()
            tok = jax.ShapeDtypeStruct(
                (batch, seq), jnp.int32, sharding=NamedSharding(mesh, tok_spec)
            )

            def _grad(params, b):
                return jax.value_and_grad(model.loss)(params, b)

            jax.jit(
                _grad,
                out_shardings=(NamedSharding(mesh, P()), params_sh),
            ).trace(params_in, (tok, tok)).lower(lowering_platforms=("tpu",))
            report.lowered_grad = True

            import optax

            opt_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s),
                opt_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            opt_in = jax.tree_util.tree_map(
                lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh),
                opt_shapes,
                opt_sh,
            )

            def _update(params, opt_state, grads):
                updates, opt_state = tx.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state

            jax.jit(_update).trace(params_in, opt_in, params_in).lower(
                lowering_platforms=("tpu",)
            )
            report.lowered_update = True
        except Exception as e:  # noqa: BLE001 — the report IS the output
            report.error = f"{type(e).__name__}: {e}"
        finally:
            model.mesh = prev_mesh
            if prev_env is None:
                os.environ.pop("TORCHFT_FLASH_PLATFORM", None)
            else:
                os.environ["TORCHFT_FLASH_PLATFORM"] = prev_env

    report.ok = bool(
        not errors
        and not report.error
        and report.hbm_frac < 0.8
        and (not lower or (report.lowered_grad and report.lowered_update))
    )
    return report


def baseline_reports(lower: bool = True) -> List[RehearsalReport]:
    """BASELINE.json configs 2/3/5, with per-replica-group meshes.

    Device-count convention: "v5p-N" is read as N *chips* (one jax device
    per chip, megacore); the per-group mesh is total chips / replica
    groups.  Sequence length 8192 (Llama-3 native).
    """
    import dataclasses

    import optax

    from torchft_tpu.models.llama import Llama, llama3_8b, llama3_70b

    tx = optax.adamw(3e-4)
    reports = []
    # per-layer remat is how these configs actually run (and what
    # _activation_estimate models) — the lowered program must match the
    # HBM verdict, so rehearse the remat'd step, not the default
    remat = lambda cfg: dataclasses.replace(cfg, remat=True)  # noqa: E731
    # config 2: FT-DDP 8B, 4 replica groups on v5p-32 → 8 chips/group.
    # "DDP" inside a group = model replicated per chip won't fit 8B+Adam on
    # 95 GB alongside activations at batch 8; the TPU-native reading of
    # per-group DDP is fsdp-only sharding (pure ZeRO, no TP) — still one
    # allreduce-equivalent per step, params sharded.
    m8 = Llama(remat(llama3_8b()))
    reports.append(
        rehearse(
            m8, tx, {"dp": 1, "fsdp": 8, "tp": 1}, batch=8, seq=8192,
            name="config2_8b_ddp_v5p32_4groups", lower=lower,
        )
    )
    # config 3: HSDP 8B, v5p-64, 4 groups → 16 chips/group: fsdp=8 × tp=2
    reports.append(
        rehearse(
            m8, tx, {"dp": 1, "fsdp": 8, "tp": 2}, batch=16, seq=8192,
            name="config3_8b_hsdp_v5p64_4groups", lower=lower,
        )
    )
    # config 5: 70B HSDP, v5p-256, 4 groups → 64 chips/group: fsdp=16 × tp=4
    m70 = Llama(remat(llama3_70b()))
    reports.append(
        rehearse(
            m70, tx, {"dp": 1, "fsdp": 16, "tp": 4}, batch=16, seq=8192,
            name="config5_70b_hsdp_v5p256_4groups", lower=lower,
        )
    )
    return reports


def quant_kernel_reports() -> List[Dict[str, Any]]:
    """Lowering-level proof for the device quant kernels (round-4 verdict
    item 9), the twin of the flash-kernel check above: trace + TPU-lower
    every Pallas kernel in ``ops/pallas_quant`` — quantize, fused
    dequant-sum-requant reduce, dequantize — for both wire kinds.  Mosaic
    serializes into the lowered module, so a kernel whose program Mosaic
    cannot EXPRESS fails here on any host; whether a given chip generation
    can COMPILE the fp8 conversion ops still needs metal, which is what the
    runtime probe ``pallas_quant._pallas_kind_ok`` covers (reference twin:
    ``torchft/quantization.py:531-686``)."""
    import functools

    from torchft_tpu.ops import pallas_quant as pq

    rows: List[Dict[str, Any]] = []
    for kind in (pq.INT8, pq.FP8):
        wire = pq._wire_jnp_dtype(kind)
        cases = (
            (
                "quantize",
                functools.partial(
                    pq._pallas_quantize,
                    row_size=pq.ROW_SIZE,
                    kind=kind,
                    interpret=False,
                ),
                (
                    jax.ShapeDtypeStruct(
                        (pq.BLOCK_ROWS * pq.ROW_SIZE,), jnp.float32
                    ),
                ),
            ),
            (
                "reduce",
                functools.partial(pq._pallas_reduce, kind=kind, interpret=False),
                (
                    jax.ShapeDtypeStruct((2, pq.BLOCK_ROWS, pq.ROW_SIZE), wire),
                    jax.ShapeDtypeStruct((2, pq.BLOCK_ROWS, 1), jnp.float32),
                ),
            ),
            (
                "dequantize",
                functools.partial(pq._pallas_dequant, interpret=False),
                (
                    jax.ShapeDtypeStruct((pq.BLOCK_ROWS, pq.ROW_SIZE), wire),
                    jax.ShapeDtypeStruct((pq.BLOCK_ROWS, 1), jnp.float32),
                ),
            ),
        )
        for name, fn, args in cases:
            row: Dict[str, Any] = {"kernel": name, "kind": kind}
            try:
                jax.jit(fn).trace(*args).lower(lowering_platforms=("tpu",))
                row["lowered"] = True
            except Exception as e:  # noqa: BLE001 — the report IS the output
                row["lowered"] = False
                row["error"] = f"{type(e).__name__}: {e}"
            rows.append(row)
    return rows


def main() -> None:
    # the rehearsal is device-free: pin the CPU backend so tracing never
    # dials a (possibly wedged) TPU tunnel — model code probes
    # ``jax.default_backend()`` for kernel dispatch during trace
    jax.config.update("jax_platforms", "cpu")
    for r in baseline_reports():
        print(r.summary())
    for row in quant_kernel_reports():
        status = "ok" if row["lowered"] else f"FAIL ({row.get('error')})"
        print(f"quant kernel {row['kernel']}[{row['kind']}]: {status}")


if __name__ == "__main__":
    main()
