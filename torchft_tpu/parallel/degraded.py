"""Degraded-mode re-lowering: keep a wounded replica contributing.

Today a single dead device fails its whole replica group; Nonuniform
Tensor Parallelism (arxiv 2504.06095) and SPARe (arxiv 2603.00357) show
that re-shaping the inner parallelism onto the survivors turns cliff-edge
fleet shrink into graceful capacity decay.  This module is the in-replica
half of that design (the fleet half — capacity-weighted outer reduce,
data-shard rescale, the lighthouse's wound→swap→evict ladder — lives in
``manager.py`` / ``collectives.py`` / ``data.py`` / ``lighthouse.py``):

1. :func:`plan_surviving` — pick the best tp×fsdp×pp×ep layout for the
   surviving device count.  Candidates are every factorization of every
   ``m <= n_surviving`` (most devices first); when a model is given each
   candidate is dry-run through the existing :mod:`rehearsal` layer
   (divisibility + sharding-aware HBM fit, optional abstract-mesh
   lowering — the MULTICHIP_r05 machinery) and the first plan that
   rehearses clean wins.  The plan's ``capacity`` fraction
   (``devices_used / original_devices``) is exactly what the Manager
   advertises on the wire-v5 capacity tail.
2. :func:`relower_hsdp_trainer` — apply a plan to a live
   :class:`~torchft_tpu.parallel.hsdp.HSDPTrainer`-shaped object: rebuild
   the mesh on the survivors, ``device_put`` params and optimizer state
   into the new layout (the reshard), and recompile the grad/update
   steps.  Call between ``Manager.begin_relower()`` and
   ``Manager.complete_relower(plan.capacity)`` so a crash mid-reshard can
   never vote commit.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# Axes a degraded re-lower may redistribute over, innermost-preference
# order: fsdp first (parameter sharding buys back the HBM the lost device
# held), then tp, then ep/pp.  ``dp``/``sp`` follow the chosen plan only
# when the original mesh used them; the default planner leaves them at 1.
RELOWER_AXES: Tuple[str, ...] = ("fsdp", "tp", "ep", "pp")


@dataclass(frozen=True)
class DegradedPlan:
    """One surviving-device layout: the mesh axes to re-lower onto, how
    many devices it uses, and the capacity fraction to advertise."""

    mesh_axes: Dict[str, int] = field(default_factory=dict)
    devices_used: int = 0
    original_devices: int = 0
    report: Optional[Any] = None  # RehearsalReport when a model was given

    @property
    def capacity(self) -> float:
        if self.original_devices <= 0:
            return 1.0
        return self.devices_used / self.original_devices


def _factorizations(m: int, axes: Sequence[str]) -> List[Dict[str, int]]:
    """Every assignment of factors of ``m`` to ``axes`` (product == m)."""
    if not axes:
        return [{}] if m == 1 else []
    head, rest = axes[0], axes[1:]
    out: List[Dict[str, int]] = []
    f = 1
    while f <= m:
        if m % f == 0:
            for tail in _factorizations(m // f, rest):
                out.append({head: f, **tail})
        f += 1
    return out


def surviving_layouts(
    n_surviving: int, axes: Sequence[str] = RELOWER_AXES
) -> List[Dict[str, int]]:
    """Candidate layouts for a wounded replica, best-first: most devices
    used, then the most fsdp (parameter sharding buys back the dead
    device's HBM share), then the flattest split.  Deterministic — every
    observer ranks the same plan first."""
    candidates: List[Dict[str, int]] = []
    for m in range(n_surviving, 0, -1):
        candidates.extend(_factorizations(m, axes))

    def _key(layout: Dict[str, int]) -> tuple:
        used = 1
        for v in layout.values():
            used *= v
        return (
            -used,
            -layout.get("fsdp", 1),
            -layout.get("tp", 1),
            tuple(sorted(layout.items())),
        )

    return sorted(candidates, key=_key)


def plan_surviving(
    n_surviving: int,
    original_devices: int,
    model: Any = None,
    tx: Any = None,
    batch: int = 8,
    seq: int = 2048,
    chip: str = "v5p",
    axes: Sequence[str] = RELOWER_AXES,
    lower: bool = False,
) -> DegradedPlan:
    """Pick the best layout for ``n_surviving`` of ``original_devices``
    devices.

    With a ``model`` (and ``tx``), each candidate is validated through
    :func:`torchft_tpu.parallel.rehearsal.rehearse` — axis divisibility
    and the sharding-aware HBM estimate must pass (plus abstract-mesh
    lowering when ``lower=True``); the first candidate that rehearses
    clean wins.  Without a model the structural ranking alone decides
    (the drill / thread-plane path).  Raises when no layout fits — the
    caller should then let the replica die normally (eviction beats
    training on a layout that cannot hold the model)."""
    if n_surviving < 1:
        raise ValueError(
            f"no surviving devices to re-lower onto ({n_surviving})"
        )
    if n_surviving > original_devices:
        raise ValueError(
            f"survivors ({n_surviving}) exceed the original device count "
            f"({original_devices})"
        )
    candidates = surviving_layouts(n_surviving, axes)
    if model is None:
        layout = candidates[0]
        used = 1
        for v in layout.values():
            used *= v
        return DegradedPlan(
            mesh_axes=dict(layout),
            devices_used=used,
            original_devices=original_devices,
        )
    from torchft_tpu.parallel.rehearsal import rehearse

    last_report = None
    for layout in candidates:
        used = 1
        for v in layout.values():
            used *= v
        report = rehearse(
            model,
            tx,
            dict(layout),
            batch=batch,
            seq=seq,
            name=f"degraded_{used}of{original_devices}",
            chip=chip,
            lower=lower,
        )
        last_report = report
        if report.ok:
            return DegradedPlan(
                mesh_axes=dict(layout),
                devices_used=used,
                original_devices=original_devices,
                report=report,
            )
    raise RuntimeError(
        "no surviving-device layout rehearses clean for "
        f"{n_surviving}/{original_devices} devices (last: "
        f"{last_report.summary() if last_report else 'none'})"
    )


def chaos_device_loss() -> int:
    """Process-plane chaos injection (``chaos.Failure.DEVICE_LOSS``): how
    many of this replica's devices "died" before startup, from
    ``TORCHFT_CHAOS_DEVICE_LOSS`` in the group's spawn env.  0 when the
    knob is unset — the normal case."""
    from torchft_tpu import knobs

    return max(0, knobs.get_int("TORCHFT_CHAOS_DEVICE_LOSS", 0))


def startup_surviving_devices(devices: Sequence[Any]) -> List[Any]:
    """Apply the process-plane device-loss chaos knob at startup: the last
    N devices are treated as dead (at least one always survives).  Workers
    that build their mesh from this list come up wounded and should plan
    via :func:`plan_surviving` + advertise ``plan.capacity``."""
    lost = chaos_device_loss()
    devices = list(devices)
    if lost <= 0:
        return devices
    survivors = max(1, len(devices) - lost)
    logger.warning(
        "chaos: %d of %d devices lost before startup — coming up wounded",
        len(devices) - survivors,
        len(devices),
    )
    return devices[:survivors]


def reshard_params(params: Any, specs: Any, mesh: Any) -> Any:
    """``device_put`` a param tree into its PartitionSpec layout on a new
    (smaller) mesh — the reshard half of a re-lower.  Values are moved,
    never recomputed: the wounded replica keeps exactly the state it had,
    only the placement changes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.tree_util.tree_map(
        lambda leaf, sh: jax.device_put(leaf, sh), params, shardings
    )


def _reshard_opt_state(opt_state: Any, params: Any, mesh: Any) -> Any:
    """Reshard optimizer state onto ``mesh``: params-mirroring leaves
    (momentum, Adam mu/nu — matched by the shared suffix+shape rule)
    inherit their freshly-placed param's sharding, everything else
    replicates — the same rule ``hsdp.sharded_opt_init`` pins at init."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchft_tpu.parallel.hsdp import match_param_by_suffix

    params_paths = {
        tuple(path): (tuple(leaf.shape), leaf.sharding)
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
        if isinstance(leaf, jax.Array)
    }

    def _place(path: Tuple, leaf: Any) -> Any:
        sharding = match_param_by_suffix(
            path, getattr(leaf, "shape", ()), params_paths
        )
        if sharding is None:
            sharding = NamedSharding(mesh, P())
        return jax.device_put(leaf, sharding)

    leaves, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
    return jax.tree_util.tree_unflatten(
        treedef, [_place(p, leaf) for p, leaf in leaves]
    )


def relower_hsdp_trainer(
    trainer: Any,
    surviving_devices: Sequence[Any],
    plan: Optional[DegradedPlan] = None,
) -> DegradedPlan:
    """Re-lower a live HSDP trainer onto ``surviving_devices``.

    ``trainer`` is anything HSDPTrainer-shaped: ``model`` / ``tx`` /
    ``mesh`` / ``holder`` (params + opt_state) plus the compiled
    ``_grad_step`` / ``_update_step`` slots.  Sequencing contract: call
    ``manager.begin_relower()`` first and ``manager.complete_relower(
    plan.capacity)`` after this returns — a crash anywhere in between
    reads as "never voted commit"."""
    from torchft_tpu.parallel.hsdp import (
        fsdp_shardings,
        make_grad_step,
        make_update_step,
    )
    from torchft_tpu.parallel.mesh import make_mesh

    original = int(trainer.mesh.devices.size)
    if plan is None:
        plan = plan_surviving(
            len(surviving_devices), original_devices=original
        )
    if plan.devices_used > len(surviving_devices):
        raise ValueError(
            f"plan needs {plan.devices_used} devices, only "
            f"{len(surviving_devices)} survive"
        )
    new_mesh = make_mesh(
        devices=list(surviving_devices)[: plan.devices_used],
        **plan.mesh_axes,
    )
    params_specs = trainer.model.param_specs()
    trainer.holder["params"] = reshard_params(
        trainer.holder["params"], params_specs, new_mesh
    )
    trainer.holder["opt_state"] = _reshard_opt_state(
        trainer.holder["opt_state"], trainer.holder["params"], new_mesh
    )
    trainer.mesh = new_mesh
    # recompile for the new layout (fsdp_shardings re-attaches the mesh to
    # the model as a side effect — both step builders funnel through it)
    fsdp_shardings(trainer.model, new_mesh)
    trainer._grad_step = make_grad_step(trainer.model, new_mesh)
    trainer._update_step = make_update_step(trainer.model, trainer.tx, new_mesh)
    logger.warning(
        "re-lowered onto %d/%d devices (%s) — capacity %.3f",
        plan.devices_used,
        plan.original_devices or original,
        plan.mesh_axes,
        plan.capacity,
    )
    return plan
