"""Device mesh construction for the intra-replica-group axes.

A replica group owns one slice of TPUs; inside it we build a
``jax.sharding.Mesh`` with up to six axes:

- ``pp``   — pipeline parallelism (layer stages, GPipe microbatching)
- ``dp``   — within-group data parallelism (batch dim)
- ``fsdp`` — parameter/optimizer sharding (the FSDP dimension of HSDP)
- ``ep``   — expert parallelism (MoE expert dispatch via all_to_all)
- ``tp``   — tensor (megatron) parallelism for the matmuls
- ``sp``   — sequence/context parallelism for long sequences (ring
  attention over ``ppermute``)

The outer fault-tolerant replica dimension deliberately has NO axis here:
compiled programs must not bake in the replica count (SURVEY.md §7 hard
part 1), so replica-dim averaging runs host-side in the Manager.

Reference contrast: torchft composes with torch DeviceMesh/FSDP2 inside a
replica (``fsdp_test.py:55-73``); this module is the jax-native equivalent.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshAxes:
    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def total(self) -> int:
        return self.pp * self.dp * self.fsdp * self.tp * self.sp * self.ep


AXIS_NAMES: Tuple[str, ...] = ("pp", "dp", "fsdp", "ep", "sp", "tp")


def make_mesh(
    dp: int = 1,
    fsdp: int = 1,
    tp: int = 1,
    sp: int = 1,
    ep: int = 1,
    pp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh with axes (pp, dp, fsdp, ep, sp, tp).

    Axis order puts ``tp`` innermost so tensor-parallel collectives ride the
    fastest ICI links, then ``sp`` (ring attention neighbor exchanges) and
    ``ep`` (MoE all_to_all), with ``dp``/``fsdp`` next and ``pp`` outermost
    (stage hops are low-volume point-to-point activation sends, the one
    traffic class that tolerates the slowest links) — the standard layout
    recipe for TPU pods.
    """
    axes = MeshAxes(pp=pp, dp=dp, fsdp=fsdp, tp=tp, sp=sp, ep=ep)
    if devices is None:
        devices = jax.devices()
    if axes.total > len(devices):
        raise ValueError(
            f"mesh needs {axes.total} devices, only {len(devices)} available"
        )
    devices = np.asarray(devices[: axes.total]).reshape(
        pp, dp, fsdp, ep, sp, tp
    )
    return Mesh(devices, AXIS_NAMES)


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """device_put every leaf with its PartitionSpec (specs matches tree)."""
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        tree,
        specs,
        is_leaf=lambda x: x is None,
    )


def named_sharding(mesh: Mesh, *spec: Any) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
