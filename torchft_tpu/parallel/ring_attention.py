"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Net-new relative to the reference (torchft has no sequence parallelism,
SURVEY.md §5.7) but first-class here: long-context training must scale past
one chip's HBM, and the TPU-native way is blockwise causal attention with
K/V blocks rotating around the ``sp`` ring via ``lax.ppermute`` over ICI
(the Ring Attention construction, with flash-style online-softmax
accumulation so memory stays O(block)).

Layout: Q/K/V are sharded on the sequence dim over ``sp`` (and heads over
``tp``); each of the ``n`` ring steps overlaps one neighbor exchange with
one block of attention math.  Causality across blocks falls out of global
block indices: a K/V block from a later position contributes nothing, the
diagonal block is masked triangularly, earlier blocks attend fully.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from torchft_tpu.parallel._compat import shard_map as _shard_map


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
) -> jax.Array:
    """shard_map body: q is a LOCAL block [B, S_blk, H, D]; k/v are LOCAL
    blocks [B, S_blk, KV, D] with H % KV == 0 (GQA **un-repeated** — the
    ring ships the grouped K/V and broadcasts to full heads only at
    compute time, cutting ppermute bytes by the group factor).

    Online softmax across ring steps (numerically stable streaming
    accumulation); one ppermute per step rotates the K/V block to the next
    neighbor so every block visits every rank.
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    groups = H // k.shape[2]
    scale = 1.0 / np.sqrt(D)

    # per-block flash: the Pallas kernel replaces the einsum-softmax block
    # math when block shapes qualify (trace-time decision; TORCHFT_FLASH
    # env forces/kills, interpret off-TPU)
    env = os.environ.get("TORCHFT_FLASH", "")
    if (
        env != "0"
        and S >= 128
        and S % 8 == 0  # Mosaic sublane-divisibility, same gate as _use_flash
        and S % min(512, S) == 0
        and (env == "1" or jax.default_backend() == "tpu")
    ):
        return _ring_attention_flash(q, k, v, axis_name, n, my_idx)

    q32 = q.astype(jnp.float32)
    # accumulators: running output (unnormalized), row max, denominator
    o = jnp.zeros((B, S, H, D), dtype=jnp.float32)
    m = jnp.full((B, S, H), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((B, S, H), dtype=jnp.float32)

    # local positions within a block (global offset falls out of block idx)
    row_pos = jnp.arange(S)
    col_pos = jnp.arange(S)

    def step(carry, step_idx):
        o, m, l, k_blk, v_blk = carry
        src_idx = (my_idx - step_idx) % n  # whose block we hold this step

        # broadcast the grouped K/V block to full heads at compute time
        k_full = jnp.repeat(k_blk, groups, axis=2)
        v_full = jnp.repeat(v_blk, groups, axis=2)
        scores = (
            jnp.einsum("bqhd,bkhd->bqhk", q32, k_full.astype(jnp.float32))
            * scale
        )
        # causal mask from global block indices:
        #   src block earlier   → attend fully
        #   same block          → lower triangle
        #   src block later     → nothing
        tri = row_pos[:, None] >= col_pos[None, :]
        allow = jnp.where(
            src_idx < my_idx,
            jnp.ones((S, S), dtype=bool),
            jnp.where(src_idx == my_idx, tri, jnp.zeros((S, S), dtype=bool)),
        )
        scores = jnp.where(allow[None, :, None, :], scores, -1e30)

        blk_max = jnp.max(scores, axis=-1)  # [B,S,H]
        m_new = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])  # [B,S,H,K]
        l_new = l * correction + jnp.sum(p, axis=-1)
        o_new = o * correction[..., None] + jnp.einsum(
            "bqhk,bkhd->bqhd", p, v_full.astype(jnp.float32)
        )

        # rotate K/V to the next rank (ring over ICI)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o, m, l, k, v), jnp.arange(n)
    )
    # rows that attended to nothing (can't happen causally, but guard /0)
    denom = jnp.where(l > 0, l, 1.0)
    return (o / denom[..., None]).astype(q.dtype)


def _ring_attention_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    n: int,
    my_idx: jax.Array,
) -> jax.Array:
    """Ring attention with the fused Pallas kernel as the per-block math.

    Each ring step runs :func:`flash_attention_lse` on the held K/V block
    (causal for the diagonal block, unmasked for earlier blocks, skipped
    for later ones — the same block relationship the einsum path masks
    with) and merges the normalized partials exactly via logsumexp:
    ``lse' = logaddexp(lse, lse_b)``,
    ``o' = o·exp(lse−lse') + o_b·exp(lse_b−lse')``.

    Step 0 is always the diagonal block, so ``lse`` is finite from the
    first merge and the −inf initializations never meet each other.
    """
    from torchft_tpu.ops.flash_attention import flash_attention_lse

    interpret = jax.default_backend() != "tpu"
    B, S, H, D = q.shape

    def _block(causal):
        def run(k_blk, v_blk):
            return flash_attention_lse(
                q, k_blk, v_blk, causal=causal, interpret=interpret
            )

        return run

    diag, full = _block(True), _block(False)

    def skip(k_blk, v_blk):
        return (
            jnp.zeros((B, S, H, D), q.dtype),
            jnp.full((B, S, H), -jnp.inf, jnp.float32),
        )

    o0 = jnp.zeros((B, S, H, D), jnp.float32)
    lse0 = jnp.full((B, S, H), -jnp.inf, jnp.float32)

    def step(carry, step_idx):
        o, lse, k_blk, v_blk = carry
        src_idx = (my_idx - step_idx) % n
        o_b, lse_b = jax.lax.cond(
            src_idx == my_idx,
            diag,
            lambda kb, vb: jax.lax.cond(src_idx < my_idx, full, skip, kb, vb),
            k_blk,
            v_blk,
        )
        lse_new = jnp.logaddexp(lse, lse_b)
        w_old = jnp.exp(lse - lse_new)
        w_new = jnp.exp(lse_b - lse_new)
        o = o * w_old[..., None] + o_b.astype(jnp.float32) * w_new[..., None]

        perm = [(j, (j + 1) % n) for j in range(n)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, lse_new, k_next, v_next), None

    (o, _, _, _), _ = jax.lax.scan(step, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    sp_axis: str = "sp",
) -> jax.Array:
    """Ring attention entry point for jit-traced (global-shape) arrays.

    q: [B, S, H, D]; k/v: [B, S, KV, D] un-repeated (H % KV == 0), with S
    sharded over ``sp_axis``, B over ``(dp, fsdp)`` (activations shard
    over the fsdp axis too — ``Llama.batch_specs``), and heads over
    ``tp``; returns attention output in q's layout.
    """
    batch_entry = ("dp", "fsdp") if "fsdp" in mesh.shape else "dp"
    spec = P(batch_entry, sp_axis, "tp", None)
    fn = _shard_map(
        partial(_ring_attention_local, axis_name=sp_axis),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str = "sp"
) -> jax.Array:
    """Raw collective form for callers already inside shard_map/pmap."""
    return _ring_attention_local(q, k, v, axis_name)
