"""Parameter server prototype: reconfigurable communicators without a
lighthouse.

Twin of the reference prototype (``torchft/parameter_server.py:30-194``): it
demonstrates that the data-plane building blocks compose outside the
Manager/quorum protocol.  A server hands out sessions over HTTP
(``/new_session`` → ``{session_id, store_addr}``); for each session it
configures a fresh world-size-2 communicator (server rank 0, client rank 1)
under a per-session store namespace, then serves parameter fetches /
gradient pushes over plain collectives.

Usage::

    ps = ParameterServer(params={"w": np.zeros(10)})
    # client side
    client = ParameterServerClient(ps.address())
    params = client.get_params({"w": np.zeros(10)})  # broadcast from server
    client.push_grads({"w": grads})                  # summed into server copy
"""

from __future__ import annotations

import json
import logging
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.request import urlopen

import numpy as np

from torchft_tpu.communicator import Communicator, ReduceOp, TCPCommunicator
from torchft_tpu.store import StoreServer

logger = logging.getLogger(__name__)


class ParameterServer:
    def __init__(
        self,
        params: Dict[str, np.ndarray],
        bind: str = "0.0.0.0:0",
        timeout_s: float = 60.0,
        comm_factory=TCPCommunicator,
    ) -> None:
        self._params = {k: np.asarray(v, dtype=np.float32) for k, v in params.items()}
        self._timeout_s = timeout_s
        self._comm_factory = comm_factory
        self._store = StoreServer("0.0.0.0:0")
        self._lock = threading.Lock()

        ps = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: object) -> None:
                logger.debug("parameter_server: " + fmt, *args)

            def do_GET(self) -> None:
                if self.path != "/new_session":
                    self.send_error(404)
                    return
                session_id = str(uuid.uuid4())
                store_addr = f"127.0.0.1:{ps._store.port}/ps/{session_id}"
                body = json.dumps(
                    {"session_id": session_id, "store_addr": store_addr}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                # serve the session on its own thread (server is rank 0)
                threading.Thread(
                    target=ps._serve_session,
                    args=(store_addr,),
                    daemon=True,
                ).start()

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        host, port = bind.rsplit(":", 1)
        self._http = _Server((host, int(port)), _Handler)
        self._port: int = self._http.server_address[1]
        threading.Thread(
            target=self._http.serve_forever, name="tpuft_ps_http", daemon=True
        ).start()

    @property
    def port(self) -> int:
        return self._port

    def address(self) -> str:
        return f"http://127.0.0.1:{self._port}"

    def params(self) -> Dict[str, np.ndarray]:
        with self._lock:
            return {k: v.copy() for k, v in self._params.items()}

    def _serve_session(self, store_addr: str) -> None:
        comm: Optional[Communicator] = None
        try:
            comm = self._comm_factory(timeout_s=self._timeout_s)
            comm.configure(
                store_addr, replica_id="ps_server", rank=0, world_size=2
            )
            # one fetch + one push per session (the prototype protocol);
            # copies — concurrent sessions mutate the originals in place
            with self._lock:
                snapshot = [self._params[k].copy() for k in sorted(self._params)]
            comm.broadcast(snapshot, root=0).wait(timeout=self._timeout_s)
            summed = comm.allreduce(
                [np.zeros_like(a) for a in snapshot], ReduceOp.SUM
            ).wait(timeout=self._timeout_s)
            with self._lock:
                for key, grad in zip(sorted(self._params), summed):
                    self._params[key] += grad
        except Exception as e:  # noqa: BLE001
            logger.warning("parameter server session failed: %s", e)
        finally:
            if comm is not None:
                comm.shutdown()

    def shutdown(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        self._store.shutdown()


class ParameterServerClient:
    """One-session client: fetch params, push gradients."""

    def __init__(self, address: str, timeout_s: float = 60.0, comm_factory=TCPCommunicator) -> None:
        with urlopen(f"{address}/new_session", timeout=timeout_s) as resp:
            session = json.loads(resp.read())
        self._comm = comm_factory(timeout_s=timeout_s)
        self._comm.configure(
            session["store_addr"], replica_id="ps_client", rank=1, world_size=2
        )
        self._timeout_s = timeout_s
        self._param_keys: Optional[list] = None
        self._shapes: Optional[list] = None

    def get_params(self, template: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        self._param_keys = sorted(template)
        bufs = [
            np.zeros_like(np.asarray(template[k], dtype=np.float32))
            for k in self._param_keys
        ]
        received = self._comm.broadcast(bufs, root=0).wait(timeout=self._timeout_s)
        return dict(zip(self._param_keys, received))

    def push_grads(self, grads: Dict[str, np.ndarray]) -> None:
        assert self._param_keys is not None, "call get_params first"
        bufs = [
            np.asarray(grads[k], dtype=np.float32) for k in self._param_keys
        ]
        self._comm.allreduce(bufs, ReduceOp.SUM).wait(timeout=self._timeout_s)

    def close(self) -> None:
        self._comm.shutdown()
