"""Runtime tier selection: C++ native plane vs pure-Python fallback.

The C++ runtime (``native/libtpuft.so``) is the production tier: per-lane
worker threads driving scatter-gather (sendmsg/recvmsg) framed collectives,
native lighthouse/manager servers speaking the same framed wire protocol as
their Python twins (``tests/test_native.py`` proves cross-tier interop,
including mixed-tier meshes).  The Python tier exists so the framework runs
anywhere the shared library doesn't build — and it remains the only tier
with hierarchical/shm topology dispatch, fault injection, and in-epoch lane
recovery.  This mirrors the reference, whose benched production path is
NCCL while Gloo is the portable fallback
(``torchft/process_group.py:643-891``).

``TORCHFT_TIER`` selects explicitly: ``cpp`` | ``python`` | ``auto``
(default — cpp whenever the library loads).  For the **data plane**
specifically (:func:`make_communicator`), ``auto`` additionally downgrades
to the Python tier when hierarchical dispatch is forced on
(``TORCHFT_HIERARCHICAL=1``): the native mesh speaks only the flat-ring
schedule today, and a forced-hierarchical fleet must not silently lose its
topology dispatch.  The downgrade is a single loud log line.
"""

from __future__ import annotations

import logging
from typing import Optional

from torchft_tpu import knobs

logger = logging.getLogger("torchft_tpu.tier")

TIER_ENV = "TORCHFT_TIER"


def _tier_env() -> str:
    return knobs.get_str(TIER_ENV, "auto").lower()


def default_tier() -> str:
    """Resolve the active tier name ("cpp" or "python")."""
    env = _tier_env()
    if env in ("cpp", "python"):
        return env
    if env not in ("", "auto"):
        logger.warning("unknown %s=%r; using auto", TIER_ENV, env)
    try:
        from torchft_tpu import native

        return "cpp" if native.available() else "python"
    except Exception:  # noqa: BLE001 — a broken build falls back, not crashes
        return "python"


def data_plane_tier() -> str:
    """The tier the flat-ring DATA PLANE should run ("cpp" or "python").

    Same resolution as :func:`default_tier`, with one extra rule: in
    ``auto`` mode a topology that *forces* hierarchical dispatch keeps the
    Python tier (the native mesh has no shm/leader-ring dispatch yet), with
    a loud one-line log of the downgrade.  An explicit ``TORCHFT_TIER=cpp``
    is honored as stated — the Python peers' forced-hierarchical rendezvous
    will then fail loudly rather than desynchronize silently.
    """
    env = _tier_env()
    if env == "python":
        return "python"
    hier = knobs.get_str("TORCHFT_HIERARCHICAL", "auto").strip().lower()
    hier_forced = hier in ("1", "true", "on")
    if env == "cpp":
        if hier_forced:
            logger.warning(
                "TORCHFT_TIER=cpp with TORCHFT_HIERARCHICAL=1: the native "
                "mesh runs the flat ring only — hierarchical peers will "
                "fail rendezvous loudly"
            )
        return "cpp"
    tier = default_tier()
    if tier == "cpp" and hier_forced:
        logger.warning(
            "native tier downgraded to python data plane: "
            "TORCHFT_HIERARCHICAL=1 requests topology dispatch the cpp "
            "mesh does not implement (set TORCHFT_TIER=cpp to override)"
        )
        return "python"
    return tier


def make_communicator(timeout_s: float = 60.0, tier: Optional[str] = None):
    """Data-plane communicator for the active tier.

    This is the factory the train loop, the DiLoCo outer sync, and the
    heal drain all ride: ``Manager`` calls it when constructed without an
    explicit ``comm``, so ``TORCHFT_TIER=auto`` puts every data-plane byte
    on the native mesh whenever the library loads (and the topology does
    not force the Python tier — see :func:`data_plane_tier`).
    """
    tier = tier or data_plane_tier()
    if tier == "cpp":
        from torchft_tpu.native import CppCommunicator

        return CppCommunicator(timeout_s=timeout_s)
    from torchft_tpu.communicator import TCPCommunicator

    return TCPCommunicator(timeout_s=timeout_s)


def make_lighthouse(
    bind: str = "0.0.0.0:0",
    min_replicas: int = 1,
    join_timeout_ms: int = 100,
    quorum_tick_ms: int = 100,
    heartbeat_timeout_ms: int = 5_000,
    tier: Optional[str] = None,
):
    """Lighthouse server for the active tier (same ctor surface both ways).

    The Python lighthouse additionally serves the web dashboard; deployments
    that want both the C++ control plane and the dashboard can front the C++
    server with ``lighthouse.py``'s HTTP handler.
    """
    tier = tier or default_tier()
    kwargs = dict(
        bind=bind,
        min_replicas=min_replicas,
        join_timeout_ms=join_timeout_ms,
        quorum_tick_ms=quorum_tick_ms,
        heartbeat_timeout_ms=heartbeat_timeout_ms,
    )
    if tier == "cpp":
        from torchft_tpu.native import CppLighthouseServer

        return CppLighthouseServer(**kwargs)
    from torchft_tpu.lighthouse import LighthouseServer

    return LighthouseServer(**kwargs)


def manager_server_cls(tier: Optional[str] = None):
    """The ``server_cls`` to hand :class:`torchft_tpu.manager.Manager`."""
    tier = tier or default_tier()
    if tier == "cpp":
        from torchft_tpu.native import CppManagerServer

        return CppManagerServer
    from torchft_tpu.manager_server import ManagerServer

    return ManagerServer
