"""Runtime tier selection: C++ native plane vs pure-Python fallback.

The C++ runtime (``native/libtpuft.so``) is the production tier: poll-driven
duplex TCP collectives, native lighthouse/manager servers speaking the same
framed wire protocol as their Python twins (``tests/test_native.py`` proves
cross-tier interop).  The Python tier exists so the framework runs anywhere
the shared library doesn't build.  This mirrors the reference, whose benched
production path is NCCL while Gloo is the portable fallback
(``torchft/process_group.py:643-891``).

``TORCHFT_TIER`` selects explicitly: ``cpp`` | ``python`` | ``auto``
(default — cpp whenever the library loads).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("torchft_tpu.tier")

TIER_ENV = "TORCHFT_TIER"


def default_tier() -> str:
    """Resolve the active tier name ("cpp" or "python")."""
    env = os.environ.get(TIER_ENV, "auto").lower()
    if env in ("cpp", "python"):
        return env
    if env not in ("", "auto"):
        logger.warning("unknown %s=%r; using auto", TIER_ENV, env)
    try:
        from torchft_tpu import native

        return "cpp" if native.available() else "python"
    except Exception:  # noqa: BLE001 — a broken build falls back, not crashes
        return "python"


def make_communicator(timeout_s: float = 60.0, tier: Optional[str] = None):
    """Data-plane communicator for the active tier."""
    tier = tier or default_tier()
    if tier == "cpp":
        from torchft_tpu.native import CppCommunicator

        return CppCommunicator(timeout_s=timeout_s)
    from torchft_tpu.communicator import TCPCommunicator

    return TCPCommunicator(timeout_s=timeout_s)


def make_lighthouse(
    bind: str = "0.0.0.0:0",
    min_replicas: int = 1,
    join_timeout_ms: int = 100,
    quorum_tick_ms: int = 100,
    heartbeat_timeout_ms: int = 5_000,
    tier: Optional[str] = None,
):
    """Lighthouse server for the active tier (same ctor surface both ways).

    The Python lighthouse additionally serves the web dashboard; deployments
    that want both the C++ control plane and the dashboard can front the C++
    server with ``lighthouse.py``'s HTTP handler.
    """
    tier = tier or default_tier()
    kwargs = dict(
        bind=bind,
        min_replicas=min_replicas,
        join_timeout_ms=join_timeout_ms,
        quorum_tick_ms=quorum_tick_ms,
        heartbeat_timeout_ms=heartbeat_timeout_ms,
    )
    if tier == "cpp":
        from torchft_tpu.native import CppLighthouseServer

        return CppLighthouseServer(**kwargs)
    from torchft_tpu.lighthouse import LighthouseServer

    return LighthouseServer(**kwargs)


def manager_server_cls(tier: Optional[str] = None):
    """The ``server_cls`` to hand :class:`torchft_tpu.manager.Manager`."""
    tier = tier or default_tier()
    if tier == "cpp":
        from torchft_tpu.native import CppManagerServer

        return CppManagerServer
    from torchft_tpu.manager_server import ManagerServer

    return ManagerServer
