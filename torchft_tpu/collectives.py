"""Quantized collectives: int8 allreduce over the replica dimension.

The reference pipeline (``torchft/collectives.py:297-415``): quantize →
``alltoall`` chunks → local dequant-reduce-requant → allgather → dequant.
Per-rank bytes drop from ~2·n·4 (f32 ring) to ~2·n·1 + scales — the win that
makes DiLoCo pseudogradient syncs viable over DCN bandwidth
(``local_sgd.py`` ``should_quantize``).

Like the reference (which chains the pipeline on a side CUDA stream,
``collectives.py:369-415``), the pipeline here runs off-thread and returns a
pending Work, so DiLoCo's τ-delay actually overlaps the sync with training.

This is the host/DCN tier in numpy; the device-side quantize kernel (cutting
HBM→host transfer to a quarter) is ``torchft_tpu.ops.pallas_quant``.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import List, Optional, Tuple, Union

import numpy as np

from torchft_tpu.communicator import Communicator
from torchft_tpu.quantization import (
    DEFAULT_ROW_SIZE,
    dequantize_int8_rowwise,
    quantize_int8_rowwise,
    reduce_quantized,
)
from torchft_tpu.work import DummyWork, Work

Buffers = Union[np.ndarray, List[np.ndarray]]


def _pack(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Payload + scales in one uint8 buffer so one collective carries both."""
    return np.concatenate([q.reshape(-1).view(np.uint8), scales.view(np.uint8)])


def _unpack(buf: np.ndarray, rows: int, row_size: int) -> Tuple[np.ndarray, np.ndarray]:
    payload = rows * row_size
    return (
        buf[:payload].view(np.int8).reshape(rows, row_size),
        buf[payload:].view(np.float32),
    )


def _quantized_reduce_scatter_sync(
    comm: Communicator, flat: np.ndarray, row_size: int, tag: int
) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Core shared by both quantized collectives: quantize, pad rows to an
    equal per-rank share, alltoall, dequant-sum-requant our shard.

    Returns (reduced q shard, its scales, total unpadded rows, rows/rank).
    """
    q, scales = quantize_int8_rowwise(flat, row_size)
    return _prequantized_reduce_scatter_sync(comm, q, scales, tag)


def _prequantized_reduce_scatter_sync(
    comm: Communicator, q: np.ndarray, scales: np.ndarray, tag: int
) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Same core for input already quantized (e.g. on-device by the Pallas
    kernel, so only int8+scales ever crossed HBM→host)."""
    ws = comm.size()
    row_size = q.shape[1]
    rows = q.shape[0]
    rows_per_rank = -(-rows // ws)
    padded_rows = rows_per_rank * ws
    if padded_rows != rows:
        q = np.concatenate([q, np.zeros((padded_rows - rows, row_size), np.int8)])
        scales = np.concatenate(
            [scales, np.zeros(padded_rows - rows, np.float32)]
        )

    chunks = [
        _pack(
            q[p * rows_per_rank : (p + 1) * rows_per_rank],
            scales[p * rows_per_rank : (p + 1) * rows_per_rank],
        )
        for p in range(ws)
    ]
    gathered = comm.alltoall(chunks, tag=tag).wait()

    qs, scs = zip(*(_unpack(g, rows_per_rank, row_size) for g in gathered))
    q_red, s_red = reduce_quantized(np.stack(qs), np.stack(scs))
    return q_red, s_red, rows, rows_per_rank


def _allreduce_quantized_sync(
    comm: Communicator, arrays: List[np.ndarray], row_size: int
) -> List[np.ndarray]:
    layout = [(a.shape, a.dtype, a.size) for a in arrays]
    flat = np.concatenate(
        [np.asarray(a, dtype=np.float32).reshape(-1) for a in arrays]
    )

    pipeline_err: Optional[BaseException] = None
    try:
        q_red, s_red, rows, rows_per_rank = _quantized_reduce_scatter_sync(
            comm, flat, row_size, tag=101
        )
    except BaseException as e:  # noqa: BLE001
        # Injected/future errors must not skip the remaining collective —
        # peers would wedge in their allgather (FakeCommunicatorWrapper
        # contract). Participate with a zero shard, then re-raise.
        pipeline_err = e
        q_red, s_red, rows, rows_per_rank = _zero_shard(
            max(1, -(-flat.size // row_size)), row_size, comm.size()
        )

    summed = _allgather_reduced_shards(
        comm, q_red, s_red, rows, rows_per_rank, row_size, flat.size, tag=102,
        pipeline_err=pipeline_err,
    )

    out: List[np.ndarray] = []
    off = 0
    for shape, dtype, size in layout:
        out.append(
            summed[off : off + size].reshape(shape).astype(dtype, copy=False)
        )
        off += size
    return out


def _allgather_reduced_shards(
    comm: Communicator,
    q_red: np.ndarray,
    s_red: np.ndarray,
    rows: int,
    rows_per_rank: int,
    row_size: int,
    n: int,
    tag: int,
    pipeline_err: Optional[BaseException],
) -> np.ndarray:
    """Shared tail of both quantized allreduces: allgather the reduced
    shards and dequantize.  Always participates in the allgather — even
    after an upstream failure (``pipeline_err``), a zero shard is
    contributed so healthy peers are never wedged — then re-raises."""
    all_shards = comm.allgather(_pack(q_red, s_red), tag=tag).wait()
    if pipeline_err is not None:
        raise pipeline_err
    qs_full, ss_full = zip(
        *(_unpack(s, rows_per_rank, row_size) for s in all_shards)
    )
    q_full = np.concatenate(qs_full)[:rows]
    s_full = np.concatenate(ss_full)[:rows]
    return dequantize_int8_rowwise(q_full, s_full, n, np.float32)


def _zero_shard(
    rows: int, row_size: int, ws: int
) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Zero contribution with the shard geometry peers expect (``rows`` must
    equal the unpadded row count every rank derived from its own input)."""
    rows_per_rank = -(-rows // ws)
    return (
        np.zeros((rows_per_rank, row_size), np.int8),
        np.zeros(rows_per_rank, np.float32),
        rows,
        rows_per_rank,
    )


def allreduce_prequantized(
    comm: Communicator,
    q: np.ndarray,
    scales: np.ndarray,
    n: int,
) -> np.ndarray:
    """SUM-allreduce of an already-quantized stream (int8 rows + f32 rowwise
    scales, e.g. produced on device by ``ops.pallas_quant``); returns the
    dequantized float32 sum of length ``n``.  Synchronous — callers layer
    Work/threading on top (``Manager.allreduce_prequantized``)."""
    scales = np.asarray(scales).reshape(-1)
    if comm.size() == 1 or getattr(comm, "is_passthrough", False):
        return dequantize_int8_rowwise(q, scales, n, np.float32)
    row_size = q.shape[1]
    err: Optional[BaseException] = None
    try:
        q_red, s_red, rows, rows_per_rank = _prequantized_reduce_scatter_sync(
            comm, q, scales, tag=105
        )
    except BaseException as e:  # noqa: BLE001 — still join the allgather
        err = e
        q_red, s_red, rows, rows_per_rank = _zero_shard(
            q.shape[0], row_size, comm.size()
        )
    return _allgather_reduced_shards(
        comm, q_red, s_red, rows, rows_per_rank, row_size, n, tag=106,
        pipeline_err=err,
    )


def allreduce_quantized(
    comm: Communicator,
    buffers: Buffers,
    row_size: int = DEFAULT_ROW_SIZE,
) -> Work:
    """SUM-allreduce through int8: the Work's value mirrors ``buffers`` with
    summed float values (the Manager divides by participants afterwards,
    exactly like the unquantized path).

    Accuracy: rowwise int8 carries ~2-3 decimal digits; intended for DiLoCo
    pseudogradients where the outer optimizer tolerates it (the reference
    ships fp8 with the same caveat).
    """
    single = isinstance(buffers, np.ndarray)
    arrays: List[np.ndarray] = [buffers] if single else list(buffers)

    if comm.size() == 1 or getattr(comm, "is_passthrough", False):
        # single member (or a passthrough test double): the sum is our own
        # contribution; round-trip through int8 so quantization error stays
        # observable in tests
        out = []
        for a in arrays:
            flat = np.asarray(a, dtype=np.float32).reshape(-1)
            q, s = quantize_int8_rowwise(flat, row_size)
            out.append(
                dequantize_int8_rowwise(q, s, flat.size, np.float32)
                .reshape(a.shape)
                .astype(a.dtype, copy=False)
            )
        return DummyWork(out[0] if single else out)

    fut: Future = Future()

    def _run() -> None:
        try:
            out = _allreduce_quantized_sync(comm, arrays, row_size)
            fut.set_result(out[0] if single else out)
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(
        target=_run, name="tpuft_quantized_allreduce", daemon=True
    ).start()
    return Work(fut)


def reduce_scatter_quantized(
    comm: Communicator,
    buffers: Buffers,
    row_size: int = DEFAULT_ROW_SIZE,
) -> Work:
    """Quantized reduce-scatter (``collectives.py:159-294``): each rank gets
    the dequantized sum of its row-shard only (flat float32)."""
    single = isinstance(buffers, np.ndarray)
    arrays: List[np.ndarray] = [buffers] if single else list(buffers)
    flat = np.concatenate(
        [np.asarray(a, dtype=np.float32).reshape(-1) for a in arrays]
    )
    if comm.size() == 1 or getattr(comm, "is_passthrough", False):
        q, s = quantize_int8_rowwise(flat, row_size)
        return DummyWork(dequantize_int8_rowwise(q, s, flat.size, np.float32))

    fut: Future = Future()

    def _run() -> None:
        try:
            q_red, s_red, _rows, rows_per_rank = _quantized_reduce_scatter_sync(
                comm, flat, row_size, tag=103
            )
            total = (q_red.astype(np.float32) * s_red[:, None]).reshape(-1)
            fut.set_result(total)
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(
        target=_run, name="tpuft_quantized_reduce_scatter", daemon=True
    ).start()
    return Work(fut)