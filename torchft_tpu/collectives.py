"""Quantized collectives: int8/fp8 allreduce over the replica dimension.

The reference pipeline (``torchft/collectives.py:297-415``): quantize →
``alltoall`` chunks → local dequant-reduce-requant → allgather → dequant.
Per-rank bytes drop from ~2·n·4 (f32 ring) to ~2·n·1 + scales — the win that
makes DiLoCo pseudogradient syncs viable over DCN bandwidth
(``local_sgd.py`` ``should_quantize``).

Two overlap mechanisms (the analog of the reference chaining its pipeline on
a side CUDA stream, ``collectives.py:369-415``):

- the whole pipeline runs off-thread and returns a pending Work, so DiLoCo's
  τ-delay actually overlaps the sync with training;
- within the pipeline, the buffer is split into fixed-size row windows
  walked in a deterministic schedule — ``a2a(0), a2a(1), ag(0), a2a(2),
  ag(1), …`` — so while the op thread drives window ``w+1``'s alltoall and
  window ``w-1``'s allgather over the wire, the caller thread
  dequant-sum-requants window ``w``.  The schedule is identical on every
  rank (the op queue executes in submission order and frames are
  tag-checked), so windows can never cross.

The reduce step runs on device when a TPU is present (fused Pallas
dequant-sum-requant, ``ops/pallas_quant.py reduce_quantized_device`` — the
twin of the reference's ``fused_reduce_fp8``, ``quantization.py:638``): the
host round-trips int8 shards only, never float32.  Elsewhere it runs as
vectorized numpy.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple, Union

import numpy as np

from torchft_tpu import wire
from torchft_tpu.communicator import Communicator, CommunicatorError
from torchft_tpu.obs.spans import span as obs_span
from torchft_tpu.quantization import (
    DEFAULT_ROW_SIZE,
    FP8,
    INT8,
    dequantize_rowwise,
    quantize_rowwise,
    reduce_quantized,
    wire_dtype,
)
from torchft_tpu.wire import (
    DEVICE_QUANT_PIPELINE_TAG_BASE,
    OUTER_SHARD_TAG_BASE,
    QUANT_PIPELINE_TAG_BASE,
    QUANT_RING_TAG,
)
from torchft_tpu.work import DummyWork, Work

logger = logging.getLogger(__name__)

Buffers = Union[np.ndarray, List[np.ndarray]]

# Rows per pipeline window are sized so one window's payload is about this
# many bytes; smaller windows overlap wire and reduce at finer grain but pay
# more per-frame overhead.
WINDOW_MB_ENV = "TORCHFT_QUANT_WINDOW_MB"
DEFAULT_WINDOW_MB = 4.0

# Device-side fused reduce: "1" forces on, "0" forces off, unset/auto uses
# the TPU when present and the window is big enough to amortize transfers.
DEVICE_REDUCE_ENV = "TORCHFT_QUANT_DEVICE_REDUCE"
_DEVICE_REDUCE_MIN_BYTES = 256 << 10


def _window_rows(row_size: int) -> int:
    try:
        mb = float(os.environ.get(WINDOW_MB_ENV, "") or DEFAULT_WINDOW_MB)
    except ValueError:
        mb = DEFAULT_WINDOW_MB
    return max(1, int(mb * (1 << 20)) // row_size)


def _kind_of(q: np.ndarray) -> str:
    return INT8 if q.dtype == np.int8 else FP8


def _use_device_reduce(shard_bytes: int) -> bool:
    mode = os.environ.get(DEVICE_REDUCE_ENV, "")
    if mode == "0":
        return False
    if mode == "1":
        return True
    try:
        import jax

        return (
            jax.default_backend() == "tpu"
            and shard_bytes >= _DEVICE_REDUCE_MIN_BYTES
        )
    except Exception:  # pragma: no cover - jax is a hard dependency
        return False


# two-byte wire-format header leading every packed shard: both kinds are
# 1 byte/element with identical geometry, so a TORCHFT_QUANT_KIND mismatch
# across replicas would otherwise reinterpret peers' bytes silently —
# garbage gradients instead of an error.  header[0] is a nonzero magic so a
# headerless legacy payload (int8-quantized gradients are mostly near zero,
# making a leading 0 byte common) fails LOUDLY instead of parsing 8 bytes
# shifted; header[1] is the kind tag.
_WIRE_MAGIC = 0xA7
_KIND_TAG = {INT8: 1, FP8: 2}
_TAG_KIND = {v: k for k, v in _KIND_TAG.items()}


_HDR = 8  # 8-byte header (magic + kind + reserved) keeps the f32 scales view aligned


def _pack(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Header + payload + scales in one uint8 buffer so one collective
    carries all three."""
    header = np.zeros(_HDR, dtype=np.uint8)
    header[0] = _WIRE_MAGIC
    header[1] = _KIND_TAG[_kind_of(q)]
    return np.concatenate(
        [
            header,
            np.ascontiguousarray(q).reshape(-1).view(np.uint8),
            scales.view(np.uint8),
        ]
    )


def _unpack(
    buf: np.ndarray, rows: int, row_size: int, kind: str
) -> Tuple[np.ndarray, np.ndarray]:
    if int(buf[0]) != _WIRE_MAGIC:
        raise CommunicatorError(
            "quantized-wire header magic mismatch: peer payload does not "
            "start with the framed header (mixed-version replica group? "
            "all groups must run the same torchft_tpu wire build)"
        )
    got = _TAG_KIND.get(int(buf[1]))
    if got != kind:
        raise CommunicatorError(
            f"quantized-wire kind mismatch: peer sent {got!r}, this replica "
            f"is configured for {kind!r} (check TORCHFT_QUANT_KIND agrees "
            "across all replica groups)"
        )
    payload = rows * row_size
    return (
        buf[_HDR : _HDR + payload].view(wire_dtype(kind)).reshape(rows, row_size),
        buf[_HDR + payload :].view(np.float32),
    )


def _reduce_shards(
    qs: np.ndarray, scs: np.ndarray, kind: str
) -> Tuple[np.ndarray, np.ndarray]:
    """Dequant-sum-requant ``w`` shards; on a TPU both wire kinds run as
    the fused Pallas kernel so only 1-byte payloads cross HBM (fp8 falls
    back to XLA-compiled jnp on chips whose Mosaic can't lower the dtype —
    see ``pallas_quant._pallas_kind_ok``)."""
    if _use_device_reduce(qs[0].nbytes):
        import jax

        from torchft_tpu.ops.pallas_quant import BLOCK_ROWS, reduce_quantized_device

        w, rows, row_size = qs.shape
        pad = (-rows) % BLOCK_ROWS
        if pad:
            qs = np.concatenate(
                [qs, np.zeros((w, pad, row_size), qs.dtype)], axis=1
            )
            scs = np.concatenate([scs, np.zeros((w, pad), np.float32)], axis=1)
        q_dev, s_dev = reduce_quantized_device(
            jax.numpy.asarray(qs), jax.numpy.asarray(scs)[:, :, None], kind=kind
        )
        q_host = np.asarray(q_dev)[:rows]
        s_host = np.asarray(s_dev).reshape(-1)[:rows]
        return q_host, s_host
    return reduce_quantized(qs, scs, kind)


# ---------------------------------------------------------------------------
# single-window core (shared with reduce_scatter and kept as the fallback)
# ---------------------------------------------------------------------------


def _quantized_reduce_scatter_sync(
    comm: Communicator, flat: np.ndarray, row_size: int, tag: int, kind: str = INT8
) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Core shared by both quantized collectives: quantize, pad rows to an
    equal per-rank share, alltoall, dequant-sum-requant our shard.

    Returns (reduced q shard, its scales, total unpadded rows, rows/rank).
    """
    q, scales = quantize_rowwise(flat, row_size, kind)
    return _prequantized_reduce_scatter_sync(comm, q, scales, tag)


def _prequantized_reduce_scatter_sync(
    comm: Communicator, q: np.ndarray, scales: np.ndarray, tag: int
) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Same core for input already quantized (e.g. on-device by the Pallas
    kernel, so only 1-byte payload + scales ever crossed HBM→host)."""
    kind = _kind_of(q)
    ws = comm.size()
    row_size = q.shape[1]
    rows = q.shape[0]
    rows_per_rank = -(-rows // ws)
    padded_rows = rows_per_rank * ws
    if padded_rows != rows:
        q = np.concatenate(
            [q, np.zeros((padded_rows - rows, row_size), q.dtype)]
        )
        scales = np.concatenate(
            [scales, np.zeros(padded_rows - rows, np.float32)]
        )

    chunks = [
        _pack(
            q[p * rows_per_rank : (p + 1) * rows_per_rank],
            scales[p * rows_per_rank : (p + 1) * rows_per_rank],
        )
        for p in range(ws)
    ]
    gathered = comm.alltoall(chunks, tag=tag).wait()

    qs, scs = zip(*(_unpack(g, rows_per_rank, row_size, kind) for g in gathered))
    q_red, s_red = _reduce_shards(np.stack(qs), np.stack(scs), kind)
    return q_red, s_red, rows, rows_per_rank


def _allgather_reduced_shards(
    comm: Communicator,
    q_red: np.ndarray,
    s_red: np.ndarray,
    rows: int,
    rows_per_rank: int,
    row_size: int,
    n: int,
    tag: int,
    pipeline_err: Optional[BaseException],
    kind: str = INT8,
) -> np.ndarray:
    """Shared tail of the single-window allreduce: allgather the reduced
    shards and dequantize.  Always participates in the allgather — even
    after an upstream failure (``pipeline_err``), a zero shard is
    contributed so healthy peers are never wedged — then re-raises."""
    all_shards = comm.allgather(_pack(q_red, s_red), tag=tag).wait()
    if pipeline_err is not None:
        raise pipeline_err
    qs_full, ss_full = zip(
        *(_unpack(s, rows_per_rank, row_size, kind) for s in all_shards)
    )
    q_full = np.concatenate(qs_full)[:rows]
    s_full = np.concatenate(ss_full)[:rows]
    return dequantize_rowwise(q_full, s_full, n, np.float32)


def _zero_shard(
    rows: int, row_size: int, ws: int, kind: str = INT8
) -> Tuple[np.ndarray, np.ndarray, int, int]:
    """Zero contribution with the shard geometry peers expect (``rows`` must
    equal the unpadded row count every rank derived from its own input)."""
    rows_per_rank = -(-rows // ws)
    return (
        np.zeros((rows_per_rank, row_size), wire_dtype(kind)),
        np.zeros(rows_per_rank, np.float32),
        rows,
        rows_per_rank,
    )


# ---------------------------------------------------------------------------
# windowed pipelined allreduce
# ---------------------------------------------------------------------------


def _allreduce_pipelined_sync(
    comm: Communicator,
    q: np.ndarray,
    scales: np.ndarray,
    n: int,
    tag_base: int,
) -> np.ndarray:
    """SUM-allreduce of quantized rows with window-level overlap.

    Deterministic per-rank schedule (identical everywhere, so the single op
    thread pairs frames correctly):

        submit a2a(0)
        for w: wait a2a(w); submit a2a(w+1); reduce(w); submit ag(w)
        for w: wait ag(w); dequantize into the output

    While the caller reduces window ``w``, the op thread drives ``a2a(w+1)``
    then ``ag(w-1)`` over the sockets.  Any stage failure degrades that
    window (and the rest of the schedule, if the communicator died) to zero
    shards so peers never wedge, then the first error re-raises at the end —
    same containment contract as the single-window path.
    """
    kind = _kind_of(q)
    ws = comm.size()
    rows, row_size = q.shape
    win = _window_rows(row_size)
    windows: List[Tuple[int, int]] = [
        (start, min(start + win, rows)) for start in range(0, rows, win)
    ]
    W = len(windows)
    # window tags are allocated 2 per window from tag_base; past the span
    # declared in wire.USER_TAG_ALLOCATIONS they spill into neighboring
    # allocations (pairing stays unambiguous today only because ops are
    # serialized per epoch and a2a/ag tags differ in parity — see the
    # registry comment).  Warn loudly so giant payloads get a bigger
    # TORCHFT_QUANT_WINDOW_MB instead of relying on that accident.
    span = next(
        (
            s
            for b, s in wire.USER_TAG_ALLOCATIONS.values()
            if b == tag_base
        ),
        None,
    )
    if span is not None and 2 * W > span:
        logger.warning(
            "quantized pipeline needs %d windows (%d tags) but tag base %d "
            "has a span of only %d — raise TORCHFT_QUANT_WINDOW_MB to "
            "shrink the window count",
            W,
            2 * W,
            tag_base,
            span,
        )
    err: Optional[BaseException] = None
    out = np.empty(rows * row_size, dtype=np.float32)

    # one padded staging scratch (q rows + their scales), sized for the
    # largest window and reused across windows — the previous per-window
    # np.concatenate allocated fresh padding buffers every window.  Reuse is
    # safe while earlier windows' collectives are still in flight because
    # ``_pack`` copies the rows into the wire buffer before submission.
    max_padded = max(
        (-(-(stop - start) // ws) * ws for start, stop in windows), default=0
    )
    pad_q: Optional[np.ndarray] = None
    pad_s: Optional[np.ndarray] = None

    def _submit_a2a(w: int) -> Work:
        nonlocal pad_q, pad_s
        start, stop = windows[w]
        wq, wsc = q[start:stop], scales[start:stop]
        wrows = stop - start
        rows_per_rank = -(-wrows // ws)
        padded = rows_per_rank * ws
        if padded != wrows:
            if pad_q is None:
                pad_q = np.empty((max_padded, row_size), q.dtype)
                pad_s = np.empty(max_padded, np.float32)
            pad_q[:wrows] = wq
            pad_q[wrows:padded] = 0
            pad_s[:wrows] = wsc
            pad_s[wrows:padded] = 0.0
            wq, wsc = pad_q[:padded], pad_s[:padded]
        chunks = [
            _pack(
                wq[p * rows_per_rank : (p + 1) * rows_per_rank],
                wsc[p * rows_per_rank : (p + 1) * rows_per_rank],
            )
            for p in range(ws)
        ]
        return comm.alltoall(chunks, tag=tag_base + 2 * w)

    def _rows_per_rank(w: int) -> int:
        start, stop = windows[w]
        return -(-(stop - start) // ws)

    a2a_work = _submit_a2a(0)
    ag_works: List[Work] = []
    for w in range(W):
        rows_per_rank = _rows_per_rank(w)
        try:
            gathered = a2a_work.wait()
        except BaseException as e:  # noqa: BLE001 — degrade, keep schedule
            err = err or e
            gathered = None
        if w + 1 < W:
            a2a_work = _submit_a2a(w + 1)
        if gathered is not None:
            try:
                qs, scs = zip(
                    *(
                        _unpack(g, rows_per_rank, row_size, kind)
                        for g in gathered
                    )
                )
                q_red, s_red = _reduce_shards(np.stack(qs), np.stack(scs), kind)
            except BaseException as e:  # noqa: BLE001
                err = err or e
                gathered = None
        if gathered is None:
            q_red = np.zeros((rows_per_rank, row_size), wire_dtype(kind))
            s_red = np.zeros(rows_per_rank, np.float32)
        ag_works.append(
            comm.allgather(_pack(q_red, s_red), tag=tag_base + 2 * w + 1)
        )

    for w, work in enumerate(ag_works):
        start, stop = windows[w]
        rows_per_rank = _rows_per_rank(w)
        try:
            all_shards = work.wait()
            qs_full, ss_full = zip(
                *(
                    _unpack(s, rows_per_rank, row_size, kind)
                    for s in all_shards
                )
            )
            q_full = np.concatenate(qs_full)[: stop - start]
            s_full = np.concatenate(ss_full)[: stop - start]
            out[start * row_size : stop * row_size] = dequantize_rowwise(
                q_full, s_full, (stop - start) * row_size, np.float32
            )
        except BaseException as e:  # noqa: BLE001
            err = err or e
            out[start * row_size : stop * row_size] = 0.0

    if err is not None:
        raise err
    return out[:n]


# ---------------------------------------------------------------------------
# sharded outer sync: chunk-pipelined reduce_scatter → update → allgather
# ---------------------------------------------------------------------------

# Bytes of the FULL flat buffer covered by one pipeline chunk (each chunk's
# per-shard slice is this divided by the shard count).  Smaller chunks start
# the outer update sooner and overlap at finer grain; larger chunks amortize
# the per-exchange RTT gates — on wan_1g-class links (10 ms RTT) chunks
# below ~8 MB cost more in frame gates than the overlap buys back.
OUTER_CHUNK_MB_ENV = "TORCHFT_OUTER_CHUNK_MB"
DEFAULT_OUTER_CHUNK_MB = 16.0
# Pipeline depth cap: tags are allocated 2 per chunk from the sharded-sync
# tag base, and a deeper pipeline stops paying for itself anyway.
_MAX_OUTER_CHUNKS = 64
_OUTER_TAG_BASE = OUTER_SHARD_TAG_BASE


def _outer_chunk_ranges(
    per: int, unit: int, gsize: int, max_chunks: int = _MAX_OUTER_CHUNKS
) -> List[Tuple[int, int]]:
    """Pipeline chunk ranges WITHIN one shard's [0, per) element extent,
    unit-aligned so quantization rows never split; identical on every
    replica (pure function of the layout).  ``max_chunks`` bounds the
    pipeline depth to the caller's tag window (2 tags per chunk)."""
    try:
        mb = float(
            os.environ.get(OUTER_CHUNK_MB_ENV, "") or DEFAULT_OUTER_CHUNK_MB
        )
    except ValueError:
        mb = DEFAULT_OUTER_CHUNK_MB
    # per-shard slice of one chunk, in elements (f32), unit-aligned
    want = int(mb * (1 << 20)) // 4 // max(1, gsize)
    want = max(unit, want // unit * unit)
    floor = -(-per // (max_chunks * unit)) * unit  # cap chunk count
    step = max(want, floor, unit)
    return [(c, min(c + step, per)) for c in range(0, per, step)]


def outer_shard_layout(
    n: int, gsize: int, should_quantize: bool, row_size: int = DEFAULT_ROW_SIZE
) -> Tuple[int, int, int]:
    """Per-replica shard layout of a flat ``n``-element f32 buffer over
    ``gsize`` shard owners: returns ``(padded, per, unit)`` elements where
    every shard is exactly ``per`` elements, ``padded = per * gsize``, and
    boundaries are ``unit``-aligned (16 f32 = 64 B raw; one quantization
    row when the wire is quantized, so each byte is quantized exactly once
    and no row straddles shards).  Thin wrapper over the wire-level
    :func:`communicator.outer_shard_parts` (mirrored in ``native/comm.h``)
    so shard ownership stays tier-uniform."""
    from torchft_tpu.communicator import outer_shard_parts

    unit = row_size if should_quantize else 16
    parts = outer_shard_parts(n * 4, gsize, unit * 4)
    per = (parts[0][1] - parts[0][0]) // 4
    return per * gsize, per, unit


def outer_sharded_sync(
    comm: Communicator,
    flat: np.ndarray,
    update_cb: Callable[[int, int, np.ndarray], np.ndarray],
    num_participants: int,
    should_quantize: bool = False,
    kind: str = INT8,
    row_size: int = DEFAULT_ROW_SIZE,
    timings: Optional[dict] = None,
    tap: Optional[Callable[[np.ndarray], None]] = None,
    weight: Optional[float] = None,
    tag_base: int = _OUTER_TAG_BASE,
    tag_span: int = wire.OUTER_SHARD_TAG_SPAN,
) -> np.ndarray:
    """ZeRO-1-style sharded outer sync: chunk-pipelined
    ``reduce_scatter → sharded outer update → allgather(update)``.

    ``flat`` is this replica's f32 pseudo-gradient (length n).  The buffer
    is split into deterministic per-owner shards (:func:`outer_shard_layout`)
    and each shard into pipeline chunks; per chunk the schedule is

        alltoall(pseudo-grad slices)         # the reduce-scatter
        avg = Σ contributions / participants
        delta = update_cb(lo, hi, avg)       # the sharded outer step
        allgather(delta)                     # owners' updates, fanned out

    with chunk ``c+1``'s alltoall submitted before chunk ``c``'s update
    runs, so the outer optimizer computes while later chunks are still
    reducing on the op thread — the ``reduce_scatter_then`` hook.  Every
    replica applies the identical wire-format delta (its own included), so
    params stay bit-identical across replicas.

    Hierarchical topologies compose: the host reduces once over shared
    memory, HOST LEADERS run the chunk pipeline (shards owned per host via
    ``leader_comm``), and the allgathered delta shm-broadcasts back out —
    non-leaders move zero socket bytes and own no shard (``update_cb`` is
    never invoked on them).

    When quantized, the pseudo-gradient is rowwise-quantized ONCE for the
    whole buffer (each byte quantized exactly once — shard and chunk
    boundaries are row-aligned) and the delta rides the wire as one more
    rowwise pass; error containment matches the pipelined allreduce: a
    failed chunk degrades to a zero delta so peers never wedge, then the
    first error re-raises after the schedule completes.

    Returns the f32 delta of length ``len(flat)`` (apply as
    ``params = backup + delta``).  Fills ``timings`` (if given) with
    ``scatter_s`` / ``update_s`` / ``gather_s`` / ``wall_s`` /
    ``overlap_ratio``.

    ``tap``, if given, observes the assembled delta (identical bytes on
    every replica by construction — the allgather fans out ONE wire-format
    update) right before it is returned: the hot-spare delta feed rides
    this hook so parked observers can keep a shadow bit-exact without
    participating in the collective.  A tap failure never fails the sync.

    ``tag_base`` / ``tag_span`` frame the chunk collectives: the default is
    the legacy OUTER_SHARD window (byte-identical to the pre-stream path);
    the streamed fragment scheduler passes a rotating per-fragment
    STREAM_OUTER window (``wire.stream_frag_tag_window``) so consecutive
    streamed syncs can never alias tags.  The pipeline depth is capped at
    ``tag_span // 2`` chunks (2 tags per chunk).

    ``weight``, if given, turns the sync into a capacity-WEIGHTED sum
    (degraded-mode fleets): this replica's contribution is pre-scaled by
    its normalized capacity share before quantization/transport and the
    ``num_participants`` division drops out (weights sum to 1 across the
    fleet by construction — every rank must pass a weight, or none).  The
    delta stays bit-identical across replicas exactly as before: the
    weighting changes the bytes each rank CONTRIBUTES, never how the
    summed wire-format delta is applied.
    """
    t_wall = time.perf_counter()
    if weight is not None:
        flat = np.asarray(flat, dtype=np.float32) * np.float32(weight)
        num_participants = 1  # weighted contributions need no division
    n = flat.size
    tm = {"scatter_s": 0.0, "update_s": 0.0, "gather_s": 0.0}
    topo = _hier_topology(comm)
    err: Optional[BaseException] = None
    delta_full: Optional[np.ndarray] = None

    if topo is None:
        gsize = max(1, comm.size())
        group: Communicator = comm
        contrib: Optional[np.ndarray] = np.asarray(flat, dtype=np.float32)
        owns = True
    else:
        # intra-host reduce once; leaders shard the outer step per host
        gsize = len(topo["leader_ring"])
        owns = bool(topo["is_leader"])
        contrib = None
        try:
            contrib = comm.intra_reduce(  # type: ignore[attr-defined]
                np.asarray(flat, dtype=np.float32)
            ).wait()
        except BaseException as e:  # noqa: BLE001 — degrade, keep schedule
            err = e
        group = comm.leader_comm() if owns else comm  # type: ignore[attr-defined]

    padded, per, unit = outer_shard_layout(n, gsize, should_quantize, row_size)

    if owns:
        try:
            if contrib is None:
                raise err or CommunicatorError("intra-host reduce failed")
            with obs_span("outer_shard::pipeline"):
                delta_full = _outer_sharded_pipeline(
                    group,
                    contrib,
                    padded,
                    per,
                    unit,
                    update_cb,
                    num_participants,
                    should_quantize,
                    kind,
                    row_size,
                    tm,
                    tag_base=tag_base,
                    tag_span=tag_span,
                )
        except BaseException as e:  # noqa: BLE001
            err = err or e
            delta_full = np.zeros(padded, dtype=np.float32)

    if topo is not None:
        # members receive the delta; leaders always broadcast (zeros after a
        # failure) so host peers are never wedged — same containment
        # contract as the hierarchical quantized allreduce
        delta_full = comm.intra_broadcast(  # type: ignore[attr-defined]
            delta_full, padded, np.float32
        ).wait()
    if err is not None:
        raise err
    assert delta_full is not None
    tm["wall_s"] = time.perf_counter() - t_wall
    busy = tm["scatter_s"] + tm["update_s"] + tm["gather_s"]
    tm["overlap_ratio"] = round(busy / tm["wall_s"], 4) if tm["wall_s"] > 0 else 0.0
    if timings is not None:
        timings.update({k: round(v, 6) for k, v in tm.items()})
    if tap is not None:
        try:
            tap(delta_full[:n])
        except Exception:  # noqa: BLE001 — observers must not fail the sync
            pass
    return delta_full[:n]


def _outer_sharded_pipeline(
    group: Communicator,
    contrib: np.ndarray,
    padded: int,
    per: int,
    unit: int,
    update_cb: Callable[[int, int, np.ndarray], np.ndarray],
    num_participants: int,
    should_quantize: bool,
    kind: str,
    row_size: int,
    tm: dict,
    tag_base: int = _OUTER_TAG_BASE,
    tag_span: int = wire.OUTER_SHARD_TAG_SPAN,
) -> np.ndarray:
    """Shard-owner body of :func:`outer_sharded_sync` over ``group`` (the
    flat communicator, or the leader view on hierarchical topologies)."""
    gsize = max(1, group.size())
    gidx = group.rank() if gsize > 1 else 0
    buf = np.zeros(padded, dtype=np.float32)
    buf[: contrib.size] = contrib
    chunks = _outer_chunk_ranges(per, unit, gsize, max_chunks=tag_span // 2)
    inv = 1.0 / max(1, num_participants)
    delta_full = np.empty(padded, dtype=np.float32)
    err: Optional[BaseException] = None

    q_full: Optional[np.ndarray] = None
    s_full: Optional[np.ndarray] = None
    if should_quantize:
        # quantize the whole contribution ONCE; every a2a slice below is a
        # row-aligned view of this single pass
        q_full, s_full = quantize_rowwise(buf, row_size, kind)

    if gsize == 1 or getattr(group, "is_passthrough", False):
        # degenerate single-owner group: no wire, but keep the per-chunk
        # schedule (and, when quantized, the wire-format round trip) so the
        # numerics match the multi-owner path's contract
        for c0, c1 in chunks:
            if should_quantize:
                assert q_full is not None and s_full is not None
                rows = slice(c0 // row_size, c1 // row_size)
                avg = dequantize_rowwise(
                    q_full[rows], s_full[rows], c1 - c0, np.float32
                )
                avg *= inv
            else:
                avg = buf[c0:c1] * inv
            t0 = time.perf_counter()
            delta = np.asarray(update_cb(c0, c1, avg), dtype=np.float32)
            tm["update_s"] += time.perf_counter() - t0
            if should_quantize:
                dq, ds = quantize_rowwise(delta, row_size, kind)
                delta = dequantize_rowwise(dq, ds, c1 - c0, np.float32)
            delta_full[c0:c1] = delta
        return delta_full

    my_base = gidx * per

    def _submit_a2a(ci: int) -> Work:
        c0, c1 = chunks[ci]
        if should_quantize:
            assert q_full is not None and s_full is not None
            parts = [
                _pack(
                    q_full[(p * per + c0) // row_size : (p * per + c1) // row_size],
                    s_full[(p * per + c0) // row_size : (p * per + c1) // row_size],
                )
                for p in range(gsize)
            ]
        else:
            parts = [buf[p * per + c0 : p * per + c1] for p in range(gsize)]
        return group.alltoall(parts, tag=tag_base + 2 * ci)

    a2a_work = _submit_a2a(0)
    ag_works: List[Work] = []
    for ci, (c0, c1) in enumerate(chunks):
        rows = (c1 - c0) // row_size
        t0 = time.perf_counter()
        try:
            gathered = a2a_work.wait()
        except BaseException as e:  # noqa: BLE001 — degrade, keep schedule
            err = err or e
            gathered = None
        tm["scatter_s"] += time.perf_counter() - t0
        if ci + 1 < len(chunks):
            a2a_work = _submit_a2a(ci + 1)
        delta: Optional[np.ndarray] = None
        if gathered is not None:
            try:
                if should_quantize:
                    qs, scs = zip(
                        *(_unpack(g, rows, row_size, kind) for g in gathered)
                    )
                    acc = np.einsum(
                        "wrc,wr->rc",
                        np.stack(qs).astype(np.float32),
                        np.stack(scs),
                    ).reshape(-1)
                else:
                    acc = np.sum(np.stack(gathered), axis=0)
                acc *= inv
                t0 = time.perf_counter()
                with obs_span("outer_shard::chunk_update", chunk=ci):
                    delta = np.asarray(
                        update_cb(my_base + c0, my_base + c1, acc),
                        dtype=np.float32,
                    )
                tm["update_s"] += time.perf_counter() - t0
            except BaseException as e:  # noqa: BLE001
                err = err or e
                delta = None
        if delta is None:
            delta = np.zeros(c1 - c0, dtype=np.float32)
        if should_quantize:
            dq, ds = quantize_rowwise(delta, row_size, kind)
            ag_works.append(
                group.allgather(_pack(dq, ds), tag=tag_base + 2 * ci + 1)
            )
        else:
            ag_works.append(
                group.allgather(delta, tag=tag_base + 2 * ci + 1)
            )

    for ci, work in enumerate(ag_works):
        c0, c1 = chunks[ci]
        rows = (c1 - c0) // row_size
        t0 = time.perf_counter()
        try:
            all_deltas = work.wait()
        except BaseException as e:  # noqa: BLE001
            err = err or e
            all_deltas = None
        tm["gather_s"] += time.perf_counter() - t0
        for p in range(gsize):
            dst = delta_full[p * per + c0 : p * per + c1]
            if all_deltas is None:
                dst[:] = 0.0
            elif should_quantize:
                # every replica (the owner included) applies the WIRE
                # delta, so params stay bit-identical across replicas
                try:
                    dq, ds = _unpack(all_deltas[p], rows, row_size, kind)
                    dst[:] = dequantize_rowwise(dq, ds, c1 - c0, np.float32)
                except BaseException as e:  # noqa: BLE001
                    err = err or e
                    dst[:] = 0.0
            else:
                dst[:] = all_deltas[p]

    if err is not None:
        raise err
    return delta_full


def _hier_topology(comm: Communicator) -> Optional[dict]:
    """The epoch's ACTIVE hierarchical topology (uniform across ranks), or
    None for flat tiers/epochs."""
    fn = getattr(comm, "hier_topology", None)
    return fn() if callable(fn) else None


def _hier_allreduce_quantized_sync(
    comm: Communicator,
    topo: dict,
    flat: np.ndarray,
    row_size: int,
    kind: str,
    tag_base: int,
) -> np.ndarray:
    """Topology-aware quantized SUM-allreduce: reduce float32 once per host
    over shared memory, quantize ONCE PER HOST, run the windowed pipeline
    only among host leaders, shm-broadcast the dequantized sum back out.
    Int8 wire bytes drop by the local-group factor on top of the 4x from
    quantization, and non-leaders never touch the DCN.

    Numerics differ from the flat pipeline (host contributions are summed
    in f32 BEFORE quantization — strictly less quantization error), so the
    contract vs the true sum is the same quantized tolerance, not
    bit-equality with the flat path."""
    # any stage failure degrades toward zeros but KEEPS the shm schedule —
    # skipping the broadcast would leave host peers spinning until their
    # deadline (the underlying shm ops run on the op thread even when a
    # wrapper fails only the returned future), then re-raises so the step
    # is voted down; same containment contract as the flat pipeline
    err: Optional[BaseException] = None
    host_sum: Optional[np.ndarray] = None
    try:
        host_sum = comm.intra_reduce(flat).wait()  # type: ignore[attr-defined]
    except BaseException as e:  # noqa: BLE001
        err = e
    out: Optional[np.ndarray] = None
    if topo["is_leader"]:
        try:
            if host_sum is None:
                raise err or CommunicatorError("intra-host reduce failed")
            q, scales = quantize_rowwise(host_sum, row_size, kind)
            lead = comm.leader_comm()  # type: ignore[attr-defined]
            if lead.size() > 1:
                out = _allreduce_pipelined_sync(
                    lead, q, scales, flat.size, tag_base=tag_base
                )
            else:
                # single host: the wire round-trip degenerates but the
                # quantization error stays observable, like ws==1 flat
                out = dequantize_rowwise(q, scales, flat.size, np.float32)
        except BaseException as e:  # noqa: BLE001
            err = err or e
            out = np.zeros(flat.size, dtype=np.float32)
    summed = comm.intra_broadcast(  # type: ignore[attr-defined]
        out, flat.size, np.float32
    ).wait()
    if err is not None:
        raise err
    return summed


def _allreduce_quantized_sync(
    comm: Communicator, arrays: List[np.ndarray], row_size: int, kind: str = INT8
) -> List[np.ndarray]:
    layout = [(a.shape, a.dtype, a.size) for a in arrays]
    flat = np.concatenate(
        [np.asarray(a, dtype=np.float32).reshape(-1) for a in arrays]
    )
    topo = _hier_topology(comm)
    if topo is not None:
        summed = _hier_allreduce_quantized_sync(
            comm, topo, flat, row_size, kind, tag_base=QUANT_PIPELINE_TAG_BASE
        )
    else:
        q, scales = quantize_rowwise(flat, row_size, kind)
        summed = _allreduce_pipelined_sync(
            comm, q, scales, flat.size, tag_base=QUANT_PIPELINE_TAG_BASE
        )

    out: List[np.ndarray] = []
    off = 0
    for shape, dtype, size in layout:
        out.append(
            summed[off : off + size].reshape(shape).astype(dtype, copy=False)
        )
        off += size
    return out


def allreduce_prequantized(
    comm: Communicator,
    q: np.ndarray,
    scales: np.ndarray,
    n: int,
) -> np.ndarray:
    """SUM-allreduce of an already-quantized stream (1-byte rows + f32
    rowwise scales, e.g. produced on device by ``ops.pallas_quant``);
    returns the dequantized float32 sum of length ``n``.  Synchronous —
    callers layer Work/threading on top (``Manager.allreduce_prequantized``)."""
    scales = np.asarray(scales).reshape(-1)
    if comm.size() == 1 or getattr(comm, "is_passthrough", False):
        return dequantize_rowwise(q, scales, n, np.float32)
    topo = _hier_topology(comm)
    if topo is not None:
        # prequantized input on a hierarchical topology: dequantize locally
        # (host-side f32, the shm hop is cheap) and take the once-per-host
        # requantize path — leaders alone quantize for the DCN
        flat = dequantize_rowwise(q, scales, n, np.float32)
        return _hier_allreduce_quantized_sync(
            comm, topo, flat, q.shape[1], _kind_of(q),
            tag_base=DEVICE_QUANT_PIPELINE_TAG_BASE,
        )
    return _allreduce_pipelined_sync(
        comm, q, scales, n, tag_base=DEVICE_QUANT_PIPELINE_TAG_BASE
    )


def allreduce_quantized(
    comm: Communicator,
    buffers: Buffers,
    row_size: int = DEFAULT_ROW_SIZE,
    kind: str = INT8,
) -> Work:
    """SUM-allreduce through a 1-byte wire format (int8 default, fp8
    optional): the Work's value mirrors ``buffers`` with summed float values
    (the Manager divides by participants afterwards, exactly like the
    unquantized path).

    Accuracy: rowwise int8 carries ~2-3 decimal digits; intended for DiLoCo
    pseudogradients where the outer optimizer tolerates it (the reference
    ships fp8 with the same caveat — pass ``kind="fp8"`` for that format).
    """
    single = isinstance(buffers, np.ndarray)
    arrays: List[np.ndarray] = [buffers] if single else list(buffers)

    if comm.size() == 1 or getattr(comm, "is_passthrough", False):
        # single member (or a passthrough test double): the sum is our own
        # contribution; round-trip through the wire format so quantization
        # error stays observable in tests
        out = []
        for a in arrays:
            flat = np.asarray(a, dtype=np.float32).reshape(-1)
            q, s = quantize_rowwise(flat, row_size, kind)
            out.append(
                dequantize_rowwise(q, s, flat.size, np.float32)
                .reshape(a.shape)
                .astype(a.dtype, copy=False)
            )
        return DummyWork(out[0] if single else out)

    fut: Future = Future()

    def _run() -> None:
        try:
            out = _allreduce_quantized_sync(comm, arrays, row_size, kind)
            fut.set_result(out[0] if single else out)
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(
        target=_run, name="tpuft_quantized_allreduce", daemon=True
    ).start()
    return Work(fut)


def reduce_scatter_quantized(
    comm: Communicator,
    buffers: Buffers,
    row_size: int = DEFAULT_ROW_SIZE,
    kind: str = INT8,
) -> Work:
    """Quantized reduce-scatter (``collectives.py:159-294``): each rank gets
    the dequantized sum of its row-shard only (flat float32)."""
    single = isinstance(buffers, np.ndarray)
    arrays: List[np.ndarray] = [buffers] if single else list(buffers)
    flat = np.concatenate(
        [np.asarray(a, dtype=np.float32).reshape(-1) for a in arrays]
    )
    if comm.size() == 1 or getattr(comm, "is_passthrough", False):
        q, s = quantize_rowwise(flat, row_size, kind)
        return DummyWork(dequantize_rowwise(q, s, flat.size, np.float32))

    fut: Future = Future()

    def _run() -> None:
        try:
            topo = _hier_topology(comm)
            if topo is not None:
                # hierarchical: once-per-host quantized allreduce, then
                # requantize the full sum and slice this rank's row-shard —
                # same shard geometry as the flat alltoall path
                summed = _hier_allreduce_quantized_sync(
                    comm, topo, flat, row_size, kind, tag_base=QUANT_RING_TAG
                )
                q_full, s_full = quantize_rowwise(summed, row_size, kind)
                ws = comm.size()
                rows_per_rank = -(-q_full.shape[0] // ws)
                r = comm.rank()
                q_red = np.zeros((rows_per_rank, row_size), wire_dtype(kind))
                s_red = np.zeros(rows_per_rank, np.float32)
                shard = q_full[r * rows_per_rank : (r + 1) * rows_per_rank]
                q_red[: shard.shape[0]] = shard
                s_red[: shard.shape[0]] = s_full[
                    r * rows_per_rank : r * rows_per_rank + shard.shape[0]
                ]
            else:
                q_red, s_red, _rows, rows_per_rank = (
                    _quantized_reduce_scatter_sync(
                        comm, flat, row_size, tag=QUANT_RING_TAG, kind=kind
                    )
                )
            total = (q_red.astype(np.float32) * s_red[:, None]).reshape(-1)
            fut.set_result(total)
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(
        target=_run, name="tpuft_quantized_reduce_scatter", daemon=True
    ).start()
    return Work(fut)
