"""Programmable failure injection: the Monarch FailureController analog.

The reference's Monarch example supervises replicas as actors and injects
typed failures programmatically — SEGFAULT / KILL_PROC / COMMS / DEADLOCK /
KILL_SLURM (``/root/reference/examples/monarch/utils/failure.py:24-95``).
This module gives torchft_tpu the same scriptable surface over both replica
planes the framework runs on:

- **process plane** (:class:`ProcessReplica`): replica groups as OS
  processes under :class:`~torchft_tpu.launcher.ReplicaSupervisor` —
  failures are real signals (SIGKILL / SIGSEGV / SIGSTOP-freeze).
- **thread plane** (:class:`ThreadReplica`): replicas as threads in one
  process (the CI harness shape, ``tests/test_manager_integ.py``) —
  failures arm the replica loop's cooperative hooks (kill flag, wedge,
  communicator abort).

:class:`ChaosController` is the scenario driver: ``inject()`` delivers a
typed failure to a chosen (or random) victim, ``await_heal()`` blocks until
the victim commits again, and ``run_poisson()`` is the randomized soak
loop (``scripts/soak.py`` runs on it; chaos tests script it directly).
"""

from __future__ import annotations

import enum
import logging
import random
import signal
import threading
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)


class Failure(enum.Enum):
    """Failure classes, matching the reference's enum
    (``examples/monarch/utils/failure.py:24-33``) plus the
    coordination-plane death the reference leaves to manual chaos."""

    KILL = "kill"  # hard process/thread death; supervisor restarts it
    SEGFAULT = "segfault"  # SIGSEGV (process plane)
    DEADLOCK = "deadlock"  # wedge mid-step; peers must evict via timeouts
    COMM_ABORT = "commabort"  # comms die under the replica (NIC analog)
    LIGHTHOUSE = "lighthouse"  # coordination plane dies + restarts


@dataclass
class ChaosEvent:
    ts: float
    failure: Failure
    victim: Optional[str]
    detail: Dict[str, Any] = field(default_factory=dict)


class ReplicaHandle(ABC):
    """One injectable replica.  ``progress()`` must be monotone in
    committed steps — ``await_heal`` is defined in terms of it."""

    name: str

    @abstractmethod
    def supports(self, failure: Failure) -> bool: ...

    @abstractmethod
    def inject(self, failure: Failure, **kw: Any) -> None: ...

    @abstractmethod
    def progress(self) -> int: ...


class ThreadReplica(ReplicaHandle):
    """Adapter over a thread-plane replica object exposing the cooperative
    hook shape used by the soak/chaos harnesses:

    - ``kill_flag: threading.Event`` — raise-and-restart on next step
    - ``wedge_flag: threading.Event`` + ``wedge_secs: float`` — park
      mid-step after joining the quorum
    - ``comm`` — live communicator with ``abort(reason)``
    - ``commits: int`` (or ``progress``) — monotone committed-step count
    """

    def __init__(self, name: str, obj: Any) -> None:
        self.name = name
        self._obj = obj

    def supports(self, failure: Failure) -> bool:
        return failure in (Failure.KILL, Failure.DEADLOCK, Failure.COMM_ABORT)

    def inject(self, failure: Failure, **kw: Any) -> None:
        if failure is Failure.KILL:
            self._obj.kill_flag.set()
        elif failure is Failure.DEADLOCK:
            self._obj.wedge_secs = float(kw.get("secs", 10.0))
            self._obj.wedge_flag.set()
        elif failure is Failure.COMM_ABORT:
            comm = getattr(self._obj, "comm", None)
            if comm is None:
                raise RuntimeError(f"{self.name}: no live communicator yet")
            comm.abort(str(kw.get("reason", "chaos: injected comm failure")))
        else:
            raise ValueError(f"thread plane cannot inject {failure}")

    def progress(self) -> int:
        return int(
            getattr(self._obj, "commits", getattr(self._obj, "progress", 0))
        )


class ProcessReplica(ReplicaHandle):
    """Adapter over one replica group of a
    :class:`~torchft_tpu.launcher.ReplicaSupervisor` — failures are real
    signals against the live process; the supervisor's restart/standby
    machinery is the recovery under test.

    ``progress_fn`` reads the group's committed step from the outside
    (an event log, the lighthouse status page, a log scraper).
    """

    def __init__(
        self,
        name: str,
        supervisor: Any,
        replica_group_id: int,
        progress_fn: Callable[[], int] = lambda: 0,
    ) -> None:
        self.name = name
        self._supervisor = supervisor
        self._gid = replica_group_id
        self._progress_fn = progress_fn

    def supports(self, failure: Failure) -> bool:
        return failure in (Failure.KILL, Failure.SEGFAULT, Failure.DEADLOCK)

    def inject(self, failure: Failure, **kw: Any) -> None:
        if failure is Failure.KILL:
            ok = self._supervisor.kill(self._gid, sig=signal.SIGKILL)
        elif failure is Failure.SEGFAULT:
            ok = self._supervisor.kill(self._gid, sig=signal.SIGSEGV)
        elif failure is Failure.DEADLOCK:
            # the truest deadlock: every thread frozen, heartbeats included;
            # thaw after ``secs`` so the victim rejoins and heals
            secs = float(kw.get("secs", 12.0))
            ok = self._supervisor.kill(self._gid, sig=signal.SIGSTOP)
            if ok:
                timer = threading.Timer(
                    secs,
                    lambda: self._supervisor.kill(
                        self._gid, sig=signal.SIGCONT
                    ),
                )
                timer.daemon = True
                timer.start()
        else:
            raise ValueError(f"process plane cannot inject {failure}")
        if not ok:
            raise RuntimeError(
                f"{self.name}: no live process to inject {failure.value}"
            )

    def progress(self) -> int:
        return int(self._progress_fn())


class ChaosController:
    """Scriptable failure scenarios over a set of replica handles.

    ``lighthouse_restart`` (when provided) implements
    :attr:`Failure.LIGHTHOUSE`: it must tear down the coordination plane
    and bring it back (same address, empty soft state).
    """

    def __init__(
        self,
        replicas: Sequence[ReplicaHandle],
        lighthouse_restart: Optional[Callable[[], None]] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.replicas = list(replicas)
        self._lighthouse_restart = lighthouse_restart
        self._rng = rng or random.Random()
        self.events: List[ChaosEvent] = []

    # -- injection ---------------------------------------------------------

    def inject(
        self,
        failure: Failure,
        victim: Optional[ReplicaHandle] = None,
        **kw: Any,
    ) -> Optional[ReplicaHandle]:
        """Deliver ``failure``; picks a random supporting victim when none
        is given.  Returns the victim (None for fleet-level failures)."""
        if failure is Failure.LIGHTHOUSE:
            if self._lighthouse_restart is None:
                raise ValueError("no lighthouse_restart configured")
            self._lighthouse_restart()
            self.events.append(
                ChaosEvent(time.time(), failure, victim=None, detail=kw)
            )
            logger.info("chaos: lighthouse restarted")
            return None
        if victim is None:
            candidates = [r for r in self.replicas if r.supports(failure)]
            if not candidates:
                raise ValueError(f"no replica supports {failure}")
            victim = self._rng.choice(candidates)
        victim.inject(failure, **kw)
        detail = dict(kw)
        detail["progress_at_inject"] = victim.progress()
        self.events.append(
            ChaosEvent(time.time(), failure, victim=victim.name, detail=detail)
        )
        logger.info("chaos: %s -> %s %s", failure.value, victim.name, kw)
        return victim

    # -- observation -------------------------------------------------------

    def await_progress(
        self,
        victim: ReplicaHandle,
        beyond: int,
        timeout_s: float,
        poll_s: float = 0.1,
    ) -> bool:
        """Block until ``victim.progress() > beyond`` (False on timeout)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if victim.progress() > beyond:
                return True
            time.sleep(poll_s)
        return victim.progress() > beyond

    def await_heal(
        self, victim: ReplicaHandle, timeout_s: float = 60.0
    ) -> bool:
        """Block until the victim commits beyond its progress at the LAST
        injection against it, plus one step of slack — thread-plane
        failures are armed via flags consumed at the victim's next step
        boundary, so the step in flight at inject time may still commit
        before the failure lands and must not count as healed."""
        baseline = victim.progress()
        slack = 0
        for ev in reversed(self.events):
            if ev.victim == victim.name:
                baseline = max(
                    baseline, int(ev.detail.get("progress_at_inject", 0))
                )
                slack = 1  # the step in flight at inject time
                break
        return self.await_progress(victim, baseline + slack, timeout_s)

    # -- randomized soak ---------------------------------------------------

    def run_poisson(
        self,
        classes: Sequence[Failure],
        mtbf_s: float,
        stop: threading.Event,
        on_inject: Optional[Callable[[ChaosEvent], None]] = None,
        deadlock_secs: Optional[Callable[[], float]] = None,
    ) -> Dict[Failure, int]:
        """Inject failures on a Poisson schedule until ``stop`` — the soak
        loop (``scripts/soak.py``).  Returns per-class injection counts."""
        counts = {c: 0 for c in classes}
        while not stop.is_set():
            stop.wait(self._rng.expovariate(1.0 / mtbf_s))
            if stop.is_set():
                break
            cls = self._rng.choice(list(classes))
            kw: Dict[str, Any] = {}
            if cls is Failure.DEADLOCK:
                kw["secs"] = (
                    deadlock_secs() if deadlock_secs
                    else self._rng.uniform(2.0, 22.0)
                )
            try:
                self.inject(cls, **kw)
            except (RuntimeError, ValueError) as e:
                # a victim with no live comm yet (etc.) is a no-op draw,
                # not a soak failure
                logger.info("chaos: %s skipped (%s)", cls.value, e)
                continue
            counts[cls] += 1
            if on_inject:
                on_inject(self.events[-1])
        return counts
