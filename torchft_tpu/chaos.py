"""Programmable failure injection: the Monarch FailureController analog.

The reference's Monarch example supervises replicas as actors and injects
typed failures programmatically — SEGFAULT / KILL_PROC / COMMS / DEADLOCK /
KILL_SLURM (``/root/reference/examples/monarch/utils/failure.py:24-95``).
This module gives torchft_tpu the same scriptable surface over both replica
planes the framework runs on:

- **process plane** (:class:`ProcessReplica`): replica groups as OS
  processes under :class:`~torchft_tpu.launcher.ReplicaSupervisor` —
  failures are real signals (SIGKILL / SIGSEGV / SIGSTOP-freeze).
- **thread plane** (:class:`ThreadReplica`): replicas as threads in one
  process (the CI harness shape, ``tests/test_manager_integ.py``) —
  failures arm the replica loop's cooperative hooks (kill flag, wedge,
  communicator abort).

:class:`ChaosController` is the scenario driver: ``inject()`` delivers a
typed failure to a chosen (or random) victim, ``await_heal()`` blocks until
the victim commits again, and ``run_poisson()`` is the randomized soak
loop (``scripts/soak.py`` runs on it; chaos tests script it directly).
"""

from __future__ import annotations

import enum
import logging
import random
import signal
import threading
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)


class Failure(enum.Enum):
    """Failure classes, matching the reference's enum
    (``examples/monarch/utils/failure.py:24-33``) plus the
    coordination-plane death the reference leaves to manual chaos."""

    KILL = "kill"  # hard process/thread death; supervisor restarts it
    SEGFAULT = "segfault"  # SIGSEGV (process plane)
    DEADLOCK = "deadlock"  # wedge mid-step; peers must evict via timeouts
    COMM_ABORT = "commabort"  # comms die under the replica (NIC analog)
    LIGHTHOUSE = "lighthouse"  # coordination plane dies + restarts
    HEAL_SOURCE = "healsource"  # die mid-transfer while SERVING a heal
    HOST_LEADER = "hostleader"  # kill a replica currently LEADING its host
    # group in the hierarchical data plane: its host's members lose their
    # shm hub and the cross-host ring loses a member mid-collective; the
    # next quorum must re-elect a leader (lowest surviving rank) and
    # /dev/shm must hold no orphaned segments (unlinked-after-map)
    # -- gray failures (arxiv 2508.21613: policy should match failure TYPE;
    # these are TRANSIENT, survived in-epoch, not crash-recovered) --------
    NET_FLAKY = "netflaky"  # flaky link: frame loss + occasional resets;
    # the lane retry/failover machinery must recover IN-epoch (zero quorum
    # reconfigurations), visible as comm_lane_reconnects > 0
    SLOW_NIC = "slownic"  # one persistently slow NIC: heavy stall windows
    # drag every collective; detection (heartbeat comm-health) must flag
    # the victim and, under TORCHFT_EVICT_SLOW, shed it from the quorum
    PARTITION = "partition"  # the victim is cut from the fleet (data-plane
    # partition mask + paused heartbeats): the majority side must form a
    # quorum without it (anti split-brain keeps the minority down)
    SPARE = "spare"  # kill a WARMING hot spare (wire-v3 SPARE role): the
    # active fleet must not notice — zero quorum reconfigurations, no
    # stalls, no poisoned state (a spare never counts toward membership
    # and its warm RPCs are served outside the heal path)
    DEVICE_LOSS = "deviceloss"  # IN-REPLICA device death (wire v5): the
    # replica must NOT die — it re-lowers its inner mesh onto the
    # surviving devices (parallel/degraded.py), advertises the reduced
    # capacity fraction, rescales its data shard, and keeps contributing
    # through the capacity-weighted outer reduce.  Zero full-replica
    # evictions; with a warm spare registered, the lighthouse swaps the
    # wounded replica for the spare in ONE membership edit instead.
    # kw: devices=N (how many devices die), mid_relower=True arms a crash
    # BETWEEN begin_relower and complete_relower (the half-relowered
    # replica must never vote commit).


@dataclass
class ChaosEvent:
    ts: float
    failure: Failure
    victim: Optional[str]
    detail: Dict[str, Any] = field(default_factory=dict)


# default fault programs for the gray failure classes — shared by BOTH
# replica planes so a tuned default cannot silently diverge between them
_GRAY_DEFAULT_SPECS = {
    Failure.NET_FLAKY: "loss:0.01,reset:0.002",
    Failure.SLOW_NIC: "stall:0.5:50",
    Failure.PARTITION: "partition:self",
}


def _flight_note(obj: Any, failure: Failure, **detail: Any) -> None:
    """Best-effort CHAOS_INJECT into the victim's flight recorder — the
    injection anchor a postmortem timeline chains its causal sequence
    from.  Reaches the recorder through the victim's manager (thread
    plane) or its communicator attachment; silently a no-op when neither
    exists (mock harnesses)."""
    manager = getattr(obj, "manager", None)
    flight = getattr(manager, "_flight", None)
    if flight is None:
        flight = getattr(getattr(obj, "comm", None), "flight", None)
    if flight is None:
        return
    from torchft_tpu.obs.flight import FlightEvent

    flight.record(FlightEvent.CHAOS_INJECT, failure=failure.value, **detail)


def arm_heal_source_kill(
    transport: Any,
    after_bytes: int = 1 << 20,
    arm: Optional[threading.Event] = None,
    striped_only: bool = False,
) -> threading.Event:
    """Arm a checkpoint transport to die after SERVING ~``after_bytes`` of
    heal payload — the deterministic form of :attr:`Failure.HEAL_SOURCE`
    (timing a SIGKILL against a transfer is racy; a byte-threshold trip
    wire is not).  Returns an event set when the kill fires.

    ``arm`` (optional) gates the trip wire: bytes served while it is unset
    do not count and do not kill, so a drill can let the initial-sync heal
    pass and only kill the source during the transfer under test.

    ``striped_only`` restricts the kill to STRIPED serving (multi-source
    chunk ranges, where a survivor can steal the dead source's chunks);
    single-source transfers pass untouched — killing the only source is a
    fatal scenario, not a failover drill.  The comm transport's trip wire
    lives in its striped serve loop, so it is striped-only by nature.

    Works on both checkpoint transports:

    - :class:`~torchft_tpu.checkpointing.http_transport.HTTPTransport`:
      the serving handler aborts mid-payload and the HTTP server shuts
      down (further range requests are refused — the source looks dead).
    - :class:`~torchft_tpu.checkpointing.comm_transport.CommTransport`:
      the striped serve loop aborts its communicator after its sent-byte
      counter passes the threshold.
    """
    fired = threading.Event()

    if hasattr(transport, "chaos_die_after_bytes"):  # CommTransport
        transport.chaos_die_after_bytes = after_bytes
        transport.chaos_arm = arm
        return transport.chaos_fired

    if hasattr(transport, "chaos_striped_only"):
        transport.chaos_striped_only = striped_only

    served_while_armed = [0]
    last_total = [0]

    def _hook(total_bytes: int) -> bool:
        delta, last_total[0] = total_bytes - last_total[0], total_bytes
        if arm is not None and not arm.is_set():
            return False
        served_while_armed[0] += delta
        if served_while_armed[0] >= after_bytes:
            fired.set()
            return True
        return False

    transport.chaos_serve_hook = _hook
    return fired


class ReplicaHandle(ABC):
    """One injectable replica.  ``progress()`` must be monotone in
    committed steps — ``await_heal`` is defined in terms of it."""

    name: str

    @abstractmethod
    def supports(self, failure: Failure) -> bool: ...

    @abstractmethod
    def inject(self, failure: Failure, **kw: Any) -> None: ...

    @abstractmethod
    def progress(self) -> int: ...


class ThreadReplica(ReplicaHandle):
    """Adapter over a thread-plane replica object exposing the cooperative
    hook shape used by the soak/chaos harnesses:

    - ``kill_flag: threading.Event`` — raise-and-restart on next step
    - ``wedge_flag: threading.Event`` + ``wedge_secs: float`` — park
      mid-step after joining the quorum
    - ``comm`` — live communicator with ``abort(reason)``
    - ``commits: int`` (or ``progress``) — monotone committed-step count
    """

    def __init__(self, name: str, obj: Any) -> None:
        self.name = name
        self._obj = obj

    def supports(self, failure: Failure) -> bool:
        # liveness probe: a harness that exposes an ``alive`` attribute (a
        # bool or a callable) lets the soak loop's every-victim-dead clean
        # stop actually fire for flag-armed classes too
        alive = getattr(self._obj, "alive", None)
        if alive is not None and not (alive() if callable(alive) else alive):
            return False
        if failure is Failure.HEAL_SOURCE:
            return getattr(self._obj, "heal_transport", None) is not None
        if failure is Failure.HOST_LEADER:
            return self._is_host_leader()
        if failure is Failure.SPARE:
            # only a replica currently in the SPARE role qualifies (a
            # promoted spare is an active — killing it is Failure.KILL)
            manager = getattr(self._obj, "manager", None)
            return getattr(manager, "role", "active") == "spare"
        if failure is Failure.DEVICE_LOSS:
            # the replica loop must expose the degraded-mode hook (it
            # owns the re-lower — chaos only kills devices)
            return getattr(self._obj, "device_loss_flag", None) is not None
        if failure in _GRAY_DEFAULT_SPECS:
            comm = getattr(self._obj, "comm", None)
            return callable(getattr(comm, "arm_faults", None))
        return failure in (Failure.KILL, Failure.DEADLOCK, Failure.COMM_ABORT)

    def _is_host_leader(self) -> bool:
        comm = getattr(self._obj, "comm", None)
        topo_fn = getattr(comm, "hier_topology", None)
        if not callable(topo_fn):
            return False
        try:
            topo = topo_fn()
        except Exception:  # noqa: BLE001 — comm mid-reconfigure
            return False
        return bool(topo and topo.get("is_leader"))

    def inject(self, failure: Failure, **kw: Any) -> None:
        if failure not in _GRAY_DEFAULT_SPECS:
            # gray classes record their CHAOS_INJECT inside
            # comm.arm_faults (which this inject routes through) — noting
            # them here too would double-record every injection
            _flight_note(
                self._obj,
                failure,
                plane="thread",
                **{
                    k: v
                    for k, v in kw.items()
                    if isinstance(v, (int, float, str, bool))
                },
            )
        if failure is Failure.HOST_LEADER:
            # targeted KILL conditioned on the victim's CURRENT topology
            # role — leadership is per-epoch (lowest surviving rank of the
            # host group), so the role is checked at inject time
            if not self._is_host_leader():
                raise RuntimeError(
                    f"{self.name}: not a host leader in the current epoch"
                )
            self._obj.kill_flag.set()
        elif failure is Failure.KILL:
            self._obj.kill_flag.set()
        elif failure is Failure.SPARE:
            if getattr(getattr(self._obj, "manager", None), "role", None) != "spare":
                raise RuntimeError(
                    f"{self.name}: not a spare in the current epoch"
                )
            self._obj.kill_flag.set()
        elif failure is Failure.DEVICE_LOSS:
            flag = getattr(self._obj, "device_loss_flag", None)
            if flag is None:
                raise RuntimeError(
                    f"{self.name}: no device_loss hook on this replica"
                )
            # the replica consumes these at its next step boundary:
            # devices = how many of its (virtual) devices just died;
            # mid_relower arms a crash INSIDE the re-lower window — the
            # kill-mid-relower chaos case proving a half-relowered
            # replica never votes commit
            self._obj.device_loss_count = int(kw.get("devices", 1))
            self._obj.device_loss_mid_relower = bool(
                kw.get("mid_relower", False)
            )
            flag.set()
        elif failure is Failure.DEADLOCK:
            self._obj.wedge_secs = float(kw.get("secs", 10.0))
            self._obj.wedge_flag.set()
        elif failure is Failure.COMM_ABORT:
            comm = getattr(self._obj, "comm", None)
            if comm is None:
                raise RuntimeError(f"{self.name}: no live communicator yet")
            comm.abort(str(kw.get("reason", "chaos: injected comm failure")))
        elif failure is Failure.HEAL_SOURCE:
            transport = getattr(self._obj, "heal_transport", None)
            if transport is None:
                raise RuntimeError(f"{self.name}: no heal transport exposed")
            arm_heal_source_kill(
                transport,
                after_bytes=int(kw.get("after_bytes", 1 << 20)),
                arm=kw.get("arm"),
            )
        elif failure in _GRAY_DEFAULT_SPECS:
            comm = getattr(self._obj, "comm", None)
            if not callable(getattr(comm, "arm_faults", None)):
                raise RuntimeError(
                    f"{self.name}: no fault-armable communicator"
                )
            # spec=None DISARMS — chaos can heal a gray link mid-run
            spec = kw.get("spec", _GRAY_DEFAULT_SPECS[failure])
            comm.arm_faults(spec)
            if failure is Failure.PARTITION:
                # a partitioned replica loses its control plane too: sever
                # the manager's lighthouse path (heartbeats AND quorum
                # forwarding — a quorum rpc is an implicit heartbeat, so
                # pausing only beats would keep the victim looking alive)
                server = getattr(
                    getattr(self._obj, "manager", None), "_manager_server", None
                )
                if server is not None:
                    server.heartbeat_paused = spec is not None
        else:
            raise ValueError(f"thread plane cannot inject {failure}")

    def progress(self) -> int:
        return int(
            getattr(self._obj, "commits", getattr(self._obj, "progress", 0))
        )


class ProcessReplica(ReplicaHandle):
    """Adapter over one replica group of a
    :class:`~torchft_tpu.launcher.ReplicaSupervisor` — failures are real
    signals against the live process; the supervisor's restart/standby
    machinery is the recovery under test.

    ``progress_fn`` reads the group's committed step from the outside
    (an event log, the lighthouse status page, a log scraper).
    """

    def __init__(
        self,
        name: str,
        supervisor: Any,
        replica_group_id: int,
        progress_fn: Callable[[], int] = lambda: 0,
    ) -> None:
        self.name = name
        self._supervisor = supervisor
        self._gid = replica_group_id
        self._progress_fn = progress_fn

    def supports(self, failure: Failure) -> bool:
        if failure in _GRAY_DEFAULT_SPECS or failure is Failure.DEVICE_LOSS:
            # gray failures / device loss arm via the group's spawn env
            # (TORCHFT_NET_FAULTS / TORCHFT_CHAOS_DEVICE_LOSS): supported
            # when the supervisor exposes its specs
            return hasattr(self._supervisor, "_specs")
        return failure in (
            Failure.KILL,
            Failure.SEGFAULT,
            Failure.DEADLOCK,
            Failure.HEAL_SOURCE,
            Failure.HOST_LEADER,
        )

    def inject(self, failure: Failure, **kw: Any) -> None:
        if failure is Failure.DEVICE_LOSS:
            # process plane: a real device can't be unplugged from outside
            # the process, so the loss rides the group's spawn env
            # (TORCHFT_CHAOS_DEVICE_LOSS=N — the worker hides N devices
            # and re-lowers at startup) and lands on the next (re)start;
            # restart=True (default) bounces the process so it comes up
            # wounded now.  devices=0 heals: the env is cleared and the
            # next restart comes up full-width.
            devices = int(kw.get("devices", 1))
            spec_env = next(
                (
                    s.env
                    for s in self._supervisor._specs
                    if s.replica_group_id == self._gid
                ),
                None,
            )
            if spec_env is None:
                raise RuntimeError(f"{self.name}: no spec for group {self._gid}")
            if devices <= 0:
                spec_env.pop("TORCHFT_CHAOS_DEVICE_LOSS", None)
            else:
                spec_env["TORCHFT_CHAOS_DEVICE_LOSS"] = str(devices)
            if kw.get("restart", True):
                ok = self._supervisor.kill(self._gid, sig=signal.SIGKILL)
                if not ok:
                    raise RuntimeError(
                        f"{self.name}: no live process to restart with "
                        f"{failure.value}"
                    )
            return
        if failure in _GRAY_DEFAULT_SPECS:
            # process plane: the fault program rides the group's spawn env
            # (TORCHFT_NET_FAULTS) and lands on the next (re)start; pass
            # restart=True to bounce the process so it comes up flaky now.
            spec = kw.get("spec", _GRAY_DEFAULT_SPECS[failure])
            spec_env = next(
                (
                    s.env
                    for s in self._supervisor._specs
                    if s.replica_group_id == self._gid
                ),
                None,
            )
            if spec_env is None:
                raise RuntimeError(f"{self.name}: no spec for group {self._gid}")
            if spec is None:
                spec_env.pop("TORCHFT_NET_FAULTS", None)
            else:
                spec_env["TORCHFT_NET_FAULTS"] = str(spec)
            if kw.get("restart", True):
                ok = self._supervisor.kill(self._gid, sig=signal.SIGKILL)
                if not ok:
                    raise RuntimeError(
                        f"{self.name}: no live process to restart with "
                        f"{failure.value}"
                    )
            return
        if failure in (Failure.KILL, Failure.HEAL_SOURCE, Failure.HOST_LEADER):
            # process plane: a heal-source or host-leader kill IS a hard
            # kill — the caller picks a victim it knows holds the role (the
            # thread plane checks the role itself via the live comm)
            ok = self._supervisor.kill(self._gid, sig=signal.SIGKILL)
        elif failure is Failure.SEGFAULT:
            ok = self._supervisor.kill(self._gid, sig=signal.SIGSEGV)
        elif failure is Failure.DEADLOCK:
            # the truest deadlock: every thread frozen, heartbeats included;
            # thaw after ``secs`` so the victim rejoins and heals
            secs = float(kw.get("secs", 12.0))
            ok = self._supervisor.kill(self._gid, sig=signal.SIGSTOP)
            if ok:
                timer = threading.Timer(
                    secs,
                    lambda: self._supervisor.kill(
                        self._gid, sig=signal.SIGCONT
                    ),
                )
                timer.daemon = True
                timer.start()
        else:
            raise ValueError(f"process plane cannot inject {failure}")
        if not ok:
            raise RuntimeError(
                f"{self.name}: no live process to inject {failure.value}"
            )

    def progress(self) -> int:
        return int(self._progress_fn())


class ChaosController:
    """Scriptable failure scenarios over a set of replica handles.

    ``lighthouse_restart`` (when provided) implements
    :attr:`Failure.LIGHTHOUSE`: it must tear down the coordination plane
    and bring it back (same address, empty soft state).
    """

    def __init__(
        self,
        replicas: Sequence[ReplicaHandle],
        lighthouse_restart: Optional[Callable[[], None]] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.replicas = list(replicas)
        self._lighthouse_restart = lighthouse_restart
        self._rng = rng or random.Random()
        self.events: List[ChaosEvent] = []

    # -- injection ---------------------------------------------------------

    def inject(
        self,
        failure: Failure,
        victim: Optional[ReplicaHandle] = None,
        **kw: Any,
    ) -> Optional[ReplicaHandle]:
        """Deliver ``failure``; picks a random supporting victim when none
        is given.  Returns the victim (None for fleet-level failures)."""
        if failure is Failure.LIGHTHOUSE:
            if self._lighthouse_restart is None:
                raise ValueError("no lighthouse_restart configured")
            self._lighthouse_restart()
            self.events.append(
                ChaosEvent(time.time(), failure, victim=None, detail=kw)
            )
            logger.info("chaos: lighthouse restarted")
            return None
        if victim is None:
            candidates = [r for r in self.replicas if r.supports(failure)]
            if not candidates:
                raise ValueError(f"no replica supports {failure}")
            victim = self._rng.choice(candidates)
        victim.inject(failure, **kw)
        detail = dict(kw)
        detail["progress_at_inject"] = victim.progress()
        self.events.append(
            ChaosEvent(time.time(), failure, victim=victim.name, detail=detail)
        )
        logger.info("chaos: %s -> %s %s", failure.value, victim.name, kw)
        return victim

    # -- observation -------------------------------------------------------

    def await_progress(
        self,
        victim: ReplicaHandle,
        beyond: int,
        timeout_s: float,
        poll_s: float = 0.1,
    ) -> bool:
        """Block until ``victim.progress() > beyond`` (False on timeout)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if victim.progress() > beyond:
                return True
            time.sleep(poll_s)
        return victim.progress() > beyond

    def await_heal(
        self, victim: ReplicaHandle, timeout_s: float = 60.0
    ) -> bool:
        """Block until the victim commits beyond its progress at the LAST
        injection against it, plus one step of slack — thread-plane
        failures are armed via flags consumed at the victim's next step
        boundary, so the step in flight at inject time may still commit
        before the failure lands and must not count as healed."""
        baseline = victim.progress()
        slack = 0
        for ev in reversed(self.events):
            if ev.victim == victim.name:
                baseline = max(
                    baseline, int(ev.detail.get("progress_at_inject", 0))
                )
                slack = 1  # the step in flight at inject time
                break
        return self.await_progress(victim, baseline + slack, timeout_s)

    # -- randomized soak ---------------------------------------------------

    def run_poisson(
        self,
        classes: Sequence[Failure],
        mtbf_s: float,
        stop: threading.Event,
        on_inject: Optional[Callable[[ChaosEvent], None]] = None,
        deadlock_secs: Optional[Callable[[], float]] = None,
        rng: Optional[random.Random] = None,
    ) -> Dict[Failure, int]:
        """Inject failures on a Poisson schedule until ``stop`` — the soak
        loop (``scripts/soak.py``).  Returns per-class injection counts.

        ``rng`` (e.g. ``random.Random(seed)``) makes the whole soak
        reproducible: it drives the inter-arrival draws, the class/victim
        choice and the deadlock durations.  The loop stops cleanly — not
        raising — when every victim is already dead (no replica supports
        any of the requested classes)."""
        if rng is not None:
            self._rng = rng
        counts = {c: 0 for c in classes}
        while not stop.is_set():
            stop.wait(self._rng.expovariate(1.0 / mtbf_s))
            if stop.is_set():
                break
            if not any(
                r.supports(c) for r in self.replicas for c in classes
            ) and not (
                Failure.LIGHTHOUSE in classes
                and self._lighthouse_restart is not None
            ):
                logger.info(
                    "chaos: every victim is dead; ending the soak cleanly"
                )
                break
            cls = self._rng.choice(list(classes))
            kw: Dict[str, Any] = {}
            if cls is Failure.DEADLOCK:
                kw["secs"] = (
                    deadlock_secs() if deadlock_secs
                    else self._rng.uniform(2.0, 22.0)
                )
            try:
                self.inject(cls, **kw)
            except (RuntimeError, ValueError) as e:
                # a victim with no live comm yet (etc.) is a no-op draw,
                # not a soak failure
                logger.info("chaos: %s skipped (%s)", cls.value, e)
                continue
            counts[cls] += 1
            if on_inject:
                on_inject(self.events[-1])
        return counts
