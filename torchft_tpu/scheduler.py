"""Cluster scheduler shims: render/submit FT jobs to SLURM or GKE.

The reference launches replica groups through TorchX components
(``torchft/torchx.py:17-89`` — one role per replica group with the
``REPLICA_GROUP_ID`` / ``NUM_REPLICA_GROUPS`` / ``TORCHFT_LIGHTHOUSE`` env
contract) and a SLURM runner that submits one app per replica group so each
is an independent failure domain
(``torchft/examples/slurm/runner.py:22-115``).  torchft_tpu renders the
same contract for TPU-VM deployments:

- **SLURM**: one sbatch script per replica group (``--requeue`` gives the
  scheduler-level auto-restart the reference gets from its monitor loop).
- **GKE**: one Job manifest per replica group against a TPU node pool
  (``google.com/tpu`` resources + ``backoffLimit`` restarts).

The input is the same shape ``torchft_tpu.launcher`` takes (replicas +
training cmd + lighthouse), so moving from a single-host supervisor to a
cluster is a flag change, not a rewrite::

    python -m torchft_tpu.scheduler slurm --replicas 4 \
        --lighthouse head-node:29510 --out-dir jobs/ -- \
        python examples/train_ddp.py --steps 1000

Rendering is pure (files written to ``--out-dir``); ``--submit`` execs
``sbatch``/``kubectl apply`` on each rendered file when those binaries
exist on PATH.
"""

from __future__ import annotations

import argparse
import logging
import os
import shlex
import shutil
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("torchft_tpu.scheduler")


@dataclass
class JobSpec:
    """One FT job: N replica groups running ``cmd`` against a lighthouse."""

    replicas: int
    cmd: List[str]
    lighthouse: str
    job_name: str = "torchft-tpu"
    env: Dict[str, str] = field(default_factory=dict)
    # SLURM knobs
    partition: Optional[str] = None
    nodes_per_replica: int = 1
    time_limit: str = "24:00:00"
    max_restarts: int = 10
    # GKE knobs
    image: str = "python:3.12"
    tpu_accelerator: str = "tpu-v5p-slice"
    tpu_topology: str = "2x2x1"
    tpu_chips: int = 4
    namespace: str = "default"

    def contract_env(self, replica_id: int) -> Dict[str, str]:
        """The env contract every backend must deliver (launcher.py twin,
        same names as the reference)."""
        env = {
            "TORCHFT_LIGHTHOUSE": self.lighthouse,
            "REPLICA_GROUP_ID": str(replica_id),
            "NUM_REPLICA_GROUPS": str(self.replicas),
        }
        env.update(self.env)
        return env


def render_sbatch(spec: JobSpec) -> List[Tuple[str, str]]:
    """One sbatch script per replica group (independent failure domains —
    killing/requeueing one group never touches the others, exactly like the
    reference's per-replica TorchX apps)."""
    out = []
    for rid in range(spec.replicas):
        env_lines = "\n".join(
            f"export {k}={shlex.quote(v)}"
            for k, v in spec.contract_env(rid).items()
        )
        partition = (
            f"#SBATCH --partition={spec.partition}\n" if spec.partition else ""
        )
        script = f"""#!/bin/bash
#SBATCH --job-name={spec.job_name}-rg{rid}
#SBATCH --nodes={spec.nodes_per_replica}
#SBATCH --ntasks-per-node=1
#SBATCH --time={spec.time_limit}
#SBATCH --requeue
#SBATCH --open-mode=append
{partition}
# torchft_tpu replica group {rid}/{spec.replicas}: requeue on failure is the
# scheduler-level restart loop; the surviving groups keep training while
# this one comes back and heals from a live peer.
{env_lines}

# multi-host replica groups: every node of this allocation joins the same
# group; group_rank/group_world_size ride on SLURM's own variables
export TPUFT_GROUP_RANK=${{SLURM_NODEID:-0}}
export TPUFT_GROUP_WORLD_SIZE=${{SLURM_NNODES:-1}}

srun --kill-on-bad-exit=1 {shlex.join(spec.cmd)}
"""
        out.append((f"{spec.job_name}-rg{rid}.sbatch", script))
    return out


def render_gke(spec: JobSpec) -> List[Tuple[str, str]]:
    """One Kubernetes Job per replica group against a TPU node pool."""
    import json

    out = []
    for rid in range(spec.replicas):
        # json.dumps is valid YAML and escapes correctly (repr is not:
        # backslashes/quotes in values would corrupt the manifest)
        env_yaml = "\n".join(
            f"            - name: {k}\n              value: {json.dumps(str(v))}"
            for k, v in spec.contract_env(rid).items()
        )
        manifest = f"""apiVersion: batch/v1
kind: Job
metadata:
  name: {spec.job_name}-rg{rid}
  namespace: {spec.namespace}
  labels:
    app: {spec.job_name}
    replica-group: "{rid}"
spec:
  # the restart loop: a killed/crashed group re-runs and heals from a peer
  backoffLimit: {spec.max_restarts}
  template:
    metadata:
      labels:
        app: {spec.job_name}
        replica-group: "{rid}"
    spec:
      restartPolicy: OnFailure
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {spec.tpu_accelerator}
        cloud.google.com/gke-tpu-topology: {spec.tpu_topology}
      containers:
        - name: train
          image: {spec.image}
          command: {json.dumps(spec.cmd)}
          env:
{env_yaml}
          resources:
            requests:
              google.com/tpu: {spec.tpu_chips}
            limits:
              google.com/tpu: {spec.tpu_chips}
"""
        out.append((f"{spec.job_name}-rg{rid}.yaml", manifest))
    return out


def write_specs(
    rendered: List[Tuple[str, str]], out_dir: str
) -> List[str]:
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for name, content in rendered:
        path = os.path.join(out_dir, name)
        with open(path, "w") as f:
            f.write(content)
        paths.append(path)
    return paths


def submit(backend: str, paths: List[str]) -> None:
    """Submit rendered specs via the scheduler CLI (sbatch / kubectl)."""
    if backend == "slurm":
        tool, args = "sbatch", []
    else:
        tool, args = "kubectl", ["apply", "-f"]
    if shutil.which(tool) is None:
        raise RuntimeError(
            f"{tool} not found on PATH; rendered specs are in "
            f"{os.path.dirname(paths[0])} for manual submission"
        )
    for path in paths:
        subprocess.run([tool, *args, path], check=True)
        logger.info("submitted %s", path)


# The reference templates protocol timeouts into every replica-group job
# (``torchft/examples/slurm/runner.py:83-89``): quorum timeout must dwarf the
# step time (it is the rejoin window), per-op timeout must stay under it so a
# wedged collective aborts before the quorum gives up on the group.
TIMEOUT_ENV_TEMPLATE: Dict[str, str] = {
    "TORCHFT_QUORUM_TIMEOUT_SEC": "900",
    "TORCHFT_TIMEOUT_SEC": "600",
    "TORCHFT_CONNECT_TIMEOUT_SEC": "60",
}


class SlurmCli:
    """Thin sbatch/squeue shim (injectable for tests)."""

    def submit(self, path: str) -> str:
        out = subprocess.run(
            ["sbatch", "--parsable", path],
            check=True,
            capture_output=True,
            text=True,
        ).stdout.strip()
        return out.split(";")[0]  # "<jobid>[;cluster]"

    def state(self, job_id: str) -> str:
        """"RUNNING"/"PENDING"/... or "DEAD" when the queue no longer knows
        the job (finished, failed, or preempted past requeue)."""
        proc = subprocess.run(
            ["squeue", "-h", "-j", job_id, "-o", "%T"],
            capture_output=True,
            text=True,
        )
        state = proc.stdout.strip().splitlines()
        if proc.returncode != 0 or not state:
            return "DEAD"
        return state[0]


class GkeCli:
    """kubectl shim: job name == manifest metadata.name (the render names
    them deterministically)."""

    def __init__(self, namespace: str = "default") -> None:
        self.namespace = namespace

    def submit(self, path: str) -> str:
        name = os.path.splitext(os.path.basename(path))[0]
        # delete-then-apply: a completed/failed Job of the same name blocks
        # resubmission (Jobs are immutable)
        subprocess.run(
            [
                "kubectl", "delete", "job", name,
                "-n", self.namespace, "--ignore-not-found",
            ],
            check=True,
            capture_output=True,
        )
        subprocess.run(
            ["kubectl", "apply", "-f", path], check=True, capture_output=True
        )
        return name

    def state(self, job_id: str) -> str:
        proc = subprocess.run(
            [
                "kubectl", "get", "job", job_id,
                "-n", self.namespace,
                "-o",
                "jsonpath={.status.active},{.status.failed},{.status.succeeded}",
            ],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            return "DEAD"
        parts = (proc.stdout.strip().split(",") + ["", ""])[:3]
        active, failed, succeeded = parts
        if active not in ("", "0"):
            return "RUNNING"
        # a finished Job — failed OR exited 0 (e.g. node drain SIGTERM) —
        # reads DEAD either way: FT training groups run until the whole job
        # ends, so "completed" mid-watch means the group left the fleet
        # (same semantics as SlurmCli, where a job absent from squeue is
        # DEAD regardless of exit code)
        if failed not in ("", "0") or succeeded not in ("", "0"):
            return "DEAD"
        return "PENDING"


@dataclass
class _WatchedGroup:
    rid: int
    path: str
    job_id: Optional[str] = None
    relaunches: int = 0
    backoff_s: float = 0.0
    not_before: float = 0.0  # monotonic gate for the next (re)launch
    launched_at: float = 0.0  # when the current incarnation was submitted
    running_since: float = 0.0  # first observed RUNNING (0 = not yet seen)
    gave_up: bool = False  # out of relaunch budget; no longer polled


class Watcher:
    """Launch + monitor + relaunch replica-group jobs — the other half of
    the reference's SLURM runner (``torchft/examples/slurm/runner.py:120-221``,
    Monarch does the same actor-style).  Each group is an independent
    failure domain: a dead job is resubmitted with per-group exponential
    backoff while the surviving groups keep training; the rejoined group
    heals from a live peer at its next quorum.

    ``backend`` needs only ``submit(path) -> job_id`` and
    ``state(job_id) -> str`` ("DEAD" meaning gone); tests inject fakes,
    deployments use :class:`SlurmCli` / :class:`GkeCli`.
    """

    def __init__(
        self,
        paths: List[str],
        backend,
        poll_s: float = 10.0,
        initial_backoff_s: float = 5.0,
        max_backoff_s: float = 300.0,
        max_relaunches: Optional[int] = None,
        healthy_reset_s: float = 600.0,
        clock=None,
        sleep=None,
    ) -> None:
        import time

        self._groups = [
            _WatchedGroup(rid=i, path=p) for i, p in enumerate(paths)
        ]
        self._backend = backend
        self._poll_s = poll_s
        self._initial_backoff_s = initial_backoff_s
        self._max_backoff_s = max_backoff_s
        self._max_relaunches = max_relaunches
        self._healthy_reset_s = healthy_reset_s
        self._clock = clock or time.monotonic
        self._sleep = sleep or time.sleep
        self._stop = False

    @property
    def groups(self) -> List[_WatchedGroup]:
        return self._groups

    def stop(self) -> None:
        self._stop = True

    def _submit(self, g: _WatchedGroup) -> bool:
        """Submit one group; a transient scheduler failure (slurmctld
        failover, apiserver blip) must never kill the watch loop — the
        group retries after its backoff."""
        try:
            g.job_id = self._backend.submit(g.path)
        except Exception as e:  # noqa: BLE001
            g.backoff_s = min(
                self._max_backoff_s,
                g.backoff_s * 2 if g.backoff_s else self._initial_backoff_s,
            )
            g.not_before = self._clock() + g.backoff_s
            logger.warning(
                "replica group %d submit failed (%s); retrying in %.0fs",
                g.rid,
                e,
                g.backoff_s,
            )
            return False
        g.launched_at = self._clock()
        return True

    def launch_all(self) -> None:
        for g in self._groups:
            if self._submit(g):
                logger.info(
                    "replica group %d submitted as %s", g.rid, g.job_id
                )

    def poll_once(self) -> int:
        """One monitoring pass; returns how many groups are currently being
        relaunched/backed off (0 = everything alive or given up)."""
        pending = 0
        now = self._clock()
        for g in self._groups:
            if g.gave_up:
                continue
            if g.job_id is not None:
                state = self._backend.state(g.job_id)
                if state != "DEAD":
                    # an incarnation that survived a long RUNNING stretch
                    # earns a fresh backoff (crash loops keep ratcheting; a
                    # job dying after days must not wait minutes to respawn).
                    # PENDING time doesn't count — a job stuck in the queue
                    # never ran, so it proved nothing about stability
                    if state == "RUNNING" and g.running_since == 0.0:
                        g.running_since = now
                    elif state != "RUNNING":
                        g.running_since = 0.0
                    if (
                        g.backoff_s
                        and g.running_since
                        and now - g.running_since > self._healthy_reset_s
                    ):
                        g.backoff_s = 0.0
                    continue
                g.running_since = 0.0
                # job vanished: schedule a relaunch with backoff
                if (
                    self._max_relaunches is not None
                    and g.relaunches >= self._max_relaunches
                ):
                    logger.error(
                        "replica group %d (%s) dead and out of relaunches; "
                        "giving up on it",
                        g.rid,
                        g.job_id,
                    )
                    g.job_id = None
                    g.gave_up = True
                    continue
                g.backoff_s = min(
                    self._max_backoff_s,
                    g.backoff_s * 2 if g.backoff_s else self._initial_backoff_s,
                )
                g.not_before = now + g.backoff_s
                logger.warning(
                    "replica group %d (%s) died; relaunching in %.0fs",
                    g.rid,
                    g.job_id,
                    g.backoff_s,
                )
                g.job_id = None
            if g.job_id is None:
                pending += 1
                if now >= g.not_before and self._submit(g):
                    g.relaunches += 1
                    logger.info(
                        "replica group %d relaunched as %s (restart %d)",
                        g.rid,
                        g.job_id,
                        g.relaunches,
                    )
        return pending

    def run(self) -> int:
        """Block, monitoring until :meth:`stop` or until every group has
        permanently given up (deployments run this in the foreground the way
        the reference runner does).  Returns how many groups gave up — 0 is
        a clean stop, nonzero means the fleet died for good."""
        self.launch_all()
        while not self._stop:
            self.poll_once()
            if all(g.gave_up for g in self._groups):
                logger.error(
                    "every replica group is out of relaunches; watch loop "
                    "exiting"
                )
                break
            self._sleep(self._poll_s)
        return sum(1 for g in self._groups if g.gave_up)


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        "torchft_tpu.scheduler",
        description="Render (and optionally submit) FT replica-group jobs "
        "to a cluster scheduler.",
    )
    parser.add_argument("backend", choices=["slurm", "gke"])
    parser.add_argument("--replicas", type=int, required=True)
    parser.add_argument("--lighthouse", required=True)
    parser.add_argument("--job-name", default="torchft-tpu")
    parser.add_argument("--out-dir", default="jobs")
    parser.add_argument("--partition", default=None)
    parser.add_argument("--nodes-per-replica", type=int, default=1)
    parser.add_argument("--time-limit", default="24:00:00")
    parser.add_argument("--max-restarts", type=int, default=10)
    parser.add_argument("--image", default="python:3.12")
    parser.add_argument("--tpu-accelerator", default="tpu-v5p-slice")
    parser.add_argument("--tpu-topology", default="2x2x1")
    parser.add_argument("--tpu-chips", type=int, default=4)
    parser.add_argument("--namespace", default="default")
    parser.add_argument(
        "--env",
        action="append",
        default=[],
        metavar="K=V",
        help="extra env var for every replica group (repeatable)",
    )
    parser.add_argument("--submit", action="store_true")
    parser.add_argument(
        "--watch",
        action="store_true",
        help="after submitting, monitor job state and relaunch dead replica "
        "groups with backoff (implies --submit)",
    )
    parser.add_argument("--poll-s", type=float, default=10.0)
    parser.add_argument(
        "--max-relaunches",
        type=int,
        default=None,
        help="per-group relaunch budget for --watch (default: unlimited)",
    )
    parser.add_argument(
        "--no-timeout-env",
        action="store_true",
        help="skip templating the TORCHFT_*_TIMEOUT_SEC doctrine into jobs",
    )
    # split at "--" before argparse: REMAINDER after a positional swallows
    # the option flags too
    raw = list(sys.argv[1:] if argv is None else argv)
    cmd: List[str] = []
    if "--" in raw:
        split = raw.index("--")
        raw, cmd = raw[:split], raw[split + 1 :]
    args = parser.parse_args(raw)
    logging.basicConfig(level=logging.INFO)

    if not cmd:
        parser.error("training command required after --")

    env = {} if args.no_timeout_env else dict(TIMEOUT_ENV_TEMPLATE)
    for kv in args.env:
        k, _, v = kv.partition("=")
        env[k] = v

    spec = JobSpec(
        replicas=args.replicas,
        cmd=cmd,
        lighthouse=args.lighthouse,
        job_name=args.job_name,
        env=env,
        partition=args.partition,
        nodes_per_replica=args.nodes_per_replica,
        time_limit=args.time_limit,
        max_restarts=args.max_restarts,
        image=args.image,
        tpu_accelerator=args.tpu_accelerator,
        tpu_topology=args.tpu_topology,
        tpu_chips=args.tpu_chips,
        namespace=args.namespace,
    )
    rendered = (
        render_sbatch(spec) if args.backend == "slurm" else render_gke(spec)
    )
    paths = write_specs(rendered, args.out_dir)
    for p in paths:
        print(p)
    if args.watch:
        backend = (
            SlurmCli() if args.backend == "slurm" else GkeCli(args.namespace)
        )
        tool = "sbatch" if args.backend == "slurm" else "kubectl"
        if shutil.which(tool) is None:
            raise RuntimeError(f"--watch needs {tool} on PATH")
        watcher = Watcher(
            paths,
            backend,
            poll_s=args.poll_s,
            max_relaunches=args.max_relaunches,
        )
        try:
            gave_up = watcher.run()
        except KeyboardInterrupt:
            watcher.stop()
        else:
            if gave_up:
                sys.exit(1)
    elif args.submit:
        submit(args.backend, paths)


if __name__ == "__main__":
    main()
