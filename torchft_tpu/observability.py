"""Observability: structured event logs + per-quorum profiler traces.

Reference analogs:

- ``torchft/otel.py``: opt-in structured loggers ``torchft_quorums`` /
  ``torchft_commits`` / ``torchft_errors`` with job/replica/rank/quorum/step
  attributes, exported over OTLP.  The Manager already emits to those logger
  names; this module attaches exporters.  OTLP is used when the
  ``opentelemetry`` SDK is importable; otherwise events are emitted as JSON
  lines (console or ``TORCHFT_LOG_DIR`` files) — same schema, greppable.
- ``torch.profiler.record_function`` spans on every protocol phase
  (``manager.py:410`` etc.) → :func:`record_function` using jax's profiler
  trace annotations.
- Per-quorum NCCL flight-recorder dirs (``manager.py:815-824``) →
  :class:`QuorumTracer`: with ``TORCHFT_TRACE_DIR`` set, each quorum epoch
  gets its own jax profiler trace directory ``quorum_{id}/``, so the
  post-mortem for a failed epoch is isolated exactly like an FR dump.

Everything is opt-in via env (``TORCHFT_USE_OTEL``, ``TORCHFT_LOG_DIR``,
``TORCHFT_TRACE_DIR``); the default is zero overhead.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import sys
import threading
import time
from typing import Iterator, Optional

USE_OTEL_ENV = "TORCHFT_USE_OTEL"
LOG_DIR_ENV = "TORCHFT_LOG_DIR"
TRACE_DIR_ENV = "TORCHFT_TRACE_DIR"

STRUCTURED_LOGGERS = (
    "torchft_quorums",
    "torchft_commits",
    "torchft_errors",
    "torchft_heals",
    # flight-recorder dump announcements (obs/flight.py): one record per
    # dump with the trigger reason, event counts and the artifact path
    "torchft_flight",
)

_ATTR_KEYS = (
    "job_id",
    "replica_id",
    "rank",
    "quorum_id",
    "step",
    "commit_result",
    "error",
    # data-plane lane counters (torchft_quorums; per-epoch, from
    # Communicator.lane_stats() at quorum change — multi-lane ring striping)
    "comm_lanes",
    "comm_lane_tx_bytes",
    "comm_lane_rx_bytes",
    "comm_lane_stalls",
    # gray-failure counters (torchft_quorums; in-epoch lane recovery +
    # fault injection of the outgoing epoch)
    "comm_lane_reconnects",
    "comm_lane_failovers",
    "comm_injected_faults",
    # hierarchical-topology counters (torchft_quorums; host grouping +
    # shared-memory transport bytes of the outgoing epoch)
    "comm_topo_hosts",
    "comm_topo_local_world",
    "comm_shm_bytes",
    # sharded-outer-sync pipeline timings (torchft_quorums; most recent
    # DiLoCo sharded sync of the outgoing epoch — scatter/update/gather
    # wall shares and how much of the outer update the pipeline hid)
    "outer_shard_scatter_s",
    "outer_shard_update_s",
    "outer_shard_gather_s",
    "outer_shard_wall_s",
    "outer_shard_overlap_ratio",
    # coordination-plane counters (torchft_quorums; how this replica's
    # heartbeats routed — zone aggregator vs direct lighthouse — and how
    # often it fell back on aggregator death)
    "coord_beats_via_agg",
    "coord_beats_direct",
    "coord_agg_fallbacks",
    # heal-path counters (torchft_heals; striped checkpoint recovery)
    "heal_bytes",
    "heal_duration_s",
    "heal_bytes_per_sec",
    "heal_num_sources",
    "heal_failed_sources",
    "heal_stolen_chunks",
    "heal_per_source_bytes",
    # flight-recorder dump facts (torchft_flight; obs/flight.py dump())
    "flight_reason",
    "flight_events",
    "flight_native_events",
    "flight_path",
)

_initialized = False
_init_lock = threading.Lock()


class _JsonLinesFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        event = {
            "ts": round(time.time(), 3),
            "event": record.name,
        }
        for key in _ATTR_KEYS:
            if hasattr(record, key):
                event[key] = getattr(record, key)
        return json.dumps(event)


def init_structured_logging(force: bool = False) -> bool:
    """Attach exporters to the structured loggers (idempotent).

    Returns True when exporters were attached (env opted in or ``force``).
    """
    global _initialized
    with _init_lock:
        if _initialized:
            return True
        opted_in = force or os.environ.get(USE_OTEL_ENV, "").lower() in (
            "1",
            "true",
        ) or bool(os.environ.get(LOG_DIR_ENV))
        if not opted_in:
            return False

        handlers: list[logging.Handler] = []
        log_dir = os.environ.get(LOG_DIR_ENV)
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)

        try:  # OTLP when the SDK exists (not baked into this environment)
            from opentelemetry._logs import set_logger_provider  # type: ignore[import-not-found]
            from opentelemetry.exporter.otlp.proto.grpc._log_exporter import (  # type: ignore[import-not-found]
                OTLPLogExporter,
            )
            from opentelemetry.sdk._logs import (  # type: ignore[import-not-found]
                LoggerProvider,
                LoggingHandler,
            )
            from opentelemetry.sdk._logs.export import (  # type: ignore[import-not-found]
                BatchLogRecordProcessor,
            )

            provider = LoggerProvider()
            provider.add_log_record_processor(
                BatchLogRecordProcessor(OTLPLogExporter())
            )
            set_logger_provider(provider)
            handlers.append(LoggingHandler(logger_provider=provider))
        except ImportError:
            pass

        for name in STRUCTURED_LOGGERS:
            logger = logging.getLogger(name)
            logger.setLevel(logging.INFO)
            logger.propagate = False
            if log_dir:
                fh = logging.FileHandler(os.path.join(log_dir, f"{name}.jsonl"))
                fh.setFormatter(_JsonLinesFormatter())
                logger.addHandler(fh)
            else:
                sh = logging.StreamHandler(sys.stderr)
                sh.setFormatter(_JsonLinesFormatter())
                logger.addHandler(sh)
            for h in handlers:
                logger.addHandler(h)
        _initialized = True
        return True


@dataclasses.dataclass
class HealMetrics:
    """Throughput/latency facts of one checkpoint heal, filled by the
    transport (``last_heal_metrics``) and logged by the manager to the
    ``torchft_heals`` structured logger.

    ``per_source_bytes`` is keyed by source id (replica rank or metadata
    URL); ``failed_sources`` lists sources that died or errored mid-heal;
    ``stolen_chunks`` counts chunk reassignments to a surviving source."""

    step: int = 0
    num_sources: int = 1
    bytes_total: int = 0
    duration_s: float = 0.0
    per_source_bytes: dict = dataclasses.field(default_factory=dict)
    failed_sources: list = dataclasses.field(default_factory=list)
    stolen_chunks: int = 0

    @property
    def bytes_per_sec(self) -> float:
        return self.bytes_total / self.duration_s if self.duration_s > 0 else 0.0

    def as_log_extra(self) -> dict:
        return {
            "step": self.step,
            "heal_bytes": self.bytes_total,
            "heal_duration_s": round(self.duration_s, 4),
            "heal_bytes_per_sec": round(self.bytes_per_sec, 1),
            "heal_num_sources": self.num_sources,
            "heal_failed_sources": list(self.failed_sources),
            "heal_stolen_chunks": self.stolen_chunks,
            "heal_per_source_bytes": dict(self.per_source_bytes),
        }


def log_heal(
    metrics: HealMetrics,
    replica_id: str = "",
    rank: int = 0,
    quorum_id: int = -1,
) -> None:
    """Emit one heal record to ``torchft_heals`` (JSON lines / OTLP when
    structured logging is opted in; free otherwise)."""
    extra = metrics.as_log_extra()
    extra.update(
        job_id=os.environ.get("JOB_ID", "unknown"),
        replica_id=replica_id,
        rank=rank,
        quorum_id=quorum_id,
    )
    logging.getLogger("torchft_heals").info("", extra=extra)


def traced(name: str):
    """Decorator form of :func:`record_function` for whole protocol verbs."""

    def _wrap(fn):
        import functools

        @functools.wraps(fn)
        def _inner(*args, **kwargs):
            with record_function(name):
                return fn(*args, **kwargs)

        return _inner

    return _wrap


@contextlib.contextmanager
def record_function(name: str) -> Iterator[None]:
    """Protocol-phase span (``torch.profiler.record_function`` analog): shows
    up in jax profiler traces as a named annotation; free when no trace is
    being captured."""
    # resolve the annotation class BEFORE entering the body so an
    # ImportError raised by the wrapped code is never swallowed here
    try:
        from jax.profiler import TraceAnnotation
    except ImportError:  # pragma: no cover
        TraceAnnotation = None
    if TraceAnnotation is None:  # pragma: no cover
        yield
    else:
        with TraceAnnotation(name):
            yield


class QuorumTracer:
    """Per-quorum-epoch jax profiler traces (flight-recorder analog).

    With ``TORCHFT_TRACE_DIR`` set, call ``on_quorum_change(quorum_id)`` from
    the manager at each reconfiguration: the previous epoch's trace is closed
    and a fresh one starts under ``{dir}/quorum_{id}``.
    """

    def __init__(self, base_dir: Optional[str] = None) -> None:
        self._base_dir = base_dir or os.environ.get(TRACE_DIR_ENV)
        self._active = False
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self._base_dir)

    def on_quorum_change(self, quorum_id: int) -> None:
        if not self.enabled:
            return
        import jax.profiler

        with self._lock:
            if self._active:
                try:
                    jax.profiler.stop_trace()
                except RuntimeError:
                    pass
                self._active = False
            path = os.path.join(self._base_dir, f"quorum_{quorum_id}")
            os.makedirs(path, exist_ok=True)
            try:
                jax.profiler.start_trace(path)
                self._active = True
            except RuntimeError:
                pass

    def stop(self) -> None:
        if not self.enabled:
            return
        import jax.profiler

        with self._lock:
            if self._active:
                try:
                    jax.profiler.stop_trace()
                except RuntimeError:
                    pass
                self._active = False
