"""Durable (disk) checkpointing for fault-tolerant jobs.

Live peer-to-peer healing covers *replica* loss; durable checkpoints cover
*job* loss, and per the reference's doctrine they must include the Manager's
own state so step counts stay consistent on restore
(``torchft/manager.py:158-160``, ``train_ddp.py:200-207``).  This helper
bundles user state + ``manager.state_dict()`` into one atomic step directory
using the framework's own streaming pytree serialization (works for any
pytree of jax/numpy arrays; orbax remains a fine alternative for sharded
multi-host arrays).

Usage::

    if manager.current_step() % 100 == 0 and manager.participating_rank() == 0:
        save_checkpoint(ckpt_dir, manager.current_step(),
                        {"model": holder, "torchft": manager.state_dict()})

    # on job restart
    step = latest_step(ckpt_dir)
    if step is not None:
        state = load_checkpoint(ckpt_dir, step)
        holder.update(state["model"])
        manager.load_state_dict(state["torchft"])
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile
from typing import Any, Optional

from torchft_tpu.checkpointing.serialization import load_pytree, save_pytree

_STEP_RE = re.compile(r"^step_(\d+)$")


def _step_dir(base: str, step: int) -> str:
    return os.path.join(base, f"step_{step}")


def save_checkpoint(base_dir: str, step: int, state: Any, keep: int = 3) -> str:
    """Atomically persist ``state`` for ``step``; prunes to ``keep`` newest."""
    os.makedirs(base_dir, exist_ok=True)
    final = _step_dir(base_dir, step)
    tmp = tempfile.mkdtemp(prefix=f".step_{step}_", dir=base_dir)
    try:
        with open(os.path.join(tmp, "state.tftc"), "wb") as f:
            save_pytree(state, f)
            # durable means surviving power loss: flush the file and the
            # directory entries before the rename is considered committed
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)  # atomic on the same filesystem
        dir_fd = os.open(base_dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise

    if keep > 0:
        steps = sorted(_all_steps(base_dir))
        for old in steps[:-keep]:
            shutil.rmtree(_step_dir(base_dir, old), ignore_errors=True)
    return final


def _all_steps(base_dir: str) -> list:
    out = []
    try:
        entries = os.listdir(base_dir)
    except FileNotFoundError:
        return out
    for entry in entries:
        match = _STEP_RE.match(entry)
        if match and os.path.exists(
            os.path.join(base_dir, entry, "state.tftc")
        ):
            out.append(int(match.group(1)))
    return out


def latest_step(base_dir: str) -> Optional[int]:
    steps = _all_steps(base_dir)
    return max(steps) if steps else None


def load_checkpoint(base_dir: str, step: int) -> Any:
    with open(os.path.join(_step_dir(base_dir, step), "state.tftc"), "rb") as f:
        return load_pytree(f)


def load_latest(base_dir: str) -> Optional[tuple]:
    """(step, state) of the newest *readable* checkpoint, falling back past
    torn/corrupt step dirs; None when nothing restorable exists."""
    for step in sorted(_all_steps(base_dir), reverse=True):
        try:
            return step, load_checkpoint(base_dir, step)
        except Exception:  # noqa: BLE001 — torn write; try the next older
            continue
    return None
