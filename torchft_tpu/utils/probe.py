"""Backend health probe, shared by bench.py and __graft_entry__.py.

Under the axon debug tunnel ``jax.devices()`` can succeed while execution
wedges, and a wedged backend hangs ANY in-process jax call forever — so
the probe (a) runs in a subprocess with a timeout, and (b) round-trips one
tiny computation to host rather than just enumerating devices.
"""

from __future__ import annotations

import subprocess
import sys
import time
from typing import Optional

_PROBE_SRC = (
    "import jax, numpy; "
    "x = jax.numpy.ones((8, 8)); "
    "assert numpy.asarray(x @ x)[0, 0] == 8.0"
)

_CACHE: dict = {}


def backend_executes(
    timeout_s: float = 180.0, use_cache: bool = True
) -> bool:
    """True when the default jax backend initializes AND executes.  The
    result is memoized per process (it depends only on env/tunnel state,
    and a wedged probe costs the full timeout every time)."""
    if use_cache and "ok" in _CACHE:
        return _CACHE["ok"]
    try:
        probe = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            timeout=timeout_s,
            capture_output=True,
        )
        ok = probe.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    _CACHE["ok"] = ok
    return ok


def backend_executes_with_retries(
    window_s: float,
    timeout_s: float = 180.0,
    log=None,
) -> bool:
    """Retry :func:`backend_executes` within a bounded window — the tunnel
    wedges transiently, and a single failed probe must not silently
    downgrade a long measurement run to CPU."""
    deadline = time.time() + window_s
    attempt = 0
    while True:
        attempt += 1
        t0 = time.time()
        if backend_executes(timeout_s, use_cache=False):
            _CACHE["ok"] = True
            if attempt > 1 and log:
                log(f"backend probe succeeded on attempt {attempt}")
            return True
        if time.time() >= deadline:
            _CACHE["ok"] = False
            return False
        wait: Optional[float] = min(
            30.0, max(5.0, deadline - time.time())
        )
        if log:
            log(
                f"backend probe attempt {attempt} failed after "
                f"{time.time() - t0:.0f}s; retrying in {wait:.0f}s "
                f"({deadline - time.time():.0f}s left in retry window)"
            )
        if time.time() + wait >= deadline:
            wait = max(0.0, deadline - time.time())
        time.sleep(wait)
