"""Utilities: durable checkpointing, misc helpers."""

_LAZY = {
    "save_checkpoint": ("torchft_tpu.utils.checkpoint", "save_checkpoint"),
    "load_checkpoint": ("torchft_tpu.utils.checkpoint", "load_checkpoint"),
    "latest_step": ("torchft_tpu.utils.checkpoint", "latest_step"),
}

__all__ = list(_LAZY)


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)
