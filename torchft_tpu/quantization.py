"""Rowwise int8 quantization for bandwidth-reduced collectives.

The reference fuses fp8 quantize/dequantize/reduce into triton kernels
(``torchft/quantization.py:44-686``, CUDA-only).  torchft_tpu's replica-dim
collectives run host-side over DCN, so the wire format lives here as
vectorized numpy; the device-side (Pallas) quantize kernel that reduces
HBM→host transfer bytes lives in ``torchft_tpu/ops/``.

Wire format per buffer: the flat array is viewed as rows of ``row_size``
elements (last row padded); each row is scaled by ``max(|row|)/127`` into
int8.  Scales travel as float32 alongside the payload, mirroring the
reference's interleaved rowwise-scale layout.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

DEFAULT_ROW_SIZE = 1024


def quantize_int8_rowwise(
    flat: np.ndarray, row_size: int = DEFAULT_ROW_SIZE
) -> Tuple[np.ndarray, np.ndarray]:
    """Quantize a flat float array → (int8 payload [rows, row_size],
    float32 scales [rows]). The payload is padded to a whole row."""
    assert flat.ndim == 1
    n = flat.size
    rows = max(1, -(-n // row_size))
    padded = np.zeros(rows * row_size, dtype=np.float32)
    padded[:n] = flat.astype(np.float32, copy=False)
    padded = padded.reshape(rows, row_size)
    absmax = np.abs(padded).max(axis=1)
    scales = (absmax / 127.0).astype(np.float32)
    safe = np.where(scales > 0, scales, 1.0)
    q = np.clip(np.rint(padded / safe[:, None]), -127, 127).astype(np.int8)
    return q, scales


def dequantize_int8_rowwise(
    q: np.ndarray, scales: np.ndarray, n: int, dtype: np.dtype
) -> np.ndarray:
    """Inverse of :func:`quantize_int8_rowwise`, truncated to ``n``."""
    out = (q.astype(np.float32) * scales[:, None]).reshape(-1)[:n]
    return out.astype(dtype, copy=False)


def reduce_quantized(
    qs: np.ndarray, scales: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Sum ``w`` quantized copies: qs [w, rows, row_size], scales [w, rows]
    → requantized (q [rows, row_size], scales [rows]) of the float sum.

    The accumulate happens in float32 (the analog of the reference's
    ``fused_reduce_fp8`` dequant-sum-requant, ``quantization.py:638``).
    """
    total = (qs.astype(np.float32) * scales[:, :, None]).sum(axis=0)
    absmax = np.abs(total).max(axis=1)
    out_scales = (absmax / 127.0).astype(np.float32)
    safe = np.where(out_scales > 0, out_scales, 1.0)
    q = np.clip(np.rint(total / safe[:, None]), -127, 127).astype(np.int8)
    return q, out_scales
