"""Rowwise quantization (int8 / fp8) for bandwidth-reduced collectives.

The reference fuses fp8 quantize/dequantize/reduce into triton kernels
(``torchft/quantization.py:44-686``, CUDA-only).  torchft_tpu's replica-dim
collectives run host-side over DCN, so the wire format lives here as
vectorized numpy; the device-side (Pallas) quantize/reduce kernels that cut
HBM→host transfer bytes live in ``torchft_tpu/ops/``.

Wire format per buffer: the flat array is viewed as rows of ``row_size``
elements (last row padded); each row is scaled by ``max(|row|)/Q`` into the
wire dtype — int8 (Q=127) or float8_e4m3 (Q=448, the reference's format,
via ml_dtypes).  Scales travel as float32 alongside the payload, mirroring
the reference's interleaved rowwise-scale layout.  Both formats are one
byte/element; fp8 trades the int8 grid's uniform spacing for more dynamic
range within a row.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

DEFAULT_ROW_SIZE = 1024

# wire dtypes: name -> (numpy dtype, max representable magnitude)
try:  # ml_dtypes ships with jax
    import ml_dtypes

    _FP8 = np.dtype(ml_dtypes.float8_e4m3fn)
    FP8_MAX = 448.0
except ImportError:  # pragma: no cover - ml_dtypes is a jax dependency
    _FP8 = None
    FP8_MAX = 448.0

INT8 = "int8"
FP8 = "fp8"


def quant_kind() -> str:
    """The configured wire format for quantized collectives:
    ``TORCHFT_QUANT_KIND`` = ``int8`` (default) or ``fp8`` (e4m3, the
    reference's format).  Raises on anything else — callers that construct
    long-lived objects (the Manager) validate at startup so a typo fails
    fast instead of silently discarding every step through the error
    funnel."""
    kind = os.environ.get("TORCHFT_QUANT_KIND", INT8).strip().lower()
    if kind not in (INT8, FP8):
        raise ValueError(
            f"TORCHFT_QUANT_KIND={kind!r}: must be {INT8!r} or {FP8!r}"
        )
    return kind


def wire_dtype(kind: str) -> np.dtype:
    if kind == INT8:
        return np.dtype(np.int8)
    if kind == FP8:
        if _FP8 is None:
            raise RuntimeError("fp8 wire format requires ml_dtypes")
        return _FP8
    raise ValueError(f"unknown wire dtype {kind!r}")


def _wire_max(kind: str) -> float:
    return 127.0 if kind == INT8 else FP8_MAX


def _native_kernels():
    """The C++ host kernels (native/quant.h) when the native runtime built;
    resolved lazily and cached (None entries mean 'fall back to numpy')."""
    global _NATIVE
    if _NATIVE is _UNRESOLVED:
        try:
            from torchft_tpu import native

            if native.available():
                _NATIVE = native
            else:
                _NATIVE = None
        except Exception:  # pragma: no cover - import/build failure
            _NATIVE = None
    return _NATIVE


_UNRESOLVED = object()
_NATIVE = _UNRESOLVED


def quantize_rowwise(
    flat: np.ndarray, row_size: int = DEFAULT_ROW_SIZE, kind: str = INT8
) -> Tuple[np.ndarray, np.ndarray]:
    """Quantize a flat float array → (1-byte payload [rows, row_size],
    float32 scales [rows]). The payload is padded to a whole row."""
    assert flat.ndim == 1
    if kind == INT8:
        native = _native_kernels()
        if native is not None:
            out = native.quantize_rowwise_native(flat, row_size)
            if out is not None:
                return out
    n = flat.size
    rows = max(1, -(-n // row_size))
    padded = np.zeros(rows * row_size, dtype=np.float32)
    padded[:n] = flat.astype(np.float32, copy=False)
    padded = padded.reshape(rows, row_size)
    qmax = _wire_max(kind)
    absmax = np.abs(padded).max(axis=1)
    scales = (absmax / qmax).astype(np.float32)
    safe = np.where(scales > 0, scales, 1.0)
    scaled = padded / safe[:, None]
    if kind == INT8:
        q = np.clip(np.rint(scaled), -127, 127).astype(np.int8)
    else:
        q = np.clip(scaled, -qmax, qmax).astype(wire_dtype(kind))
    return q, scales


def dequantize_rowwise(
    q: np.ndarray, scales: np.ndarray, n: int, dtype: np.dtype
) -> np.ndarray:
    """Inverse of :func:`quantize_rowwise`, truncated to ``n`` (dtype of
    ``q`` distinguishes the wire format)."""
    if q.dtype == np.int8 and dtype == np.float32:
        native = _native_kernels()
        if native is not None:
            out = native.dequantize_rowwise_native(q, scales, n)
            if out is not None:
                return out
    out = (q.astype(np.float32) * scales[:, None]).reshape(-1)[:n]
    return out.astype(dtype, copy=False)


def reduce_quantized(
    qs: np.ndarray, scales: np.ndarray, kind: str = INT8
) -> Tuple[np.ndarray, np.ndarray]:
    """Sum ``w`` quantized copies: qs [w, rows, row_size], scales [w, rows]
    → requantized (q [rows, row_size], scales [rows]) of the float sum.

    The accumulate happens in float32 (the analog of the reference's
    ``fused_reduce_fp8`` dequant-sum-requant, ``quantization.py:638``); the
    device-resident twin is ``ops.pallas_quant.reduce_quantized_device``.
    """
    if kind == INT8 and qs.dtype == np.int8:
        native = _native_kernels()
        if native is not None:
            out = native.reduce_rowwise_native(qs, scales)
            if out is not None:
                return out
    total = (qs.astype(np.float32) * scales[:, :, None]).sum(axis=0)
    qmax = _wire_max(kind)
    absmax = np.abs(total).max(axis=1)
    out_scales = (absmax / qmax).astype(np.float32)
    safe = np.where(out_scales > 0, out_scales, 1.0)
    scaled = total / safe[:, None]
    if kind == INT8:
        q = np.clip(np.rint(scaled), -127, 127).astype(np.int8)
    else:
        q = np.clip(scaled, -qmax, qmax).astype(wire_dtype(kind))
    return q, out_scales


# backwards-compatible int8-named surface (round-1 API)
def quantize_int8_rowwise(
    flat: np.ndarray, row_size: int = DEFAULT_ROW_SIZE
) -> Tuple[np.ndarray, np.ndarray]:
    return quantize_rowwise(flat, row_size, INT8)


def dequantize_int8_rowwise(
    q: np.ndarray, scales: np.ndarray, n: int, dtype: np.dtype
) -> np.ndarray:
    return dequantize_rowwise(q, scales, n, dtype)
