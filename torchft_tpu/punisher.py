"""Chaos injection tool: kill replicas of a running FT job.

The reference's analog lives in ``torchft/examples/slurm/punisher.py``
(kill_one / kill_all / kill_loop against SLURM jobs) and the lighthouse
dashboard's kill button.  This tool speaks to the lighthouse: it reads the
current quorum membership and delivers Kill RPCs to replica managers — so it
works against any deployment (local launcher, TPU-VM fleet) without
scheduler integration.

CLI::

    python -m torchft_tpu.punisher --lighthouse host:port kill-one
    python -m torchft_tpu.punisher --lighthouse host:port kill-loop --mtbf-secs 60
"""

from __future__ import annotations

import argparse
import logging
import random
import time
from typing import List, Optional

from torchft_tpu.lighthouse import LighthouseClient
from torchft_tpu.manager_server import ManagerClient

logger = logging.getLogger("torchft_tpu.punisher")


def _members(client: LighthouseClient) -> List[dict]:
    status = client.status()
    return status.get("participants", [])


def kill_replica(address: str, msg: str = "killed by punisher") -> bool:
    try:
        mgr = ManagerClient(address, connect_timeout=10.0)
        mgr.kill(msg)
        mgr.close()
        return True
    except Exception as e:  # noqa: BLE001 — the process dying mid-rpc is success
        logger.info("kill rpc to %s ended with %s (process likely died)", address, e)
        return True


def kill_one(client: LighthouseClient, rng: random.Random) -> Optional[str]:
    members = _members(client)
    if not members:
        logger.warning("no quorum members to kill")
        return None
    victim = rng.choice(members)
    logger.info("killing %s at %s", victim["replica_id"], victim["address"])
    kill_replica(victim["address"])
    return victim["replica_id"]


def kill_all(client: LighthouseClient) -> int:
    members = _members(client)
    for m in members:
        logger.info("killing %s at %s", m["replica_id"], m["address"])
        kill_replica(m["address"])
    return len(members)


def kill_loop(
    client: LighthouseClient, mtbf_secs: float, rng: random.Random
) -> None:
    """Poisson-ish kill loop: one random replica per ~mtbf_secs
    (``punisher.py`` ``kill_loop --mtbf-secs``)."""
    while True:
        wait = rng.expovariate(1.0 / mtbf_secs)
        logger.info("next kill in %.1fs", wait)
        time.sleep(wait)
        kill_one(client, rng)


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser("torchft_tpu.punisher")
    parser.add_argument("--lighthouse", required=True, help="host:port")
    parser.add_argument("--seed", type=int, default=None)
    sub = parser.add_subparsers(dest="command", required=True)
    sub.add_parser("kill-one")
    sub.add_parser("kill-all")
    loop = sub.add_parser("kill-loop")
    loop.add_argument("--mtbf-secs", type=float, default=60.0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = random.Random(args.seed)
    client = LighthouseClient(args.lighthouse, connect_timeout=10.0)
    if args.command == "kill-one":
        kill_one(client, rng)
    elif args.command == "kill-all":
        kill_all(client)
    elif args.command == "kill-loop":
        kill_loop(client, args.mtbf_secs, rng)


if __name__ == "__main__":
    main()
