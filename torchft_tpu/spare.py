"""Hot spares: continuously-warmed standby replicas, sub-second promotion.

PHOENIX (PAPERS.md) shows hot-swap recovery can be near-zero overhead when
standby state is kept continuously warm; the 100k-GPU HSDP report makes the
fleet-scale case: spare capacity that is already caught up turns a failure
from a 6–12 s heal-in (BENCH_r03/r04 ``heal_breakdown``) into a membership
edit.  This module is the SPARE side of that design:

- :class:`WarmChunkStore` — warm channel (b): a per-chunk, crc-watermarked
  cache of an active peer's serialized state dict, filled at idle priority
  over the manager warm RPCs (``MGR_WARM_INDEX``/``MGR_WARM_RANGE``).
  Chunks are keyed at ARRAY-payload granularity
  (``serialization.array_chunk_ranges``) so keys are stable across steps;
  a chunk is re-fetched exactly when its crc moved — "a stale chunk is
  re-fetched rather than trusted" — and partial progress survives quorum
  epochs, source rotation, and source death (resume from the cache).
- :class:`SpareAgent` — the spare replica's state machine: register with
  the lighthouse as ``ROLE_SPARE`` via the manager quorum path, warm on
  both channels (the outer-sync delta feed keeps a DiLoCo shadow bit-exact
  at commit granularity; the chunk store converges the full state dict
  between syncs), and run the promotion handshake when the lighthouse
  moves this replica into the participant set: adopt the promotion quorum
  (``Manager._adopt_quorum`` — no fresh RPC, the actives are already
  parked in mesh rendezvous waiting), flip the role to ACTIVE, and hand
  the caller a manager that is mid-``start_quorum`` of its first active
  step.

The ACTIVE side (staging warm snapshots, publishing committed deltas)
lives in ``manager.py``/``manager_server.py``; a spare is a pure consumer
and a dying or poisoned spare can never stall or fork the active fleet —
every warm RPC is served outside the heal path, the delta feed ring is
bounded, and the fleet's quorum math never counts a spare.

Degraded-mode swaps (wire v5, ``docs/operations.md`` §16): the lighthouse
may promote a spare not only over a DEATH but over a WOUND — a replica
that lost in-replica devices and re-lowered at reduced capacity trades
places with a full-width warm spare in one membership edit
(``TORCHFT_DEGRADED_SWAP``).  Nothing changes on this side: the promotion
handshake below is identical whether the replaced member died or was
swapped out (the spare is seated by the same ``_promote_spares``
computation and adopts the quorum through the same fast path); a spare is
always full-width by construction, so it registers at capacity 1.0 and
its promotion restores the fleet's full data shard.
"""

from __future__ import annotations

import logging
import struct
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from torchft_tpu import knobs
from torchft_tpu.manager import Manager
from torchft_tpu.wire import WireError

logger = logging.getLogger(__name__)

# Pause between warm chunk fetches (idle priority, spare side): keeps the
# warm stream from ever saturating a source's NIC; the source additionally
# yields warm responses to live collectives (ManagerServer.busy_fn).
SPARE_WARM_PACE_MS_ENV = "TORCHFT_SPARE_WARM_PACE_MS"  # default 5
# Per-round warm budget: how long one SpareAgent.step() spends fetching
# chunks before going back to park on the quorum RPC.
SPARE_WARM_BUDGET_S_ENV = "TORCHFT_SPARE_WARM_BUDGET_S"  # default 2.0


def _env_float(env: str, default: float) -> float:
    return knobs.get_float(env, default)


class WarmChunkStore:
    """crc-watermarked chunk cache of one peer's serialized state dict.

    Chunk keys are ``(array_index, lo, hi)`` byte ranges WITHIN each array
    payload (``array_chunk_ranges``) — stable across steps for a fixed
    tree structure, unlike serialized-stream offsets (the pickled header's
    length can drift with the step integer's pickle width).  A chunk's
    watermark is its content crc32: the refresh pass diffs cached crcs
    against the source's index and fetches only movers, so a shadow that
    is mostly warm costs a final delta, not a bulk transfer.
    """

    def __init__(self) -> None:
        self.leaf_nbytes: List[int] = []
        # prefix[i] = sum(leaf_nbytes[:i]) — O(1) stream-offset lookups
        # (a per-chunk O(leaves) sum would make a refresh pass
        # O(chunks x leaves) of pure-Python adds on big trees)
        self._prefix: List[int] = [0]
        self.chunk_target = 0
        self._chunks: Dict[int, Tuple[int, bytes]] = {}  # idx -> (crc, data)
        self._header: Optional[bytes] = None
        self._header_digest = ""
        # cumulative observability (+ how much of the source's index the
        # cache matched on the last refresh — the promotion-cost gauge)
        self.bytes_fetched = 0
        self.chunks_fetched = 0
        self.last_fresh_fraction = 0.0

    def _table(self) -> List[Tuple[int, int, int]]:
        from torchft_tpu.checkpointing.serialization import array_chunk_ranges

        return array_chunk_ranges(self.leaf_nbytes, max(1, self.chunk_target))

    def _stream_offset(self, header_len: int, ai: int, lo: int) -> int:
        # header, then per array: 8-byte length prefix + payload
        return header_len + 8 * (ai + 1) + self._prefix[ai] + lo

    def fresh_fraction(self, hashes: List[int]) -> float:
        if not hashes:
            return 0.0
        fresh = sum(
            1
            for i, h in enumerate(hashes)
            if self._chunks.get(i, (None, b""))[0] == h
        )
        return fresh / len(hashes)

    def refresh(
        self,
        client,
        deadline: float,
        pace_s: float = 0.005,
    ) -> Optional[Tuple[int, object]]:
        """One idle-priority refresh pass against ``client`` (a
        ``ManagerClient``): diff crc watermarks, fetch stale chunks until
        ``deadline``, and — when every chunk matches the source's index —
        assemble and deserialize the full state dict.

        Returns ``(step, state_dict)`` when a complete consistent snapshot
        landed this pass, else None (progress is kept either way).  Raises
        the client's transport errors (the caller rotates sources)."""
        from torchft_tpu.checkpointing.serialization import (
            ViewReader,
            load_pytree,
        )

        index = client.warm_index()
        step = int(index["step"])
        if (
            list(index["leaf_nbytes"]) != self.leaf_nbytes
            or int(index["chunk_target_bytes"]) != self.chunk_target
        ):
            # tree structure (or chunking) changed: every cached watermark
            # is meaningless — start over
            self._chunks.clear()
            self._header = None
            self.leaf_nbytes = [int(n) for n in index["leaf_nbytes"]]
            import itertools

            self._prefix = [0] + list(
                itertools.accumulate(self.leaf_nbytes)
            )
            self.chunk_target = int(index["chunk_target_bytes"])
        hashes = [int(h) for h in index["chunk_hashes"]]
        table = self._table()
        if len(hashes) != len(table):
            raise WireError(3, "warm index chunk table mismatch")

        # the header is small and step-dependent (it pickles the step
        # integer): refetch whenever the digest moved
        header_len = int(index["header_len"])
        if self._header is None or self._header_digest != index["header_digest"]:
            header = client.warm_range(step, 0, header_len)
            self._header = bytes(header)
            self._header_digest = str(index["header_digest"])

        stale = [
            i
            for i, h in enumerate(hashes)
            if self._chunks.get(i, (None, b""))[0] != h
        ]
        for i in stale:
            if time.monotonic() > deadline:
                # budget spent; resume next round
                self.last_fresh_fraction = self.fresh_fraction(hashes)
                return None
            ai, lo, hi = table[i]
            off = self._stream_offset(header_len, ai, lo)
            data = client.warm_range(step, off, off + (hi - lo))
            crc = zlib.crc32(data)
            if crc != hashes[i]:
                # the source restaged between index and range at the SAME
                # step label — impossible by protocol (ranges of a moved
                # snapshot are refused), so treat as corruption and drop
                logger.warning("warm chunk %d crc mismatch; dropped", i)
                continue
            self._chunks[i] = (crc, bytes(data))
            self.bytes_fetched += hi - lo
            self.chunks_fetched += 1
            if pace_s > 0:
                time.sleep(pace_s)

        self.last_fresh_fraction = self.fresh_fraction(hashes)
        if self.last_fresh_fraction < 1.0:
            return None

        # complete + consistent: every chunk crc matches ONE index (one
        # step's staging) — assemble the stream and deserialize
        parts: List[bytes] = [self._header or b""]
        chunk_iter = iter(range(len(table)))
        by_array: Dict[int, List[bytes]] = {}
        for i in chunk_iter:
            ai = table[i][0]
            by_array.setdefault(ai, []).append(self._chunks[i][1])
        for ai, nbytes in enumerate(self.leaf_nbytes):
            parts.append(struct.pack("<Q", nbytes))
            parts.extend(by_array.get(ai, []))
        buf = b"".join(parts)
        state = load_pytree(ViewReader(memoryview(buf)))
        return step, state


class SpareAgent:
    """Drives a ``Manager(role="spare")``: park on the quorum RPC for the
    live membership/commit-front view, warm on both channels between
    rounds, and adopt the promotion quorum when the lighthouse moves this
    replica into the participant set.

    Usage::

        manager = Manager(..., role="spare", use_async_quorum=...)
        agent = SpareAgent(manager, delta_apply=diloco_delta_apply(diloco))
        while not agent.step():
            pass  # warming; agent.metrics has warm_lag_steps etc.
        # promoted: run the normal train loop — the manager is already
        # mid-start_quorum of its first active step (do NOT re-request)

    ``delta_apply(step, frag, payload)`` applies one committed outer-sync
    delta to the caller's shadow (see :func:`diloco_delta_apply`); without
    it the spare warms on the chunk store alone.
    """

    def __init__(
        self,
        manager: Manager,
        delta_apply: Optional[Callable[[int, int, bytes], None]] = None,
    ) -> None:
        if manager.role != "spare":
            raise ValueError("SpareAgent requires Manager(role='spare')")
        self._manager = manager
        self._delta_apply = delta_apply
        self._store = WarmChunkStore()
        self._clients: Dict[str, object] = {}
        self._addresses: List[str] = []
        self._max_step = 0
        self._round = 0
        self._delta_cursor: Tuple[int, int] = (-1, 1 << 60)
        self._loaded_once = False
        # shadow_fresh: True while the delta chain from the last full load
        # is unbroken — a gap (feed ring overrun, missed poll) demotes the
        # shadow to "chunk store only" until the next complete snapshot
        self._shadow_fresh = False
        self.warm_step = -1
        self.promoted = False
        self.metrics: Dict[str, float] = {}

    # -- plumbing ----------------------------------------------------------

    def _client(self, addr: str):
        client = self._clients.get(addr)
        if client is None:
            client = self._manager._peer_client_factory(addr)
            self._clients[addr] = client
        return client

    def _drop_client(self, addr: str) -> None:
        client = self._clients.pop(addr, None)
        if client is not None:
            try:
                client.close()
            except OSError:
                pass

    def close(self) -> None:
        for addr in list(self._clients):
            self._drop_client(addr)

    # -- the spare state machine ------------------------------------------

    def step(self, park_timeout_s: float = 2.0) -> bool:
        """One spare round: park on the quorum RPC (registers this replica
        as a spare and yields the live membership view), then warm until
        the round budget runs out.  Returns True exactly once — when the
        lighthouse promoted this replica and the manager adopted the
        promotion quorum (it is then mid-``start_quorum`` of its first
        active step)."""
        m = self._manager
        result = None
        try:
            result = m._client._quorum(
                group_rank=m._group_rank,
                step=max(0, self.warm_step),
                checkpoint_metadata=m._checkpoint_transport.metadata(),
                shrink_only=False,
                timeout=park_timeout_s,
                init_sync=False,
            )
        except TimeoutError:
            pass  # idle fleet: no quorum activity — warm on cached facts
        except (ConnectionError, OSError, WireError) as e:
            logger.info("spare quorum round failed: %s", e)
            time.sleep(0.1)
            return False

        if result is not None and not result.is_spare:
            self._finalize_promotion(result)
            return True
        if result is not None:
            if result.all_manager_addresses:
                self._addresses = list(result.all_manager_addresses)
            self._max_step = result.max_step
        self._warm()
        return False

    # -- warm channels -----------------------------------------------------

    def _warm(self) -> None:
        if not self._addresses:
            return
        budget = _env_float(SPARE_WARM_BUDGET_S_ENV, 2.0)
        pace = _env_float(SPARE_WARM_PACE_MS_ENV, 5.0) / 1000.0
        deadline = time.monotonic() + budget
        self._poll_deltas()
        # rotate warm sources across rounds (spreads the idle load; a dead
        # source costs one round, the cache resumes against the next)
        addr = self._addresses[self._round % len(self._addresses)]
        self._round += 1
        try:
            loaded = self._store.refresh(
                self._client(addr), deadline=deadline, pace_s=pace
            )
        except (ConnectionError, OSError, TimeoutError) as e:
            logger.info("warm refresh from %s failed: %s", addr, e)
            self._drop_client(addr)
            loaded = None
        except WireError:
            # nothing staged yet (no commit since we registered) — normal
            loaded = None
        if loaded is not None:
            step, state = loaded
            if step > self.warm_step:
                self._load_state(state, step)
        self._export_metrics()

    def _poll_deltas(self) -> None:
        """Warm channel (a): drain the outer-sync delta feed and apply the
        entries in order.  The chain must be gapless from the shadow's
        step — a hole (bounded ring overran us) demotes the shadow until
        the chunk store next converges."""
        if self._delta_apply is None or not self._loaded_once:
            return
        addr = self._addresses[0]
        try:
            entries = self._client(addr).deltas(*self._delta_cursor)
        except (ConnectionError, OSError, TimeoutError, WireError) as e:
            logger.info("delta poll from %s failed: %s", addr, e)
            self._drop_client(addr)
            return
        applied = 0
        for step, frag, payload in entries:
            self._delta_cursor = (step, frag)
            if not self._shadow_fresh:
                continue
            if step != self.warm_step + 1:
                logger.info(
                    "delta chain gap (have step %d, got %d); shadow demoted "
                    "to chunk-store warming",
                    self.warm_step,
                    step,
                )
                self._shadow_fresh = False
                continue
            try:
                self._delta_apply(step, frag, payload)
            except Exception:  # noqa: BLE001 — a bad delta poisons only the
                # SHADOW (refetched from chunks), never the fleet
                logger.exception("delta apply failed; shadow demoted")
                self._shadow_fresh = False
                continue
            self.warm_step = step
            self._manager._step = step
            applied += 1
        if applied:
            self.metrics["warm_deltas_applied"] = (
                self.metrics.get("warm_deltas_applied", 0.0) + applied
            )

    def _load_state(self, state: dict, step: int) -> None:
        """Adopt one complete warm snapshot: apply every registered user
        load fn plus the torchft step facts — the exact load path a heal
        uses, so promotion from here is indistinguishable from a healed
        join."""
        m = self._manager
        user = state.get("user", {})
        with m._state_dict_lock.w_lock():
            for key, load_fn in m._load_state_dict_fns.items():
                if key in user:
                    load_fn(user[key])
        m.load_state_dict(state["torchft"])
        self.warm_step = m._step
        self._loaded_once = True
        self._shadow_fresh = self._delta_apply is not None
        # deltas at or before the snapshot step are already baked in
        self._delta_cursor = (self.warm_step, 1 << 60)
        from torchft_tpu.obs.flight import FlightEvent

        m._flight.record(
            FlightEvent.SPARE_WARM,
            step=self.warm_step,
            lag=max(0, self._max_step - max(0, self.warm_step)),
        )
        logger.info("spare warm snapshot loaded at step %d", self.warm_step)

    def _export_metrics(self) -> None:
        self.metrics.update(
            warm_step=float(self.warm_step),
            warm_lag_steps=float(max(0, self._max_step - max(0, self.warm_step))),
            warm_bytes_fetched=float(self._store.bytes_fetched),
            warm_chunks_fetched=float(self._store.chunks_fetched),
            warm_fresh_fraction=self._store.last_fresh_fraction,
        )
        # spares have no active quorum rounds, so this dict is ours to fill
        self._manager.last_quorum_timings.update(self.metrics)

    # -- promotion ---------------------------------------------------------

    def _finalize_promotion(self, result) -> None:
        """Promotion handshake: adopt the promotion quorum WITHOUT a fresh
        RPC (the actives are already parked in mesh rendezvous waiting for
        this replica), flip the role to ACTIVE, and leave the manager
        mid-``start_quorum`` — the caller's next ``start_quorum()`` is a
        no-op and its step runs under the adopted quorum.  When the warm
        watermark equals the commit front the adopted round has
        ``heal=False``: promotion = quorum adoption + configure, no
        transfer at all; otherwise the standard (striped) heal fetches the
        remainder."""
        m = self._manager
        t0 = time.monotonic()
        m._promote_to_active()
        timings: Dict[str, float] = {}
        m.last_quorum_timings = timings
        timings["promote_warm_lag_steps"] = float(
            max(0, result.max_step - max(0, self.warm_step))
        )
        m._errored = None
        m._healing = False
        with m._pending_works_lock:
            m._pending_works.clear()

        def _stamp_adopt(_fut) -> None:
            # stamped when the adoption (configure + any final heal)
            # actually FINISHES — in async-quorum mode the submit returns
            # immediately, and a promote_s taken there would report
            # microseconds even when a lagging spare runs a striped heal
            timings["promote_s"] = time.monotonic() - t0
            self.metrics["promotion_adopt_s"] = timings["promote_s"]
            logger.warning(
                "spare %s promoted at warm step %d (fleet max_step %d, "
                "adopt %.3fs)",
                m.replica_id,
                self.warm_step,
                result.max_step,
                timings["promote_s"],
            )

        fut = m._executor.submit(m._adopt_quorum, result, True, timings)
        fut.add_done_callback(_stamp_adopt)
        m._quorum_future = fut
        m._adopted_quorum = True
        if not m._use_async_quorum:
            try:
                m.wait_quorum()
            except Exception as e:  # noqa: BLE001 — funnel, never raise
                m.report_error(e)
            else:
                if m._healing:
                    m._apply_pending_state_dict()
                    m._healing = False
        self.metrics.update(
            promote_warm_lag_steps=timings["promote_warm_lag_steps"],
        )
        self.promoted = True


def diloco_delta_apply(diloco) -> Callable[[int, int, bytes], None]:
    """Delta-apply callback for a spare shadowing a DiLoCo fleet: applies
    one committed outer-sync delta to fragment ``frag``'s backup and
    mirrors the globally-consistent params into the holder — byte-for-byte
    the committed-sharded branch of ``_Fragment.perform_sync`` with no
    local mixing (a parked spare has no inner steps, i.e. local == global,
    so the update is exact at ANY alpha)."""
    import jax

    from torchft_tpu.local_sgd import _like_leaf

    def _apply(step: int, frag: int, payload: bytes) -> None:
        f = diloco._fragments[frag]
        delta = np.frombuffer(payload, dtype=np.float32)
        if delta.size != f._n:
            raise ValueError(
                f"delta for fragment {frag} has {delta.size} elements, "
                f"expected {f._n}"
            )
        leaves = jax.tree_util.tree_leaves(f._holder["params"])
        new_backup = []
        for (off, size, shape, dtype), b in zip(f._leaf_meta, f.backup):
            g = (
                (b.reshape(-1).astype(np.float32) + delta[off : off + size])
                .astype(dtype, copy=False)
                .reshape(shape)
            )
            new_backup.append(g)
        for j, i in enumerate(f._leaf_idxs):
            leaves[i] = _like_leaf(new_backup[j], leaves[i])
        f.backup = new_backup
        f._holder["params"] = jax.tree_util.tree_unflatten(f._treedef, leaves)

    return _apply
