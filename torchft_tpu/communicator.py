"""Reconfigurable host-side communicators for the replica (outer-DP) dimension.

This is the data-plane analog of the reference's reconfigurable
ProcessGroups (``torchft/process_group.py``), redesigned for TPU: the
replica dimension lives *outside* XLA programs.  Gradients produced by a
jit-compiled step are averaged across replica groups by a host-driven
communicator over DCN/TCP, so membership changes never invalidate compiled
executables — ``configure()`` swaps the communicator; the gradient divisor is
a runtime scalar (SURVEY.md §7.3).

Semantics carried over from the reference (SURVEY.md §5.8):

1. ``configure()`` is callable repeatedly, each call rendezvousing under a
   fresh per-quorum store namespace and fully superseding the previous
   communicator (``process_group.py:435-471``).
2. ``abort()`` unblocks in-flight collectives and poisons the communicator
   until the next ``configure()`` (``process_group.py:875-888``).
3. Collectives return :class:`~torchft_tpu.work.Work` handles with value
   chaining (``manager.py:1216-1363``).
4. Errors are recorded, never raised into the train loop (the Manager votes
   the step down instead, ``manager.py:487-493``).
5. Timeouts are userspace and per-operation: an op that exceeds its deadline
   aborts the communicator rather than killing the process
   (``process_group.py:714-777``).

The wire tier here (:class:`TCPCommunicator`) is the CPU/"gloo" equivalent
that runs anywhere; the same interface is implemented by the C++ runtime
(``native/``) for production DCN use.
"""

from __future__ import annotations

import logging
import mmap
import os
import platform
import queue
import select
import socket
import struct
import tempfile
import threading
import time
import uuid
from abc import ABC, abstractmethod
from concurrent.futures import Future
from enum import Enum
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchft_tpu.futures import TimerHandle, schedule_timeout
from torchft_tpu.obs.flight import FlightEvent, FlightRecorder
from torchft_tpu.obs.spans import span as obs_span, spans_enabled
from torchft_tpu.store import create_store_client
from torchft_tpu import wire as wire_tags
from torchft_tpu.wire import create_listener
from torchft_tpu.work import DummyWork, Work

logger = logging.getLogger(__name__)


def _spanned(name: str):
    """Wrap a hot method in an obs trace span — one truthiness check when
    spans are disabled, a recorded wall-clock window when enabled."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            if not spans_enabled():
                return fn(*args, **kwargs)
            with obs_span(name):
                return fn(*args, **kwargs)

        return inner

    return deco


Buffers = Union[np.ndarray, Sequence[np.ndarray]]


class ReduceOp(Enum):
    """Reduction for collectives; AVG divides the SUM by the communicator
    world size (the Manager instead divides by live participants)."""

    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"


def _bytes_view(arr: np.ndarray) -> memoryview:
    """Writable raw-byte view of a contiguous array; extension dtypes like
    bfloat16 reject memoryview.cast, so reinterpret through uint8 instead."""
    return memoryview(arr.reshape(-1).view(np.uint8))


def _reduce_into(op: ReduceOp, acc: np.ndarray, incoming: np.ndarray) -> None:
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        np.add(acc, incoming, out=acc)
    elif op == ReduceOp.MAX:
        np.maximum(acc, incoming, out=acc)
    elif op == ReduceOp.MIN:
        np.minimum(acc, incoming, out=acc)
    else:  # pragma: no cover
        raise ValueError(f"unsupported reduce op {op}")


class CommunicatorError(RuntimeError):
    pass


class CommunicatorAborted(CommunicatorError):
    pass


class PeerGoneError(CommunicatorError):
    """A peer's connection is DEAD (closed socket / failed send) — a
    fail-stop condition scoped to that pair.  Distinct from protocol errors
    (tag/size mismatch) where the socket survives with a desynchronized
    stream and the whole epoch must be poisoned."""


class Communicator(ABC):
    """Abstract reconfigurable communicator (``process_group.py:131-399``)."""

    @abstractmethod
    def configure(
        self,
        store_addr: str,
        replica_id: str,
        rank: int,
        world_size: int,
        quorum_id: int = 0,
        group_rank: int = 0,
        group_world_size: int = 1,
        global_ranks: Sequence[int] = (),
    ) -> None:
        ...

    @abstractmethod
    def allreduce(
        self,
        buffers: Buffers,
        op: ReduceOp = ReduceOp.SUM,
        in_place: bool = False,
    ) -> Work:
        """Reduce ``buffers`` across ranks; the Work's value is the reduced
        list of arrays (AVG divides by world size).

        ``in_place=True`` lets the tier reduce directly in the caller's
        (contiguous, writable) buffers and return them aliased — c10d
        allreduce semantics, skipping a full-payload copy.  Only pass it for
        buffers you own and will not reuse (on error the contents are
        unspecified; the step is voted down anyway)."""

    @abstractmethod
    def broadcast(self, buffers: Buffers, root: int = 0) -> Work:
        ...

    @abstractmethod
    def send_bytes(self, data: bytes, dst: int, tag: int = 0) -> Work:
        ...

    @abstractmethod
    def recv_bytes(self, src: int, tag: int = 0) -> Work:
        ...

    @abstractmethod
    def barrier(self) -> Work:
        ...

    def alltoall(self, chunks: List[np.ndarray], tag: int = 0) -> Work:
        raise NotImplementedError

    def allgather(self, data: np.ndarray, tag: int = 0) -> Work:
        raise NotImplementedError

    def recv_bytes_into(self, src: int, out: np.ndarray, tag: int = 0) -> Work:
        """Zero-copy variant: receive one frame directly into ``out`` (a
        contiguous writable array); the Work's value is the payload size."""
        raise NotImplementedError

    def heal_drain(
        self,
        chunk_views: List[memoryview],
        expected: Dict[int, List[int]],
        orphans: List[int],
        chunk_tag: Callable[[int], int],
        ctrl_tag: int,
        make_need: Callable[[List[int]], bytes],
        done_blob: bytes,
        timeout_s: Optional[float] = None,
    ) -> Work:
        """Striped-heal receive: concurrently drain disjoint chunk frames
        from many source peers straight into ``chunk_views`` (see
        :meth:`TCPCommunicator.heal_drain`).  ``timeout_s`` bounds the whole
        drain (it may legitimately outlast the per-collective op timeout).
        Tiers without it raise, and the checkpoint transport falls back to
        the single-source heal."""
        raise NotImplementedError

    def reduce_scatter(
        self, data: np.ndarray, op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        """Reduce ``data`` (same shape on every rank) across ranks and
        scatter: the Work's value is THIS rank's chunk of the flattened
        reduction (chunk r of ``world_size`` near-equal chunks, the first
        ``n % ws`` chunks one element longer).  Half the wire cost of a full
        allreduce when each rank only needs its own slice — the reference
        carries the same op on its PG surface (``process_group.py:236-276``).
        """
        raise NotImplementedError

    @abstractmethod
    def abort(self, reason: str = "aborted") -> None:
        ...

    @abstractmethod
    def errored(self) -> Optional[Exception]:
        ...

    @abstractmethod
    def rank(self) -> int:
        ...

    @abstractmethod
    def size(self) -> int:
        ...

    def set_timeout(self, timeout_s: float) -> None:
        ...

    def lane_stats(self) -> Dict[str, object]:
        """Per-lane data-plane counters of the current epoch (lane count,
        stripe floor, bytes, stall events); empty for tiers without lane
        striping or before configure."""
        return {}

    def hier_topology(self) -> Optional[Dict[str, object]]:
        """Facts of the epoch's active hierarchical host topology (host
        count, local group, leader ring) or None when collectives run flat.
        Tiers without topology awareness report None."""
        return None

    def shutdown(self) -> None:
        ...


# ---------------------------------------------------------------------------
# TCP mesh
# ---------------------------------------------------------------------------

_HDR = struct.Struct("<QQ")  # payload nbytes, tag


class _StreamBucket:
    """Per-connection token bucket modeling a cwnd-limited TCP stream:
    rate = cwnd/RTT, burst = cwnd."""

    __slots__ = ("rate", "burst", "_tokens", "_last")

    def __init__(self, rate: float, burst: int) -> None:
        self.rate = rate
        self.burst = burst
        self._tokens = float(burst)
        self._last = time.monotonic()

    def allow(self, want: int) -> int:
        now = time.monotonic()
        self._tokens = min(
            float(self.burst), self._tokens + (now - self._last) * self.rate
        )
        self._last = now
        return max(0, min(want, int(self._tokens)))

    def consume(self, n: int) -> None:
        self._tokens -= n


class _LinkBucket:
    """Process-shared token bucket for one emulated LINK — the host NIC:
    a :class:`_StreamBucket` (same capped accrual math, one source of
    truth) behind a lock, because op threads of several communicators pace
    concurrently.

    Every communicator in a process draws from the same bucket (keyed by
    the link parameters), because one process models one host: replicas
    co-located on a host share its uplink, which is exactly the contention
    the hierarchical collectives exist to relieve.  Benches emulate an
    N-replica host by running N ranks as threads of one process
    (``dcn_bench.py --hosts``); single-rank processes (the existing bench
    layouts) are unaffected — their bucket has one tenant."""

    __slots__ = ("_bucket", "_lock")

    def __init__(self, rate: float, burst: int) -> None:
        self._bucket = _StreamBucket(rate, burst)
        self._lock = threading.Lock()

    def allow(self, want: int) -> int:
        with self._lock:
            return self._bucket.allow(want)

    def consume(self, n: int) -> None:
        with self._lock:
            self._bucket.consume(n)


_LINK_BUCKETS: Dict[Tuple[float, int], _LinkBucket] = {}
_LINK_BUCKETS_LOCK = threading.Lock()


def _shared_link(rate: float, burst: int) -> _LinkBucket:
    with _LINK_BUCKETS_LOCK:
        bucket = _LINK_BUCKETS.get((rate, burst))
        if bucket is None:
            bucket = _LINK_BUCKETS[(rate, burst)] = _LinkBucket(rate, burst)
        return bucket


class _NetEmu:
    """Deterministic sender-side network emulation (netem analog) for the
    TCP tier: a shared token-bucket link cap, a per-connection cwnd-limited
    stream cap, and a half-RTT gate before each frame's first byte.
    Loopback hides the regime the replica dimension is designed for (DCN:
    ~1-10 Gb/s, 2-10 ms RTT); with this, ring / quantized ring /
    heal-transfer behavior at DCN profiles is measured rather than
    extrapolated (``benchmarks/dcn_bench.py``).

    The stream cap is what makes multi-lane striping measurable: a single
    TCP stream on a long-RTT path is limited by min(link, cwnd/RTT), so one
    connection cannot saturate the link — exactly the underutilization the
    lane striping in :class:`_TcpMesh` exists to cure.  Default cwnd is
    ``TORCHFT_NET_CWND_KB`` (256 KiB; ``0`` disables the stream cap and
    restores the pure link-rate model); it only engages when RTT > 0.

    Enabled only via env — ``TORCHFT_NET_EMU`` (a named profile:
    ``wan_1g`` = 1 Gb/s / 10 ms, ``dcn_10g`` = 10 Gb/s / 2 ms) or the raw
    ``TORCHFT_NET_GBPS`` (link rate, Gbit/s) and ``TORCHFT_NET_RTT_MS``
    knobs — and never in production paths by default."""

    def __init__(
        self, gbps: float, rtt_ms: float, cwnd_bytes: int = 256 << 10
    ) -> None:
        self.bytes_per_s = gbps * 1e9 / 8.0
        self.half_rtt_s = rtt_ms / 2e3
        self.rtt_s = rtt_ms / 1e3
        # per-stream throughput cap (cwnd/RTT); 0 = uncapped
        self.stream_bytes_per_s = (
            cwnd_bytes / self.rtt_s if cwnd_bytes > 0 and self.rtt_s > 0 else 0.0
        )
        self.cwnd_bytes = cwnd_bytes
        self.burst = max(64 << 10, int(self.bytes_per_s * 0.005))
        # the LINK bucket is process-shared (one process = one emulated
        # host NIC; see _LinkBucket); stream buckets stay per-mesh since a
        # cwnd is per-connection state
        self._link = (
            _shared_link(self.bytes_per_s, self.burst)
            if self.bytes_per_s > 0
            else None
        )
        self._streams: Dict[object, _StreamBucket] = {}

    def frame_gate(self) -> float:
        """Earliest monotonic time the next frame may start transmitting."""
        return time.monotonic() + self.half_rtt_s

    def bdp_bytes(self) -> int:
        """RTT × bandwidth product of the emulated link (0 when either is
        unshaped) — the natural frame size on this profile."""
        if self.bytes_per_s <= 0 or self.rtt_s <= 0:
            return 0
        return int(self.bytes_per_s * self.rtt_s)

    def allow(self, want: int, stream: object = None) -> int:
        """Bytes the link (and, when RTT emulation is on, ``stream``'s cwnd
        bucket) permit right now (<= ``want``)."""
        if self._link is not None:
            want = self._link.allow(want)
        if stream is not None and self.stream_bytes_per_s > 0 and want > 0:
            bucket = self._streams.get(stream)
            if bucket is None:
                bucket = self._streams[stream] = _StreamBucket(
                    self.stream_bytes_per_s, self.cwnd_bytes
                )
            want = bucket.allow(want)
        return want

    def consume(self, n: int, stream: object = None) -> None:
        if self._link is not None:
            self._link.consume(n)
        if stream is not None and self.stream_bytes_per_s > 0:
            bucket = self._streams.get(stream)
            if bucket is not None:
                bucket.consume(n)


# ---------------------------------------------------------------------------
# fault injection (gray failures)
# ---------------------------------------------------------------------------

# Per-link fault program for the TCP tier's data plane — the gray-failure
# analog of the _NetEmu pacer: where the pacer shapes HEALTHY links, the
# fault program makes them flaky.  Spec syntax (comma-separated terms):
#
#   loss:P            per-sub-frame drop probability; a dropped sub-frame is
#                     retransmitted after one RTO (sender stalls ~2xRTT) —
#                     the TCP-over-lossy-link throughput penalty, without
#                     breaking the reliable-stream contract
#   reset:P           per-sub-frame probability the lane's connection is
#                     reset (socket closed mid-collective) — what the
#                     in-epoch lane retry/failover machinery recovers from
#   reset_once:N      deterministic form: exactly ONE reset after N
#                     sub-frames have been sent (tests/drills)
#   stall:P:MS        per-sub-frame probability the lane stalls MS
#                     milliseconds (one slow-NIC hiccup)
#   partition:A+B|self  partition mask: frames between the listed ranks and
#                     everyone else are silently blackholed (both
#                     directions); 'self' resolves to this mesh's own rank
#
# Armed via env (TORCHFT_NET_FAULTS=loss:0.01,reset:0.002) or at runtime —
# TCPCommunicator.arm_faults() — so chaos can flip a healthy link
# mid-collective.  TORCHFT_NET_FAULT_SEED makes draws reproducible.
NET_FAULTS_ENV = "TORCHFT_NET_FAULTS"
NET_FAULT_SEED_ENV = "TORCHFT_NET_FAULT_SEED"
# In-epoch lane recovery: how many re-dial attempts a transiently-reset
# lane gets before its traffic fails over to the surviving lanes, and the
# base of the jittered exponential backoff between attempts.
LANE_RETRIES_ENV = "TORCHFT_LANE_RETRIES"
LANE_BACKOFF_MS_ENV = "TORCHFT_LANE_BACKOFF_MS"
_LANE_RETRIES_DEFAULT = 2
_LANE_BACKOFF_MS_DEFAULT = 50.0


class _FaultProgram:
    """Parsed TORCHFT_NET_FAULTS spec (immutable; per-mesh RNG state lives
    on the mesh so one program can arm many meshes)."""

    __slots__ = (
        "loss", "reset", "reset_once", "stall_p", "stall_ms", "partition",
    )

    def __init__(
        self,
        loss: float = 0.0,
        reset: float = 0.0,
        reset_once: int = -1,
        stall_p: float = 0.0,
        stall_ms: float = 200.0,
        partition: Optional[frozenset] = None,
    ) -> None:
        self.loss = loss
        self.reset = reset
        self.reset_once = reset_once
        self.stall_p = stall_p
        self.stall_ms = stall_ms
        self.partition = partition

    def active(self) -> bool:
        return bool(
            self.loss > 0
            or self.reset > 0
            or self.reset_once >= 0
            or self.stall_p > 0
            or self.partition
        )

    def partitions(self, my_rank: int, peer: int) -> bool:
        """True when the (my_rank, peer) link crosses the partition mask."""
        if not self.partition:
            return False
        mask = {my_rank if m == "self" else m for m in self.partition}
        return (my_rank in mask) != (peer in mask)


def parse_fault_spec(raw: Optional[str]) -> Optional[_FaultProgram]:
    """Parse a fault-program spec string; None/empty disables injection."""
    if not raw or not raw.strip():
        return None
    kw: Dict[str, object] = {}
    for term in raw.strip().split(","):
        parts = term.strip().split(":")
        name = parts[0].strip().lower()
        try:
            if name == "loss":
                kw["loss"] = float(parts[1])
            elif name == "reset":
                kw["reset"] = float(parts[1])
            elif name == "reset_once":
                kw["reset_once"] = int(parts[1])
            elif name == "stall":
                kw["stall_p"] = float(parts[1])
                if len(parts) > 2:
                    kw["stall_ms"] = float(parts[2])
            elif name == "partition":
                kw["partition"] = frozenset(
                    "self" if m.strip().lower() == "self" else int(m)
                    for m in parts[1].split("+")
                )
            else:
                raise ValueError(f"unknown fault {name!r}")
        except (IndexError, ValueError) as e:
            # loud, not silent: a typo'd program would otherwise run CLEAN
            # and record healthy numbers as a fault drill
            raise CommunicatorError(
                f"unparseable {NET_FAULTS_ENV} term {term!r}: {e} "
                "(valid: loss:P, reset:P, reset_once:N, stall:P:MS, "
                "partition:A+B|self)"
            ) from e
    return _FaultProgram(**kw)  # type: ignore[arg-type]


def _net_faults_from_env() -> Optional[_FaultProgram]:
    return parse_fault_spec(os.environ.get(NET_FAULTS_ENV))


def _lane_retry_knobs() -> Tuple[int, float]:
    """(re-dial attempts, backoff base seconds) for in-epoch lane recovery."""
    try:
        retries = int(
            os.environ.get(LANE_RETRIES_ENV, "") or _LANE_RETRIES_DEFAULT
        )
        backoff_ms = float(
            os.environ.get(LANE_BACKOFF_MS_ENV, "") or _LANE_BACKOFF_MS_DEFAULT
        )
    except ValueError as e:
        raise CommunicatorError(
            f"unparseable {LANE_RETRIES_ENV}="
            f"{os.environ.get(LANE_RETRIES_ENV)!r} / {LANE_BACKOFF_MS_ENV}="
            f"{os.environ.get(LANE_BACKOFF_MS_ENV)!r}"
        ) from e
    return max(0, retries), max(0.001, backoff_ms / 1000.0)


# named emulation profiles (TORCHFT_NET_EMU): (link Gbit/s, RTT ms).  The
# aliases with the explicit RTT suffix match benchmarks/dcn_bench.py's
# profile names, so a bench row can be reproduced verbatim from env.
_NET_EMU_PROFILES = {
    "wan_1g": (1.0, 10.0),
    "wan_1g_10ms": (1.0, 10.0),
    "dcn_10g": (10.0, 2.0),
    "dcn_10g_2ms": (10.0, 2.0),
    "loopback": (0.0, 0.0),
}


def _net_emu_from_env() -> Optional["_NetEmu"]:
    profile = os.environ.get("TORCHFT_NET_EMU", "").strip().lower()
    prof_gbps, prof_rtt = 0.0, 0.0
    if profile:
        if profile not in _NET_EMU_PROFILES:
            # loud, not silent: a typo'd profile would otherwise run
            # UNSHAPED and record loopback numbers as a DCN profile
            raise CommunicatorError(
                f"unknown TORCHFT_NET_EMU profile {profile!r}; "
                f"valid: {sorted(_NET_EMU_PROFILES)}"
            )
        prof_gbps, prof_rtt = _NET_EMU_PROFILES[profile]
    try:
        gbps = float(os.environ.get("TORCHFT_NET_GBPS", "") or prof_gbps)
        rtt_ms = float(os.environ.get("TORCHFT_NET_RTT_MS", "") or prof_rtt)
        cwnd = int(
            float(os.environ.get("TORCHFT_NET_CWND_KB", "") or 256) * 1024
        )
    except ValueError as e:
        raise CommunicatorError(
            "unparseable network-emulation knob: "
            f"TORCHFT_NET_GBPS={os.environ.get('TORCHFT_NET_GBPS')!r} "
            f"TORCHFT_NET_RTT_MS={os.environ.get('TORCHFT_NET_RTT_MS')!r} "
            f"TORCHFT_NET_CWND_KB={os.environ.get('TORCHFT_NET_CWND_KB')!r}"
        ) from e
    if gbps <= 0 and rtt_ms <= 0:
        return None
    return _NetEmu(gbps, rtt_ms, cwnd)


# ---------------------------------------------------------------------------
# lane striping
# ---------------------------------------------------------------------------

# Parallel-connection ("lane") count for ring collectives.  One TCP stream
# on a long-RTT DCN path is cwnd-limited far below the link rate; striping
# each ring chunk across L independent connections is the standard cure
# (cf. PAPERS.md: HSDP-at-100k-GPUs / SPARe stripe inter-replica reduction
# the same way).  MUST be uniform across replicas (verified loudly at
# rendezvous); "auto"/unset derives it from the emulated link profile (1 on
# plain loopback, where a single stream already saturates).
RING_LANES_ENV = "TORCHFT_RING_LANES"
# Floor for one striped sub-frame, in KiB.  Unset/auto picks the link's
# RTT×bandwidth product (jumbo frames on DCN so the per-frame half-RTT gate
# amortizes; 64 KiB on loopback).  Uniform across replicas, like the lanes.
RING_FRAME_KB_ENV = "TORCHFT_RING_FRAME_KB"
_MAX_AUTO_LANES = 4
_MIN_STRIPE_BYTES = 64 << 10
# sub-frame boundaries are 64-byte aligned so no element of any supported
# dtype (itemsize a power of two <= 64) ever splits across lanes — the
# receive path can reduce a completed part without waiting for its siblings
_STRIPE_ALIGN = 64

# High bit of the rendezvous hello's rank field marks the EXTENDED hello
# (rank|flag, lane, lane count, stripe floor; 32 bytes), sent whenever
# lanes > 1.  A single-lane build sends the legacy 8-byte rank hello —
# wire-identical to every pre-lane build — and the flag bit lets EITHER
# side detect a lane-config disagreement from the first 8 bytes and fail
# loudly, instead of wedging on missing hello bytes or misparsing the
# extended hello's tail as a frame header.  (Ranks are tiny integers; the
# top bit is never a real rank.)
_LANE_HELLO_FLAG = 1 << 63
# Second-highest bit marks a RECONNECT hello: a lane re-dialed mid-epoch
# after a transient reset (in-epoch lane recovery).  Always the extended
# 32-byte form; only this build speaks it, which is fine — a peer that
# cannot reconnect simply leaves the lane dead and the legacy poison path
# applies.
_LANE_RECONN_FLAG = 1 << 62
# Reserved frame tag for in-band lane-failover control frames (a dead
# lane's endpoints agree on outstanding sub-frames over a surviving lane).
# Data tags are small positive ints (tag bases + step indices); the top of
# the u64 space is never a real tag.
_LANE_CTRL_TAG = (1 << 64) - 17
_LANE_CTRL = struct.Struct("<QQQ")  # kind, dead lane, completed-rx count
_LANE_RESYNC = struct.Struct("<QQ")  # tx seq, rx seq (reconnect handshake)


def _ring_lanes(emu: Optional[_NetEmu]) -> int:
    raw = os.environ.get(RING_LANES_ENV, "").strip().lower()
    if raw and raw != "auto":
        try:
            lanes = int(raw)
        except ValueError as e:
            raise CommunicatorError(
                f"unparseable {RING_LANES_ENV}={raw!r} (int or 'auto')"
            ) from e
        if lanes < 1:
            raise CommunicatorError(f"{RING_LANES_ENV} must be >= 1")
        return lanes
    # auto: enough lanes that the aggregate stream rate reaches the link
    # rate, capped; 1 when unshaped (loopback) or the stream cap is off
    if emu is None or emu.stream_bytes_per_s <= 0 or emu.bytes_per_s <= 0:
        return 1
    need = -(-int(emu.bytes_per_s) // max(1, int(emu.stream_bytes_per_s)))
    return max(1, min(_MAX_AUTO_LANES, need))


def _stripe_floor(emu: Optional[_NetEmu]) -> int:
    raw = os.environ.get(RING_FRAME_KB_ENV, "").strip().lower()
    if raw and raw != "auto":
        try:
            return max(_STRIPE_ALIGN, int(float(raw) * 1024))
        except ValueError as e:
            raise CommunicatorError(
                f"unparseable {RING_FRAME_KB_ENV}={raw!r} (KiB or 'auto')"
            ) from e
    if emu is not None:
        bdp = emu.bdp_bytes()
        if bdp > 0:
            # jumbo frames on DCN: one sub-frame covers at least a BDP so
            # the half-RTT frame gate amortizes over a full pipe of bytes
            return max(_MIN_STRIPE_BYTES, min(bdp, 8 << 20))
    return _MIN_STRIPE_BYTES


def _lane_parts(
    nbytes: int, lanes: int, floor: int
) -> List[Tuple[int, int, int]]:
    """Deterministic split of one ``nbytes`` frame into per-lane sub-frames:
    ``[(lane, start, stop), ...]``.  Both endpoints compute this from the
    frame length alone, so no extra wire metadata is needed; the native tier
    (``native/comm.h lane_parts``) implements the identical math so the
    tiers stay wire-compatible at any lane count.  Payloads smaller than
    two floors ride lane 0 whole (striping tiny frames only adds per-frame
    overhead)."""
    if lanes <= 1 or nbytes < 2 * floor:
        return [(0, 0, nbytes)]
    k = min(lanes, max(1, nbytes // floor))
    if k <= 1:
        return [(0, 0, nbytes)]
    bounds = [0]
    for i in range(1, k):
        cut = (i * nbytes // k) // _STRIPE_ALIGN * _STRIPE_ALIGN
        bounds.append(max(cut, bounds[-1]))
    bounds.append(nbytes)
    return [(lane, bounds[lane], bounds[lane + 1]) for lane in range(k)]


def outer_shard_parts(
    nbytes: int, parts: int, unit: int = _STRIPE_ALIGN
) -> List[Tuple[int, int]]:
    """Deterministic per-replica shard split for the sharded outer
    optimizer (``local_sgd``): the buffer is padded up to a multiple of
    ``parts * unit`` and every shard is exactly ``padded // parts`` bytes.
    A pure function of the payload size and the participant count — every
    replica derives identical shard ownership with no extra wire metadata,
    the same contract as :func:`_lane_parts` — and ``unit``-aligned so a
    shard boundary never splits an element (64 B default) or a
    quantization row (callers pass the row byte size).  Mirrored exactly in
    ``native/comm.h outer_shard_parts`` so the tiers agree on shard
    ownership at any world size.  Returns ``[(start, stop), ...]`` over the
    PADDED byte range, one entry per shard."""
    if parts < 1:
        raise CommunicatorError("outer_shard_parts: parts must be >= 1")
    if unit < 1 or unit % _STRIPE_ALIGN != 0:
        raise CommunicatorError(
            f"outer_shard_parts: unit must be a positive multiple of "
            f"{_STRIPE_ALIGN}, got {unit}"
        )
    share = -(-nbytes // (parts * unit)) * unit
    return [(p * share, (p + 1) * share) for p in range(parts)]


# ---------------------------------------------------------------------------
# host topology + shared-memory intra-host transport
# ---------------------------------------------------------------------------

# Hierarchical (topology-aware) collectives gate: "auto" (default) turns
# the two-level schedule on when the discovered topology has >= 2 hosts AND
# at least one host holds >= 2 replicas — the regime where flat rings push
# every byte across the DCN once per REPLICA instead of once per HOST.
# "1" forces it on (any topology, including all-one-host: collectives then
# run entirely over shared memory); "0" pins the flat ring, byte-for-byte
# identical to the pre-topology wire behavior.  A peer that speaks no
# topology (gate "0", legacy or native-tier build) never publishes its
# topology key: "auto" groups deterministically fall back to the flat ring
# (the key lands in the store before the dialable address, so absence
# after rendezvous is a fact, not a race); a forced "1" fails loudly.
HIERARCHICAL_ENV = "TORCHFT_HIERARCHICAL"
# Overrides host-group identity for this replica.  Default grouping is by
# the advertised rendezvous address' host part (same-IP grouping), which is
# right for one-process-per-replica SLURM/bench layouts; set distinct
# TORCHFT_HOST_ID values to partition co-located replicas into emulated
# hosts, or identical values to co-group replicas NAT'd behind one IP.
HOST_ID_ENV = "TORCHFT_HOST_ID"
# Per-member slot capacity of the intra-host shared-memory segment, MiB.
# Payloads larger than a slot stream through it in chunks.
SHM_SLOT_MB_ENV = "TORCHFT_SHM_SLOT_MB"
_SHM_SLOT_DEFAULT_MB = 16.0


def _hier_mode(override: Optional[str] = None) -> str:
    raw = (
        override
        if override is not None
        else os.environ.get(HIERARCHICAL_ENV, "auto")
    )
    raw = str(raw).strip().lower()
    if raw in ("", "auto"):
        return "auto"
    if raw in ("1", "true", "on"):
        return "1"
    if raw in ("0", "false", "off"):
        return "0"
    raise CommunicatorError(
        f"unparseable {HIERARCHICAL_ENV}={raw!r} (auto|0|1)"
    )


def _shm_slot_bytes() -> int:
    raw = os.environ.get(SHM_SLOT_MB_ENV, "").strip()
    try:
        mb = float(raw) if raw else _SHM_SLOT_DEFAULT_MB
    except ValueError as e:
        raise CommunicatorError(
            f"unparseable {SHM_SLOT_MB_ENV}={raw!r} (MiB)"
        ) from e
    # 64-byte multiple so chunk boundaries never split an element of any
    # supported dtype (same rationale as _STRIPE_ALIGN)
    return max(64 << 10, int(mb * (1 << 20)) // 64 * 64)


class _HostTopology:
    """Host grouping of one quorum epoch, identical on every rank.

    Hosts are ordered by their smallest global rank; each host's leader IS
    that smallest rank, and the cross-host ring runs over ``leader_ring``
    in that order — all derived from the (rank -> host id) map alone, so
    every rank computes the same schedule with no extra wire metadata.
    The native tier (``native/comm.h HostTopology``) implements the
    identical ordering so the tiers stay wire-compatible."""

    def __init__(self, host_of: Dict[int, str], rank: int) -> None:
        self.host_of = dict(host_of)
        groups: Dict[str, List[int]] = {}
        for r in sorted(host_of):
            groups.setdefault(host_of[r], []).append(r)
        self.hosts: List[List[int]] = sorted(
            groups.values(), key=lambda g: g[0]
        )
        self.leader_ring: List[int] = [g[0] for g in self.hosts]
        self.local: List[int] = next(g for g in self.hosts if rank in g)
        self.leader: int = self.local[0]
        self.is_leader: bool = rank == self.leader
        self.local_index: int = self.local.index(rank)

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    @property
    def local_world(self) -> int:
        return len(self.local)

    def worth_it(self) -> bool:
        """The "auto" criterion: hierarchy only pays when a cross-host ring
        exists AND some host would otherwise push duplicate bytes."""
        return self.num_hosts > 1 and any(len(g) > 1 for g in self.hosts)


_SHM_ABORT_OFF = 0  # u64 abort latch at the head of the segment header
_SHM_HDR = 64
_SHM_SLOT_HDR = 64  # u64 publish-sequence, padded to a cache line


class _ShmSeg:
    """mmap'd per-host segment: the zero-socket intra-host transport.

    The host leader creates a file under ``/dev/shm`` (tmpdir fallback),
    every local member maps it, and the leader unlinks it the moment all
    members acknowledge the mapping — unlinked-after-map, so a killed
    replica leaks nothing: the kernel frees the pages when the last
    mapping dies, and ``/dev/shm`` never shows an orphan.

    One slot per local member plus a seqlock-style publish protocol:
    a writer copies its payload into its slot and then publishes a
    monotonically increasing sequence number; readers spin (abort- and
    deadline-checked) until the slot's sequence reaches the op's expected
    value.  The sequence store happens strictly after the payload copy
    (single ``struct.pack_into`` following the slice assignment), which on
    the GIL within a process — and x86-TSO across processes — is exactly
    the publish-after-payload order a seqlock needs.  Flow control is
    lock-step per chunk: the consumer republishes the same sequence on its
    OWN slot as an ack before the producer may overwrite.

    ``_seq`` is a local op counter advanced identically on every member
    (collectives execute in submission order on each rank's op thread, and
    submission order matches across ranks), so expected sequence values
    never ride the wire either."""

    def __init__(self, mm: mmap.mmap, members: int, slot_bytes: int) -> None:
        self._mm = mm
        self.members = members
        self.slot_bytes = slot_bytes
        self._seq = 0

    # -- lifecycle -----------------------------------------------------------

    @staticmethod
    def size_for(members: int, slot_bytes: int) -> int:
        return _SHM_HDR + members * (_SHM_SLOT_HDR + slot_bytes)

    @classmethod
    def create(cls, members: int, slot_bytes: int) -> Tuple["_ShmSeg", str]:
        base = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
        path = os.path.join(base, f"tpuft_shm_{uuid.uuid4().hex}")
        nbytes = cls.size_for(members, slot_bytes)
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, nbytes)
            mm = mmap.mmap(fd, nbytes)
        except BaseException:
            os.close(fd)
            os.unlink(path)
            raise
        os.close(fd)
        return cls(mm, members, slot_bytes), path

    @classmethod
    def attach(cls, path: str, members: int, slot_bytes: int) -> "_ShmSeg":
        nbytes = cls.size_for(members, slot_bytes)
        fd = os.open(path, os.O_RDWR)
        try:
            mm = mmap.mmap(fd, nbytes)
        finally:
            os.close(fd)
        return cls(mm, members, slot_bytes)

    def _slot_off(self, idx: int) -> int:
        return _SHM_HDR + idx * (_SHM_SLOT_HDR + self.slot_bytes)

    # -- abort latch ---------------------------------------------------------

    def set_abort(self) -> None:
        try:
            struct.pack_into("<Q", self._mm, _SHM_ABORT_OFF, 1)
        except ValueError:  # pragma: no cover - segment already torn down
            pass

    def aborted(self) -> bool:
        return struct.unpack_from("<Q", self._mm, _SHM_ABORT_OFF)[0] != 0

    # -- seqlock publish / wait ---------------------------------------------

    def post(self, idx: int, seq: int, payload: Optional[memoryview]) -> None:
        """Copy ``payload`` (None = flag-only ack) into slot ``idx``, then
        publish ``seq``."""
        off = self._slot_off(idx)
        if payload is not None and len(payload) > 0:
            start = off + _SHM_SLOT_HDR
            self._mm[start : start + len(payload)] = payload
        struct.pack_into("<Q", self._mm, off, seq)

    def wait(
        self,
        idx: int,
        seq: int,
        deadline: float,
        extra_abort: Optional[threading.Event] = None,
    ) -> None:
        """Spin until slot ``idx`` publishes a sequence >= ``seq``."""
        off = self._slot_off(idx)
        spins = 0
        while struct.unpack_from("<Q", self._mm, off)[0] < seq:
            if self.aborted() or (
                extra_abort is not None and extra_abort.is_set()
            ):
                raise CommunicatorAborted("communicator aborted (shm)")
            if time.monotonic() > deadline:
                raise TimeoutError("intra-host shm op timed out")
            spins += 1
            # yield the GIL so a sibling-thread writer can run; back off to
            # a real sleep once it is clearly a cross-process wait
            time.sleep(0 if spins < 2000 else 0.0002)

    def view(self, idx: int, nbytes: int) -> memoryview:
        start = self._slot_off(idx) + _SHM_SLOT_HDR
        return memoryview(self._mm)[start : start + nbytes]


def _rearm_frame(frame: dict) -> None:
    """(Re)build a send frame's live buffer list from its retained
    originals — fresh frames and reset-replayed frames go through the same
    path, so a replay is byte-identical to the first transmission."""
    bufs = [memoryview(frame["hdr"])]
    payload = frame["payload"]
    if payload is not None and len(payload):
        bufs.append(payload)
    frame["bufs"] = bufs


def _mk_frame(hdr: bytes, payload: Optional[memoryview], ctrl: bool = False) -> dict:
    frame = {"hdr": hdr, "payload": payload, "ctrl": ctrl, "checked": ctrl}
    _rearm_frame(frame)
    return frame


class _ExchangeCtx:
    """Mutable state of one ``exchange()`` call, shared with the lane
    recovery machinery: the send/recv FIFOs, per-socket receive state, the
    completed-sub-frame log (replay source for lane resets), pacer gates,
    and in-flight failover handshakes."""

    __slots__ = (
        "send_q", "recv_q", "recv_st", "sent_log", "frame_gates",
        "pending_failover", "dying", "dying_sends",
    )

    def __init__(self) -> None:
        self.send_q: Dict[Tuple[int, int], List[dict]] = {}
        self.recv_q: Dict[Tuple[int, int], List[dict]] = {}
        self.recv_st: Dict[Tuple[int, int], dict] = {}
        self.sent_log: Dict[Tuple[int, int], List[dict]] = {}
        self.frame_gates: Dict[Tuple[int, int], float] = {}
        self.pending_failover: Dict[Tuple[int, int], dict] = {}
        # injected-reset half-close state: lanes we SHUT_WR'd and are
        # draining to EOF before recovery (so no flushed byte is ever
        # destroyed by an abortive close), with their parked sends
        self.dying: set = set()
        self.dying_sends: Dict[Tuple[int, int], List[dict]] = {}


class _TcpMesh:
    """Full mesh of rank-to-rank lane sockets for one quorum epoch.

    Rendezvous: every rank publishes its listener under ``{prefix}/{rank}``
    in the store; for each pair (i, j) with i < j, j dials i — once per
    **lane**.  Lanes are parallel TCP connections that one logical
    collective stripes its frames across (``_lane_parts``), curing
    single-stream cwnd underutilization on long-RTT links; lane count MUST
    be uniform across ranks and is verified in the hello frame.  All data
    ops for the epoch run on a single op thread, so sockets need no locking
    and collective issue order matches across ranks; one select loop
    multiplexes every lane.

    Point-to-point byte ops (sends/recvs, heal drains) ride the LAST lane
    (``p2p_lane``) whole — with lanes > 1 that keeps striped heal traffic
    off lane 0, where collective control frames (barriers, small rings)
    concentrate; with lanes == 1 it is byte-for-byte the legacy behavior.
    """

    def __init__(
        self,
        store_addr: str,
        rank: int,
        world_size: int,
        timeout_s: float,
        lanes: int = 0,
        host_id: Optional[str] = None,
        hier: Optional[str] = None,
        faults: Optional[_FaultProgram] = None,
        flight: Optional[FlightRecorder] = None,
    ) -> None:
        self.rank = rank
        self.world_size = world_size
        # flight recorder of the owning communicator (None when unattached):
        # lane reconnects/failovers and env-armed fault programs record here
        self._flight = flight
        self._aborted = threading.Event()
        # netem-style pacing (off unless TORCHFT_NET_EMU/GBPS/RTT_MS set)
        self._emu = _net_emu_from_env()
        self.lanes = lanes if lanes > 0 else _ring_lanes(self._emu)
        self.p2p_lane = self.lanes - 1
        self.stripe_floor = _stripe_floor(self._emu)
        # lane-0 sockets keep the legacy name: single-lane code paths (and
        # tests) address peers through it unchanged
        self.peers: Dict[int, socket.socket] = {}
        self.lane_socks: Dict[Tuple[int, int], socket.socket] = {}
        self._sock_key: Dict[socket.socket, Tuple[int, int]] = {}
        # per-lane observability: payload bytes moved and stall events
        # (pacer denials / kernel would-block) — surfaced via
        # TCPCommunicator.lane_stats() into manager.last_quorum_timings
        self.lane_tx_bytes = [0] * self.lanes
        self.lane_rx_bytes = [0] * self.lanes
        self.lane_stalls = [0] * self.lanes
        # gray-failure machinery: fault program (env or runtime-armed),
        # in-epoch lane recovery knobs + counters, per-(peer, lane)
        # completed-sub-frame sequence counters the reconnect/failover
        # resync handshakes run on, and the per-peer dead-lane set (agreed
        # by handshake, so both sides route identically)
        self.faults: Optional[_FaultProgram] = (
            faults if faults is not None else _net_faults_from_env()
        )
        if faults is None and self.faults is not None and self._flight:
            # process-plane chaos arming: the fault program rode the spawn
            # env (TORCHFT_NET_FAULTS); runtime arming records in
            # arm_faults instead, so the two planes never double-record
            self._flight.record(
                FlightEvent.CHAOS_INJECT, via="env", armed=True
            )
        import random as _random

        seed_raw = os.environ.get(NET_FAULT_SEED_ENV, "")
        self._fault_rng = _random.Random(
            (int(seed_raw) * 1_000_003 + rank) if seed_raw else None
        )
        self.lane_retries, self.lane_backoff_s = _lane_retry_knobs()
        self.lane_reconnects = 0
        self.lane_failovers = 0
        self.faults_injected = 0
        self._fault_frames = 0
        self._reset_once_fired = False
        self._tx_seq: Dict[Tuple[int, int], int] = {}
        self._rx_seq: Dict[Tuple[int, int], int] = {}
        self.dead_lanes: Dict[int, set] = {}
        # lane re-dials land here (accept thread -> recovering op thread)
        self._pending_reconn: Dict[Tuple[int, int], socket.socket] = {}
        self._reconn_cv = threading.Condition()
        self._peer_addrs: Dict[int, Tuple[str, int]] = {}
        # topology (hierarchical collectives): filled by _topo_rendezvous
        # below; None = flat ring (the byte-for-byte legacy data plane)
        self.topo: Optional[_HostTopology] = None
        self.shm: Optional[_ShmSeg] = None
        self.shm_tx_bytes = 0
        self.shm_rx_bytes = 0
        hier_mode = _hier_mode(hier)

        store = create_store_client(store_addr, timeout=timeout_s)

        listener = create_listener("0.0.0.0:0", backlog=world_size * self.lanes)
        port = listener.getsockname()[1]
        host = socket.gethostname()
        try:
            # prefer a dialable address even on hosts with odd hostname setup
            socket.getaddrinfo(host, port)
        except socket.gaierror:
            host = "127.0.0.1"
        self._my_host_id = host_id or os.environ.get(HOST_ID_ENV) or host
        if "|" in self._my_host_id:
            raise CommunicatorError(
                f"host id {self._my_host_id!r} must not contain '|'"
            )
        if hier_mode != "0":
            # published BEFORE the dialable address: a completed socket mesh
            # then implies every topology-speaking peer's key is already
            # visible, so "key absent" after rendezvous is a deterministic
            # legacy/native-tier signal (fall back to flat), never a race.
            # The MODE rides along so an auto-vs-forced disagreement (which
            # would let one rank engage the two-level schedule while a peer
            # stays flat) fails loudly, like the lane-count hello.
            store.set(
                f"topo_{rank}", f"{hier_mode}|{self._my_host_id}".encode()
            )
        store.set(f"{rank}", f"{host}:{port}".encode())

        expected_inbound = (world_size - rank - 1) * self.lanes
        inbound: Dict[Tuple[int, int], socket.socket] = {}
        accept_err: List[BaseException] = []

        def _accept_all() -> None:
            try:
                listener.settimeout(timeout_s)
                for _ in range(expected_inbound):
                    conn, _ = listener.accept()
                    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    raw = _recv_exact(conn, 8, self._aborted, timeout_s)
                    (first,) = struct.unpack("<Q", raw)
                    if not first & _LANE_HELLO_FLAG:
                        # legacy 8-byte hello: a single-lane peer.  A lane
                        # disagreement is a config error — fail LOUDLY here
                        # instead of desynchronizing frames mid-collective.
                        if self.lanes != 1:
                            raise CommunicatorError(
                                f"lane-count mismatch: rank {first} has 1 "
                                f"lane, we have {self.lanes} "
                                f"({RING_LANES_ENV} must be uniform)"
                            )
                        inbound[(int(first), 0)] = conn
                        continue
                    peer_rank = int(first & ~_LANE_HELLO_FLAG)
                    tail = _recv_exact(conn, 24, self._aborted, timeout_s)
                    lane, peer_lanes, peer_floor = struct.unpack("<QQQ", tail)
                    if int(peer_lanes) != self.lanes:
                        raise CommunicatorError(
                            f"lane-count mismatch: rank {peer_rank} has "
                            f"{peer_lanes} lanes, we have {self.lanes} "
                            f"({RING_LANES_ENV} must be uniform)"
                        )
                    if int(peer_floor) != self.stripe_floor:
                        # the floor shapes the deterministic sub-frame
                        # split — a disagreement would desynchronize every
                        # striped frame
                        raise CommunicatorError(
                            f"stripe-floor mismatch: rank {peer_rank} has "
                            f"{peer_floor} bytes, we have "
                            f"{self.stripe_floor} ({RING_FRAME_KB_ENV} / "
                            "the net-emu profile must be uniform)"
                        )
                    inbound[(peer_rank, int(lane))] = conn
            except BaseException as e:  # noqa: BLE001
                accept_err.append(e)

        acceptor = threading.Thread(target=_accept_all, daemon=True)
        acceptor.start()

        try:
            for peer in range(rank):
                addr = store.get(f"{peer}", timeout=timeout_s).decode()
                peer_host, peer_port = addr.rsplit(":", 1)
                # kept for in-epoch lane re-dials (we are the dialer for
                # every peer with a lower rank)
                self._peer_addrs[peer] = (peer_host.strip("[]"), int(peer_port))
                for lane in range(self.lanes):
                    sock = socket.create_connection(
                        (peer_host.strip("[]"), int(peer_port)),
                        timeout=timeout_s,
                    )
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    if self.lanes == 1:
                        sock.sendall(struct.pack("<Q", rank))
                    else:
                        sock.sendall(
                            struct.pack(
                                "<QQQQ",
                                rank | _LANE_HELLO_FLAG,
                                lane,
                                self.lanes,
                                self.stripe_floor,
                            )
                        )
                    self.lane_socks[(peer, lane)] = sock

            acceptor.join(timeout=timeout_s + 5.0)
            if accept_err:
                raise CommunicatorError(
                    f"rank {rank} rendezvous accept failed: {accept_err[0]}"
                ) from accept_err[0]
            if acceptor.is_alive():
                raise CommunicatorError(f"rank {rank} rendezvous timed out")
            self.lane_socks.update(inbound)
        except BaseException:
            listener.close()
            raise
        # the listener stays open for the epoch: a transiently-reset lane
        # re-dials it mid-epoch (in-epoch lane recovery) instead of forcing
        # a full re-rendezvous; abort() closes it
        self._listener = listener
        self._timeout_s = timeout_s
        threading.Thread(
            target=self._reconn_accept,
            name=f"tpuft_lane_reconn_{rank}",
            daemon=True,
        ).start()

        for (peer, lane), sock in self.lane_socks.items():
            sock.setblocking(False)
            self._sock_key[sock] = (peer, lane)
            if lane == 0:
                self.peers[peer] = sock

        if hier_mode != "0":
            try:
                self._topo_rendezvous(store, hier_mode, timeout_s)
            except BaseException:
                self.abort()  # close the lane sockets a failed epoch leaves
                raise

    def _topo_rendezvous(self, store, hier_mode: str, timeout_s: float) -> None:
        """Host-group discovery + per-host shared-memory segment setup.

        Every topology-speaking rank published its host identity under
        ``topo_{rank}`` (the explicit ctor/``TORCHFT_HOST_ID`` override,
        else the host part of its advertised rendezvous address — same-IP
        grouping) BEFORE its dialable address, so with the socket mesh up
        every such key is already visible.  A peer with no key is a
        legacy/native-tier build or runs ``TORCHFT_HIERARCHICAL=0``: in
        "auto" mode the whole group deterministically falls back to the
        flat ring (every rank observes the same missing key); a FORCED "1"
        fails loudly instead — the operator demanded a schedule the peer
        cannot speak."""
        host_of = {self.rank: self._my_host_id}
        for peer in range(self.world_size):
            if peer == self.rank:
                continue
            # present-or-never (see publication ordering above), so the
            # non-blocking exists() is unambiguous: False IS "peer speaks
            # no topology", never "not yet".  A store ERROR must raise —
            # mapping it to the flat fallback could desync this rank's
            # schedule from peers that read the key fine.
            if not store.exists(f"topo_{peer}"):
                if hier_mode == "1":
                    raise CommunicatorError(
                        f"rank {peer} published no topology key — "
                        f"{HIERARCHICAL_ENV}=1 requires every replica "
                        "(and tier) to speak topology"
                    )
                logger.info(
                    "topology: rank %d speaks no topology; flat ring", peer
                )
                return
            peer_mode, peer_host = (
                store.get(f"topo_{peer}", timeout=timeout_s)
                .decode()
                .split("|", 1)
            )
            if peer_mode != hier_mode:
                # auto-vs-forced would leave the engaged/flat decision to
                # each rank's own gate — a silent schedule desync on any
                # topology where the two disagree.  Loud, like lanes.
                raise CommunicatorError(
                    f"{HIERARCHICAL_ENV} mismatch: rank {peer} runs "
                    f"{peer_mode!r}, we run {hier_mode!r} (must be uniform)"
                )
            host_of[peer] = peer_host
        topo = _HostTopology(host_of, self.rank)
        if hier_mode != "1" and not topo.worth_it():
            return  # auto: flat topology, keep the legacy ring
        if platform.machine().lower() not in ("x86_64", "amd64"):
            # the shm seqlock's publish-after-payload ordering leans on
            # x86-TSO for CROSS-PROCESS members; weaker memory models could
            # let a reader see the sequence before the payload lands
            if hier_mode == "1":
                raise CommunicatorError(
                    "the shared-memory intra-host transport requires a TSO "
                    f"architecture (x86_64); this host is "
                    f"{platform.machine()!r} — unset {HIERARCHICAL_ENV}"
                )
            logger.warning(
                "topology: non-TSO architecture %s; flat ring",
                platform.machine(),
            )
            return
        self.topo = topo
        if topo.local_world == 1:
            return  # leader-only host: the cross-host ring needs no shm
        # the leader's slot size wins so an intra-host TORCHFT_SHM_SLOT_MB
        # disagreement can corrupt nothing — members adopt it from the key
        if topo.is_leader:
            slot_bytes = _shm_slot_bytes()
            seg, path = _ShmSeg.create(topo.local_world, slot_bytes)
            store.set(f"shmseg_{topo.leader}", f"{path}|{slot_bytes}".encode())
            try:
                for member in topo.local[1:]:
                    store.get(f"shmok_{member}", timeout=timeout_s)
            finally:
                # unlinked-after-map: from here the segment exists only as
                # live mappings; a killed replica leaks nothing in /dev/shm
                try:
                    os.unlink(path)
                except OSError:
                    pass
            self.shm = seg
        else:
            raw = store.get(f"shmseg_{topo.leader}", timeout=timeout_s).decode()
            path, slot_raw = raw.rsplit("|", 1)
            self.shm = _ShmSeg.attach(path, topo.local_world, int(slot_raw))
            store.set(f"shmok_{self.rank}", b"1")

    # -- intra-host shared-memory collectives --------------------------------

    def _shm_chunks(self, nbytes: int) -> List[Tuple[int, int]]:
        assert self.shm is not None
        cap = self.shm.slot_bytes
        if nbytes == 0:
            return [(0, 0)]
        return [(s, min(s + cap, nbytes)) for s in range(0, nbytes, cap)]

    def shm_reduce(self, flat: np.ndarray, op: ReduceOp, deadline: float) -> None:
        """Intra-host reduce into the host leader's ``flat``, in FIXED
        ascending global-rank order (run-to-run deterministic: the leader's
        own buffer is the accumulator, members fold in by local index).
        Members' buffers are left untouched; lock-step per chunk — the
        leader's ack republish gates each member's next chunk."""
        seg, topo = self.shm, self.topo
        assert topo is not None
        if seg is None or topo.local_world == 1:
            return
        view = _bytes_view(flat)
        chunks = self._shm_chunks(view.nbytes)
        base = seg._seq
        itemsize = flat.dtype.itemsize
        me = topo.local_index
        if me == 0:
            acc = flat.reshape(-1)
            for c, (s, e) in enumerate(chunks):
                lo, hi = s // itemsize, e // itemsize
                for j in range(1, topo.local_world):
                    seg.wait(j, base + c + 1, deadline, self._aborted)
                    incoming = np.frombuffer(
                        seg.view(j, e - s), dtype=flat.dtype
                    )
                    _reduce_into(op, acc[lo:hi], incoming)
                    self.shm_rx_bytes += e - s
                seg.post(0, base + c + 1, None)  # ack: slots may be reused
        else:
            for c, (s, e) in enumerate(chunks):
                seg.post(me, base + c + 1, view[s:e])
                self.shm_tx_bytes += e - s
                seg.wait(0, base + c + 1, deadline, self._aborted)
        seg._seq = base + len(chunks)

    def shm_bcast(
        self, flat: np.ndarray, deadline: float, src_idx: int = 0
    ) -> None:
        """Intra-host broadcast of ``flat`` from local member ``src_idx``
        (the leader by default) into every other member's ``flat``."""
        seg, topo = self.shm, self.topo
        assert topo is not None
        if seg is None or topo.local_world == 1:
            return
        view = _bytes_view(flat)
        chunks = self._shm_chunks(view.nbytes)
        base = seg._seq
        me = topo.local_index
        readers = [j for j in range(topo.local_world) if j != src_idx]
        if me == src_idx:
            for c, (s, e) in enumerate(chunks):
                seg.post(src_idx, base + c + 1, view[s:e])
                self.shm_tx_bytes += e - s
                for j in readers:
                    seg.wait(j, base + c + 1, deadline, self._aborted)
        else:
            for c, (s, e) in enumerate(chunks):
                seg.wait(src_idx, base + c + 1, deadline, self._aborted)
                view[s:e] = seg.view(src_idx, e - s)
                self.shm_rx_bytes += e - s
                seg.post(me, base + c + 1, None)  # ack
        seg._seq = base + len(chunks)

    def shm_gather(
        self, arr: np.ndarray, deadline: float
    ) -> Optional[List[np.ndarray]]:
        """Intra-host gather: the leader returns every local member's
        buffer (local-group order, its own included); members return None.
        Same shape/dtype on every member."""
        seg, topo = self.shm, self.topo
        assert topo is not None
        if seg is None or topo.local_world == 1:
            return [arr] if topo.is_leader else None
        view = _bytes_view(arr)
        chunks = self._shm_chunks(view.nbytes)
        base = seg._seq
        me = topo.local_index
        out: Optional[List[np.ndarray]] = None
        if me == 0:
            out = [arr] + [
                np.empty_like(arr) for _ in range(topo.local_world - 1)
            ]
            views = [_bytes_view(a) for a in out]
            for c, (s, e) in enumerate(chunks):
                for j in range(1, topo.local_world):
                    seg.wait(j, base + c + 1, deadline, self._aborted)
                    views[j][s:e] = seg.view(j, e - s)
                    self.shm_rx_bytes += e - s
                seg.post(0, base + c + 1, None)  # ack
        else:
            for c, (s, e) in enumerate(chunks):
                seg.post(me, base + c + 1, view[s:e])
                self.shm_tx_bytes += e - s
                seg.wait(0, base + c + 1, deadline, self._aborted)
        seg._seq = base + len(chunks)
        return out

    # -- lane lookups --------------------------------------------------------

    def lane_sock(self, peer: int, lane: int) -> socket.socket:
        return self.lane_socks[(peer, lane)]

    def _alive_lanes(self, peer: int) -> List[int]:
        dead = self.dead_lanes.get(peer, ())
        return [ln for ln in range(self.lanes) if ln not in dead]

    def _lane_route(self, peer: int, lane: int) -> int:
        """Transport lane actually carrying logical lane ``lane`` to
        ``peer``: identity while the lane lives; after an agreed failover,
        the lowest surviving lane.  Both endpoints derive the dead set from
        the same failover handshake, so routed frames stay matched — the
        LOGICAL ``_lane_parts`` split (and therefore the reduction math)
        never changes, only the transport assignment."""
        dead = self.dead_lanes.get(peer)
        if not dead or lane not in dead:
            return lane
        alive = self._alive_lanes(peer)
        if not alive:
            raise PeerGoneError(f"all lanes to rank {peer} are dead")
        return alive[0]

    def p2p_sock(self, peer: int) -> socket.socket:
        """The designated point-to-point lane socket (last lane; the one and
        only socket at lanes == 1).  Routed around failed-over lanes."""
        return self.lane_socks[(peer, self._lane_route(peer, self.p2p_lane))]

    # -- in-epoch lane recovery ----------------------------------------------

    def _reconn_accept(self) -> None:
        """Accept in-epoch lane re-dials for the life of the mesh.

        A reconnect hello is always the 32-byte extended form with
        ``_LANE_RECONN_FLAG`` set; anything else is dropped (stray dials).
        The accepted socket is parked in ``_pending_reconn`` for the
        recovering op thread to pick up — the resync handshake runs there,
        never here, so this loop can stay dumb and lock-free."""
        try:
            self._listener.settimeout(0.25)
        except OSError:
            return
        while not self._aborted.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                conn.settimeout(5.0)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                raw = _recv_exact(conn, 8, self._aborted, 5.0)
                (first,) = struct.unpack("<Q", raw)
                if not first & _LANE_RECONN_FLAG:
                    conn.close()
                    continue
                peer_rank = int(
                    first & ~(_LANE_HELLO_FLAG | _LANE_RECONN_FLAG)
                )
                tail = _recv_exact(conn, 24, self._aborted, 5.0)
                lane, peer_lanes, peer_floor = struct.unpack("<QQQ", tail)
                if (
                    not 0 <= peer_rank < self.world_size
                    or int(peer_lanes) != self.lanes
                    or int(peer_floor) != self.stripe_floor
                    or not 0 <= int(lane) < self.lanes
                ):
                    conn.close()
                    continue
            except (OSError, CommunicatorError):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            with self._reconn_cv:
                stale = self._pending_reconn.pop((peer_rank, int(lane)), None)
                if stale is not None:
                    try:
                        stale.close()
                    except OSError:
                        pass
                self._pending_reconn[(peer_rank, int(lane))] = conn
                self._reconn_cv.notify_all()

    # -- low-level duplex IO -------------------------------------------------

    def abort(self) -> None:
        self._aborted.set()
        if self.shm is not None:
            # latch the abort into the shared segment so local members
            # blocked in an shm spin (possibly in OTHER processes) unblock
            # with CommunicatorAborted, same poison path as the sockets
            self.shm.set_abort()
        listener = getattr(self, "_listener", None)
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        with self._reconn_cv:
            pending, self._pending_reconn = dict(self._pending_reconn), {}
            self._reconn_cv.notify_all()
        for sock in pending.values():
            try:
                sock.close()
            except OSError:
                pass
        for sock in self.lane_socks.values():
            try:
                sock.close()
            except OSError:
                pass

    def _check_abort(self) -> None:
        if self._aborted.is_set():
            raise CommunicatorAborted("communicator aborted")

    def recv_dynamic_into(
        self, src: int, tag: int, view: memoryview, deadline: float
    ) -> int:
        """Header-aware zero-copy receive: payload lands in ``view`` (cap
        semantics — payload may be smaller); returns the payload size."""
        sock = self.p2p_sock(src)

        def _recv_some(into: memoryview) -> int:
            while True:
                self._check_abort()
                if time.monotonic() > deadline:
                    raise TimeoutError("recv_dynamic_into timed out")
                readable, _, _ = select.select([sock], [], [], 0.1)
                if not readable:
                    continue
                try:
                    n = sock.recv_into(into)
                except BlockingIOError:
                    continue
                if n == 0:
                    raise PeerGoneError(f"connection to rank {src} closed")
                return n

        hdr = bytearray(_HDR.size)
        off = 0
        while off < len(hdr):
            off += _recv_some(memoryview(hdr)[off:])
        nbytes, rtag = _HDR.unpack(bytes(hdr))
        if rtag != tag:
            raise CommunicatorError(
                f"tag mismatch from rank {src}: got {rtag}, want {tag}"
            )
        if nbytes > len(view):
            # drain into scratch so the stream stays frame-aligned, THEN fail
            scratch = bytearray(min(1 << 20, nbytes))
            remaining = nbytes
            while remaining > 0:
                got = _recv_some(memoryview(scratch)[: min(len(scratch), remaining)])
                remaining -= got
            raise CommunicatorError(
                f"recv buffer too small: payload {nbytes} > cap {len(view)}"
            )
        off = 0
        while off < nbytes:
            off += _recv_some(view[off:nbytes])
        return nbytes

    def recv_dynamic(self, src: int, tag: int, deadline: float) -> bytes:
        """Receive one frame from ``src`` without knowing its size upfront —
        the frame header carries nbytes, so this pairs with any plain send."""
        sock = self.p2p_sock(src)

        def _recv_some(view: memoryview) -> int:
            while True:
                self._check_abort()
                if time.monotonic() > deadline:
                    raise TimeoutError("recv_dynamic timed out")
                readable, _, _ = select.select([sock], [], [], 0.1)
                if not readable:
                    continue
                try:
                    n = sock.recv_into(view)
                except BlockingIOError:
                    continue
                if n == 0:
                    raise PeerGoneError(f"connection to rank {src} closed")
                return n

        hdr = bytearray(_HDR.size)
        off = 0
        while off < len(hdr):
            off += _recv_some(memoryview(hdr)[off:])
        nbytes, rtag = _HDR.unpack(bytes(hdr))
        if rtag != tag:
            raise CommunicatorError(
                f"tag mismatch from rank {src}: got {rtag}, want {tag}"
            )
        buf = bytearray(nbytes)
        off = 0
        while off < nbytes:
            off += _recv_some(memoryview(buf)[off:])
        return bytes(buf)

    @_spanned("comm::lane_window")
    def exchange(
        self,
        sends: List[Tuple[int, int, memoryview]],
        recvs: Sequence[Tuple],
        deadline: float,
        lane: Optional[int] = None,
    ) -> None:
        """Concurrently push ``sends`` and drain ``recvs``.

        ``sends`` entries are ``(peer_rank, tag, payload_view)``; ``recvs``
        entries additionally accept an optional 4th element — an
        ``on_part(start, stop)`` callable invoked (on the op thread) as each
        completed byte range of the payload lands, which is what lets the
        ring reduce a lane's sub-chunk while the other lanes still stream.

        With ``lane=None`` every frame is striped across the mesh's lanes
        by the deterministic ``_lane_parts`` split (both endpoints compute
        the identical split from the frame length, and sub-frame boundaries
        are element-aligned, so results are bit-identical at any lane
        count); pass an explicit ``lane`` to pin a whole frame to one
        connection (the point-to-point path).

        Concurrent duplex IO (select-driven, non-blocking sockets, one loop
        multiplexing all lanes) is what makes ring steps deadlock-free:
        every rank sends to its right neighbor while receiving from its
        left without ordering constraints.

        Gray-failure resilience (striped path only, ``lane=None``): a
        transient connection reset on one lane re-dials with bounded
        jittered backoff (``TORCHFT_LANE_RETRIES`` /
        ``TORCHFT_LANE_BACKOFF_MS``) and replays the sub-frames the reset
        swallowed (every completed sub-frame of the CURRENT exchange is
        retained for replay; resets reaching deeper poison the epoch as
        before).  If re-dial fails, the two endpoints agree — via a control
        frame on a surviving lane — on the dead lane's outstanding
        sub-frames and re-route them; the epoch only poisons when every
        lane to a peer is dead.  Point-to-point ops (explicit ``lane``)
        keep the peer-scoped fail-stop contract the striped heal relies on.
        """
        emu = self._emu
        recovery_ok = lane is None

        def _parts(nbytes: int) -> List[Tuple[int, int, int]]:
            if lane is not None:
                return [(lane, 0, nbytes)]
            return _lane_parts(nbytes, self.lanes, self.stripe_floor)

        # per-socket FIFO of outgoing sub-frames; each frame keeps its
        # original (header, payload) so a lane reset can replay it whole,
        # plus the live buffer list carrying sub-frames strictly in order
        ctx = _ExchangeCtx()
        send_q, recv_q = ctx.send_q, ctx.recv_q
        for peer, tag, view in sends:
            for ln, start, stop in _parts(len(view)):
                header = _HDR.pack(stop - start, tag)
                key = (peer, self._lane_route(peer, ln))
                send_q.setdefault(key, []).append(
                    _mk_frame(header, view[start:stop] if stop > start else None)
                )
        for entry in recvs:
            peer, tag, view = entry[0], entry[1], entry[2]
            on_part = entry[3] if len(entry) > 3 else None
            for ln, start, stop in _parts(len(view)):
                key = (peer, self._lane_route(peer, ln))
                recv_q.setdefault(key, []).append(
                    {
                        "view": view[start:stop],
                        "tag": tag,
                        "start": start,
                        "stop": stop,
                        "on_part": on_part,
                    }
                )

        frame_gates = ctx.frame_gates
        if emu is not None:
            for key in send_q:
                # half-RTT before the first frame's first byte leaves; the
                # gate re-arms as each subsequent frame reaches the head
                frame_gates[key] = emu.frame_gate()

        partition_noted: set = set()

        def _blocked(key: Tuple[int, int]) -> bool:
            prog = self.faults
            if prog is None or not prog.partitions(self.rank, key[0]):
                return False
            if key[0] not in partition_noted:
                partition_noted.add(key[0])
                self.faults_injected += 1
                logger.warning(
                    "fault injection: partition mask blackholes rank %d <-> %d",
                    self.rank,
                    key[0],
                )
            return True

        while send_q or recv_q or ctx.pending_failover or ctx.dying:
            self._check_abort()
            if time.monotonic() > deadline:
                raise TimeoutError("collective exchange timed out")
            failover_peers = {k[0] for k in ctx.pending_failover}
            rlist = [
                self.lane_socks[k]
                for k in self.lane_socks
                if not _blocked(k)
                and (
                    k in recv_q
                    or k in ctx.dying
                    or k[0] in failover_peers
                    or (k in ctx.recv_st and ctx.recv_st[k]["hdr"])
                )
            ]
            wlist = [
                self.lane_socks[k]
                for k in send_q
                if k in self.lane_socks
                and not _blocked(k)
                and k not in ctx.dying
            ]
            if not rlist and not wlist:
                # everything outstanding is blackholed (partition mask) or
                # parked on a failover handshake: wait out the deadline
                time.sleep(0.01)
                continue
            readable, writable, _ = select.select(rlist, wlist, [], 0.1)

            paced_block = False
            faulted: List[Tuple[Tuple[int, int], BaseException]] = []
            for sock in writable:
                key = self._sock_key.get(sock)
                if key is None:
                    continue
                frames = send_q.get(key)
                if frames is None:
                    continue
                ln = key[1]
                if time.monotonic() < frame_gates.get(key, 0.0):
                    paced_block = True
                    self.lane_stalls[ln] += 1
                    continue
                try:
                    while frames:
                        frame = frames[0]
                        bufs = frame["bufs"]
                        # len 0 = a zero-payload frame's body (e.g. the
                        # empty ring chunk at ws=2): nothing to pace
                        while bufs and len(bufs[0]) == 0:
                            bufs.pop(0)
                        if not bufs:
                            frames.pop(0)
                            if not frame["ctrl"]:
                                ctx.sent_log.setdefault(key, []).append(frame)
                                self._tx_seq[key] = (
                                    self._tx_seq.get(key, 0) + 1
                                )
                            if frames and emu is not None:
                                frame_gates[key] = emu.frame_gate()
                                break
                            continue
                        verdict = self._fault_gate(key, frame, frame_gates)
                        if verdict == "reset":
                            # half-close choreography: FIN our send side,
                            # park the unsent frames, and keep DRAINING
                            # until the peer's EOF comes back — an abortive
                            # close would destroy flushed-but-unread bytes
                            # and push the loss beyond the replay log
                            try:
                                sock.shutdown(socket.SHUT_WR)
                            except OSError:
                                pass
                            ctx.dying.add(key)
                            ctx.dying_sends[key] = send_q.pop(key, [])
                            logger.warning(
                                "fault injection: reset lane %s", key
                            )
                            break
                        if verdict == "stall":
                            paced_block = True
                            self.lane_stalls[ln] += 1
                            break
                        chunk = bufs[0]
                        if emu is not None:
                            allowed = emu.allow(len(chunk), stream=key)
                            if allowed <= 0:
                                paced_block = True
                                self.lane_stalls[ln] += 1
                                break
                            chunk = chunk[:allowed]
                        sent = sock.send(chunk)
                        if emu is not None:
                            emu.consume(sent, stream=key)
                        self.lane_tx_bytes[ln] += sent
                        if sent == len(bufs[0]):
                            bufs.pop(0)
                        else:
                            bufs[0] = bufs[0][sent:]
                            break
                except BlockingIOError:
                    self.lane_stalls[ln] += 1
                except PeerGoneError as e:
                    faulted.append((key, e))
                    continue
                except OSError as e:
                    faulted.append(
                        (key, PeerGoneError(f"send to rank {key[0]} failed: {e}"))
                    )
                    continue
                if frames is not None and not frames:
                    send_q.pop(key, None)

            for sock in readable:
                key = self._sock_key.get(sock)
                if key is None:
                    continue
                if any(k == key for k, _ in faulted):
                    continue
                peer, ln = key
                # drain the socket fully per readiness event (sub-frames
                # arrive back to back): one recv per select round would
                # multiply the syscall count and cap the aggregate rate
                try:
                    while True:
                        # stop at the exchange's expectation boundary: with
                        # nothing expected and no frame mid-flight, reading
                        # on would eat the NEXT exchange's bytes (only a
                        # pending failover justifies listening for a
                        # peer's control frame beyond that)
                        if (
                            key not in ctx.recv_st
                            and not recv_q.get(key)
                            and key not in ctx.dying
                            and key[0]
                            not in {k[0] for k in ctx.pending_failover}
                        ):
                            break
                        st = ctx.recv_st.setdefault(
                            key, {"hdr": bytearray(), "off": 0, "exp": None}
                        )
                        if len(st["hdr"]) < _HDR.size:
                            chunk = sock.recv(_HDR.size - len(st["hdr"]))
                            if not chunk:
                                raise PeerGoneError(
                                    f"connection to rank {peer} closed"
                                )
                            st["hdr"] += chunk
                            if len(st["hdr"]) == _HDR.size:
                                nbytes, tag = _HDR.unpack(bytes(st["hdr"]))
                                if tag == _LANE_CTRL_TAG:
                                    if nbytes != _LANE_CTRL.size:
                                        raise CommunicatorError(
                                            f"bad lane ctrl frame from rank "
                                            f"{peer}: {nbytes} bytes"
                                        )
                                    st["exp"] = {
                                        "view": memoryview(
                                            bytearray(_LANE_CTRL.size)
                                        ),
                                        "ctrl": True,
                                    }
                                else:
                                    queue_ = recv_q.get(key)
                                    if not queue_:
                                        raise CommunicatorError(
                                            f"unexpected frame tag {tag} "
                                            f"from rank {peer} (lane {ln})"
                                        )
                                    exp = queue_[0]
                                    if tag != exp["tag"]:
                                        raise CommunicatorError(
                                            f"tag mismatch from rank {peer}: "
                                            f"got {tag}, want {exp['tag']}"
                                        )
                                    if nbytes != len(exp["view"]):
                                        raise CommunicatorError(
                                            f"size mismatch from rank {peer}: "
                                            f"got {nbytes}, want "
                                            f"{len(exp['view'])} (lane {ln})"
                                        )
                                    st["exp"] = exp
                        elif st["off"] < len(st["exp"]["view"]):
                            n = sock.recv_into(st["exp"]["view"][st["off"] :])
                            if n == 0:
                                raise PeerGoneError(
                                    f"connection to rank {peer} closed"
                                )
                            st["off"] += n
                            if not st["exp"].get("ctrl"):
                                self.lane_rx_bytes[ln] += n
                        # complete once the header arrived and the payload
                        # (possibly zero-length) is fully received
                        if (
                            len(st["hdr"]) == _HDR.size
                            and st["off"] == len(st["exp"]["view"])
                        ):
                            exp = st["exp"]
                            ctx.recv_st.pop(key, None)
                            if exp.get("ctrl"):
                                _kind, dead_ln, peer_rx = _LANE_CTRL.unpack(
                                    bytes(exp["view"])
                                )
                                self._handle_lane_ctrl(
                                    peer, int(dead_ln), int(peer_rx), ctx
                                )
                            else:
                                queue_ = recv_q[key]
                                queue_.pop(0)
                                if not queue_:
                                    del recv_q[key]
                                self._rx_seq[key] = (
                                    self._rx_seq.get(key, 0) + 1
                                )
                                if exp["on_part"] is not None:
                                    exp["on_part"](exp["start"], exp["stop"])
                except BlockingIOError:
                    pass
                except (OSError, PeerGoneError) as e:
                    faulted.append(
                        (
                            key,
                            e
                            if isinstance(e, PeerGoneError)
                            else PeerGoneError(str(e)),
                        )
                    )

            for key, exc in faulted:
                if not recovery_ok:
                    raise exc
                self._lane_fault(key, exc, ctx, deadline)

            if paced_block:
                # socket writable but the pacer denied bytes — select would
                # return immediately and spin the op thread hot
                time.sleep(0.0005)

    # -- gray-failure recovery internals -------------------------------------

    def _fault_gate(
        self, key: Tuple[int, int], frame: dict, frame_gates: Dict
    ) -> Optional[str]:
        """Evaluate the armed fault program once per sub-frame, at the
        moment the frame reaches the head of its lane queue (before its
        first byte leaves).  Returns 'reset' (connection torn down),
        'stall' (a loss-retransmit or slow-NIC window was injected as a
        frame gate), or None (clean)."""
        prog = self.faults
        if prog is None or frame["checked"]:
            return None
        frame["checked"] = True
        if not prog.active():
            return None
        if prog.reset_once >= 0 and not self._reset_once_fired:
            self._fault_frames += 1
            if self._fault_frames > prog.reset_once:
                self._reset_once_fired = True
                self.faults_injected += 1
                return "reset"
        if prog.reset > 0 and self._fault_rng.random() < prog.reset:
            self.faults_injected += 1
            return "reset"
        if prog.loss > 0 and self._fault_rng.random() < prog.loss:
            # a dropped sub-frame costs one retransmit timeout: the sender
            # stalls ~2xRTT before the bytes go out — the TCP-on-lossy-link
            # throughput penalty without breaking the reliable stream
            rtt = self._emu.rtt_s if self._emu is not None else 0.0
            self.faults_injected += 1
            frame_gates[key] = time.monotonic() + max(2.0 * rtt, 0.02)
            return "stall"
        if prog.stall_p > 0 and self._fault_rng.random() < prog.stall_p:
            self.faults_injected += 1
            frame_gates[key] = time.monotonic() + prog.stall_ms / 1000.0
            return "stall"
        return None

    def _lane_fault(
        self,
        key: Tuple[int, int],
        exc: BaseException,
        ctx: _ExchangeCtx,
        deadline: float,
    ) -> None:
        """One lane to a live peer died mid-exchange: re-dial it with
        bounded jittered backoff and replay what the reset swallowed; if
        that fails, fail the lane over to a survivor.  Raises (poisoning
        the epoch) only when no lane to the peer survives or the reset ate
        sub-frames older than the current collective."""
        if key in ctx.dying:
            # we half-closed this lane ourselves (injected reset) and have
            # now drained it to EOF: un-park the sends so recovery replays
            # them like any other outstanding frames
            ctx.dying.discard(key)
            parked = ctx.dying_sends.pop(key, [])
            if parked:
                ctx.send_q[key] = parked + ctx.send_q.get(key, [])
        old = self.lane_socks.get(key)
        if old is not None:
            self._sock_key.pop(old, None)
            try:
                old.close()
            except OSError:
                pass
        # discard partial receive state: post-resync the peer re-sends the
        # interrupted sub-frame whole
        ctx.recv_st.pop(key, None)
        logger.warning(
            "lane %s: transient fault (%s); attempting in-epoch recovery",
            key,
            exc,
        )
        if self._try_reconnect(key, ctx, deadline):
            self.lane_reconnects += 1
            if self._flight:
                self._flight.record(
                    FlightEvent.LANE_RECONNECT, peer=key[0], lane=key[1]
                )
            logger.info("lane %s: reconnected in-epoch", key)
            return
        self._initiate_failover(key, ctx, exc)

    def _try_reconnect(
        self, key: Tuple[int, int], ctx: _ExchangeCtx, deadline: float
    ) -> bool:
        """Bounded re-dial of one lane.  The endpoint that dialed the lane
        at rendezvous (the higher rank) re-dials the peer's epoch listener;
        the other side waits for the accept thread to park the replacement.
        On success both run the resync handshake and replay."""
        peer, ln = key
        retries = self.lane_retries
        if retries <= 0:
            return False
        if self.rank > peer:
            addr = self._peer_addrs.get(peer)
            if addr is None:
                return False
            for attempt in range(retries):
                delay = (
                    self.lane_backoff_s
                    * (2 ** attempt)
                    * (0.5 + self._fault_rng.random())
                )
                if self._aborted.wait(delay):
                    raise CommunicatorAborted("communicator aborted")
                if time.monotonic() > deadline:
                    return False
                sock: Optional[socket.socket] = None
                try:
                    sock = socket.create_connection(
                        addr,
                        timeout=min(
                            5.0, max(0.1, deadline - time.monotonic())
                        ),
                    )
                    sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                    sock.settimeout(5.0)
                    sock.sendall(
                        struct.pack(
                            "<QQQQ",
                            self.rank
                            | _LANE_HELLO_FLAG
                            | _LANE_RECONN_FLAG,
                            ln,
                            self.lanes,
                            self.stripe_floor,
                        )
                    )
                    sock.sendall(
                        _LANE_RESYNC.pack(
                            self._tx_seq.get(key, 0), self._rx_seq.get(key, 0)
                        )
                    )
                    raw = _recv_exact(
                        sock, _LANE_RESYNC.size, self._aborted, 5.0
                    )
                    _peer_tx, peer_rx = _LANE_RESYNC.unpack(raw)
                except (OSError, CommunicatorError):
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                    continue
                self._install_lane(key, sock, int(peer_rx), ctx)
                return True
            return False
        # the peer re-dials us; its worst-case retry schedule bounds our
        # wait (plus slack so a slow final attempt still lands)
        window = self.lane_backoff_s * 1.5 * (2 ** retries) + 0.25
        wait_deadline = min(deadline, time.monotonic() + window)
        with self._reconn_cv:
            while key not in self._pending_reconn:
                if self._aborted.is_set():
                    raise CommunicatorAborted("communicator aborted")
                remaining = wait_deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._reconn_cv.wait(min(remaining, 0.1))
            sock = self._pending_reconn.pop(key)
        try:
            sock.settimeout(5.0)
            raw = _recv_exact(sock, _LANE_RESYNC.size, self._aborted, 5.0)
            _peer_tx, peer_rx = _LANE_RESYNC.unpack(raw)
            sock.sendall(
                _LANE_RESYNC.pack(
                    self._tx_seq.get(key, 0), self._rx_seq.get(key, 0)
                )
            )
        except (OSError, CommunicatorError):
            try:
                sock.close()
            except OSError:
                pass
            return False
        self._install_lane(key, sock, int(peer_rx), ctx)
        return True

    def _install_lane(
        self,
        key: Tuple[int, int],
        sock: socket.socket,
        peer_rx: int,
        ctx: _ExchangeCtx,
    ) -> None:
        """Swap a re-dialed socket into the lane maps and replay the
        sub-frames the reset swallowed (peer_rx = how many completed data
        sub-frames the peer HAS; everything we counted beyond that is
        re-sent whole, byte-identical, from the exchange's sent log)."""
        peer, ln = key
        missing = self._tx_seq.get(key, 0) - peer_rx
        log = ctx.sent_log.get(key, [])
        if missing < 0 or missing > len(log):
            try:
                sock.close()
            except OSError:
                pass
            raise CommunicatorError(
                f"lane {key} reset lost {missing} sub-frames beyond the "
                "current collective; cannot replay in-epoch"
            )
        q = ctx.send_q.setdefault(key, [])
        if missing:
            replay = log[-missing:]
            del log[-missing:]
            q[:0] = replay
            self._tx_seq[key] = peer_rx
        # re-arm every queued frame whole: the head may have been
        # part-written when the lane died, and the peer discarded its
        # partial receive state at resync
        for frame in q:
            _rearm_frame(frame)
        if not q:
            ctx.send_q.pop(key, None)
        sock.setblocking(False)
        self.lane_socks[key] = sock
        self._sock_key[sock] = key
        if ln == 0:
            self.peers[peer] = sock
        ctx.frame_gates.pop(key, None)

    def _initiate_failover(
        self, key: Tuple[int, int], ctx: _ExchangeCtx, exc: BaseException
    ) -> None:
        """Re-dial failed: park the dead lane's outstanding traffic and
        tell the peer (a control frame on the lowest surviving lane, with
        our completed-rx count) so both sides can agree on what to replay
        where.  Raises PeerGoneError when no lane survives — the epoch
        poisons only then."""
        peer, ln = key
        if key in ctx.dying:
            ctx.dying.discard(key)
            parked = ctx.dying_sends.pop(key, [])
            if parked:
                ctx.send_q[key] = parked + ctx.send_q.get(key, [])
        self.lane_socks.pop(key, None)
        alive = [
            l
            for l in self._alive_lanes(peer)
            if l != ln
            and (peer, l) in self.lane_socks
            and (peer, l) not in ctx.pending_failover
        ]
        if not alive:
            raise PeerGoneError(
                f"rank {peer} unreachable on every lane: {exc}"
            )
        surv = alive[0]
        ent = ctx.pending_failover.get(key)
        if ent is None:
            ent = ctx.pending_failover[key] = {
                "surv": surv,
                "peer_rx": None,
                "sent_ctrl": False,
                "sends": [],
                "recvs": [],
            }
        ent["sends"].extend(ctx.send_q.pop(key, []))
        ent["recvs"].extend(ctx.recv_q.pop(key, []))
        ctx.recv_st.pop(key, None)
        if not ent["sent_ctrl"]:
            blob = _LANE_CTRL.pack(1, ln, self._rx_seq.get(key, 0))
            raw = _HDR.pack(len(blob), _LANE_CTRL_TAG) + blob
            ctx.send_q.setdefault((peer, surv), []).append(
                _mk_frame(raw, None, ctrl=True)
            )
            ent["sent_ctrl"] = True
            logger.warning(
                "lane %s dead after retries (%s); failing over to lane %d",
                key,
                exc,
                surv,
            )
        if ent["peer_rx"] is not None:
            self._finalize_failover(key, ctx)

    def _handle_lane_ctrl(
        self, peer: int, dead_ln: int, peer_rx: int, ctx: _ExchangeCtx
    ) -> None:
        """The peer declared one of our shared lanes dead.  Adopt (close
        our end, park, answer with our own declaration) if we had not
        noticed, then finalize once both declarations are in hand."""
        key = (peer, dead_ln)
        if dead_ln in self.dead_lanes.get(peer, ()):
            return  # duplicate declaration for an already-buried lane
        ent = ctx.pending_failover.get(key)
        if ent is None:
            sock = self.lane_socks.get(key)
            if sock is not None:
                self._sock_key.pop(sock, None)
                try:
                    sock.close()
                except OSError:
                    pass
            try:
                self._initiate_failover(
                    key, ctx, CommunicatorError("peer declared lane dead")
                )
            except PeerGoneError as e:
                # no survivor left: total peer loss, poison the epoch (the
                # caller's recv loop must not mistake this for a
                # recoverable fault on the lane that carried the ctrl)
                raise CommunicatorError(str(e)) from e
            ent = ctx.pending_failover[key]
        ent["peer_rx"] = peer_rx
        if ent["sent_ctrl"]:
            self._finalize_failover(key, ctx)

    def _finalize_failover(self, key: Tuple[int, int], ctx: _ExchangeCtx) -> None:
        """Both endpoints agreed the lane is dead: replay the sub-frames
        the peer is missing and re-route all parked traffic onto the
        surviving lane.  The LOGICAL ``_lane_parts`` split is untouched —
        only transport assignment changes — so results stay bit-identical."""
        peer, ln = key
        ent = ctx.pending_failover.pop(key)
        surv_key = (peer, ent["surv"])
        if surv_key not in self.lane_socks:
            # the survivor chosen at initiate died while the handshake was
            # in flight (a second transient fault in one exchange): poison
            # NOW rather than stranding the re-routed frames on a dead
            # queue until the op deadline.  Concurrent multi-lane faults
            # stay fail-stop — exactly the legacy contract.
            raise CommunicatorError(
                f"lane {key} failover target lane {ent['surv']} died "
                "mid-handshake; poisoning the epoch"
            )
        missing = self._tx_seq.get(key, 0) - ent["peer_rx"]
        log = ctx.sent_log.get(key, [])
        if missing < 0 or missing > len(log):
            raise CommunicatorError(
                f"lane {key} failover lost {missing} sub-frames beyond the "
                "current collective; cannot replay"
            )
        replay: List[dict] = []
        if missing:
            replay = log[-missing:]
            del log[-missing:]
            self._tx_seq[key] = ent["peer_rx"]
        moved = replay + ent["sends"]
        for frame in moved:
            _rearm_frame(frame)
        if moved:
            ctx.send_q.setdefault(surv_key, []).extend(moved)
        if ent["recvs"]:
            ctx.recv_q.setdefault(surv_key, []).extend(ent["recvs"])
        self.dead_lanes.setdefault(peer, set()).add(ln)
        self.lane_failovers += 1
        if self._flight:
            self._flight.record(
                FlightEvent.LANE_FAILOVER, peer=peer, lane=ln, surv=ent["surv"]
            )
        ctx.frame_gates.pop(key, None)
        logger.warning(
            "lane %s failed over: %d outstanding sub-frames re-routed to "
            "lane %d",
            key,
            len(moved) + len(ent["recvs"]),
            ent["surv"],
        )

    def striped_drain(
        self,
        chunk_views: List[memoryview],
        expected: Dict[int, List[int]],
        orphans: List[int],
        chunk_tag: Callable[[int], int],
        ctrl_tag: int,
        make_need: Callable[[List[int]], bytes],
        done_blob: bytes,
        deadline: float,
    ) -> Dict[str, object]:
        """Concurrently drain disjoint chunk frames from MANY peers into one
        assembly buffer — the striped-heal receive path.

        Per-chunk recv ops would serialize on the op thread and cap a
        multi-source heal at one link's bandwidth; this runs as ONE op,
        select-driven across every source socket at once (the same duplex
        pattern as :meth:`exchange`), so P paced senders aggregate to ~P
        links.

        ``chunk_views`` maps each chunk index to the writable buffer slice
        its bytes land in (usually a range of a preallocated final array —
        the heal has no reassembly pass).  ``expected`` maps each live
        source rank to the ORDERED chunk indices it will push
        spontaneously; ``orphans`` are chunks whose owner was already dead
        at start.  A source that errors mid-drain
        has its outstanding chunks (including the partially-received one —
        chunk content is byte-identical across peers, so a re-fetch simply
        overwrites) re-requested from the least-loaded survivor via a
        ``make_need`` control frame on the dst→src direction.  Survivors
        get ``done_blob`` when everything landed.  Raises only when ALL
        sources are dead with chunks outstanding (or on deadline); returns
        ``{"per_source": {rank: bytes}, "dead": {rank: exc}, "stolen": n}``.
        """
        needed = set(orphans)
        for lst in expected.values():
            needed.update(lst)
        queues: Dict[int, List[int]] = {p: list(lst) for p, lst in expected.items()}
        # heal frames ride the designated p2p lane (the last lane): with
        # lanes > 1 a heal no longer contends with lane 0, where the
        # collective epoch's control frames concentrate; with lanes == 1
        # this is exactly the legacy single-socket behavior
        socks: Dict[int, socket.socket] = {p: self.p2p_sock(p) for p in queues}
        sock_peer: Dict[socket.socket, int] = {s: p for p, s in socks.items()}
        pending_ctrl: Dict[int, List[memoryview]] = {p: [] for p in queues}
        frame_gates: Dict[int, float] = {}
        recv_st: Dict[int, Optional[dict]] = {p: None for p in queues}
        received: set = set()
        per_source: Dict[int, int] = {p: 0 for p in queues}
        dead: Dict[int, BaseException] = {}
        stolen = [0]
        orphan_list = list(orphans)

        def _enqueue_ctrl(p: int, payload: bytes) -> None:
            frame = _HDR.pack(len(payload), ctrl_tag) + payload
            pending_ctrl[p].append(memoryview(frame))

        def _assign_orphans() -> None:
            if not orphan_list:
                return
            alive = [p for p in queues if p not in dead]
            if not alive:
                return
            target = min(alive, key=lambda p: len(queues[p]))
            batch = sorted(orphan_list)
            orphan_list.clear()
            stolen[0] += len(batch)
            _enqueue_ctrl(target, make_need(batch))
            queues[target].extend(batch)

        def _mark_dead(p: int, e: BaseException) -> None:
            dead[p] = e
            orphan_list.extend(i for i in queues[p] if i not in received)
            queues[p] = []
            recv_st[p] = None
            pending_ctrl[p] = []
            if not isinstance(e, PeerGoneError):
                # protocol error (tag/size mismatch): the pair's stream is
                # desynchronized but the socket is alive — close it so later
                # ops fail cleanly instead of misparsing garbage frames
                try:
                    socks[p].close()
                except OSError:
                    pass
            logger.warning(
                "striped drain: source rank %d died (%s); reassigning", p, e
            )
            _assign_orphans()

        def _flush_writes(wlist_socks: List[socket.socket]) -> bool:
            paced = False
            for sock in wlist_socks:
                p = sock_peer[sock]
                bufs = pending_ctrl.get(p)
                if not bufs or p in dead:
                    continue
                if self._emu is not None:
                    gate = frame_gates.setdefault(p, self._emu.frame_gate())
                    if time.monotonic() < gate:
                        paced = True
                        continue
                try:
                    while bufs:
                        chunk_b = bufs[0]
                        if self._emu is not None and len(chunk_b) > 0:
                            allowed = self._emu.allow(
                                len(chunk_b), stream=(p, self.p2p_lane)
                            )
                            if allowed <= 0:
                                paced = True
                                break
                            chunk_b = chunk_b[:allowed]
                        sent = sock.send(chunk_b)
                        if self._emu is not None:
                            self._emu.consume(sent, stream=(p, self.p2p_lane))
                        if sent == len(bufs[0]):
                            bufs.pop(0)
                            frame_gates.pop(p, None)
                        else:
                            bufs[0] = bufs[0][sent:]
                            break
                except BlockingIOError:
                    pass
                except OSError as e:
                    _mark_dead(p, PeerGoneError(f"send to rank {p} failed: {e}"))
            return paced

        _assign_orphans()

        while received != needed:
            self._check_abort()
            if time.monotonic() > deadline:
                raise TimeoutError("striped drain timed out")
            alive = [p for p in queues if p not in dead]
            if not alive:
                first = next(iter(dead.values()))
                raise CommunicatorError(
                    f"all heal sources died with "
                    f"{len(needed) - len(received)} chunks outstanding: {first}"
                )
            rlist = [socks[p] for p in alive if queues[p]]
            wlist = [socks[p] for p in alive if pending_ctrl[p]]
            if not rlist and not wlist:
                time.sleep(0.001)  # only orphan bookkeeping left; rare
                continue
            readable, writable, _ = select.select(rlist, wlist, [], 0.1)
            paced_block = _flush_writes(writable)
            for sock in readable:
                p = sock_peer[sock]
                # drain the socket fully per readiness event (frames arrive
                # back to back): one recv per select round would double the
                # syscall count and cap the aggregate drain rate
                while p not in dead and queues[p]:
                    st = recv_st[p]
                    if st is None:
                        st = recv_st[p] = {"hdr": bytearray(), "off": 0}
                    try:
                        if len(st["hdr"]) < _HDR.size:
                            chunk_b = sock.recv(_HDR.size - len(st["hdr"]))
                            if not chunk_b:
                                raise PeerGoneError(
                                    f"connection to rank {p} closed"
                                )
                            st["hdr"] += chunk_b
                            if len(st["hdr"]) == _HDR.size:
                                nbytes, tag = _HDR.unpack(bytes(st["hdr"]))
                                idx = queues[p][0]
                                view = chunk_views[idx]
                                if tag != chunk_tag(idx):
                                    raise CommunicatorError(
                                        f"tag mismatch from rank {p}: got "
                                        f"{tag}, want {chunk_tag(idx)} "
                                        f"(chunk {idx})"
                                    )
                                if nbytes != len(view):
                                    raise CommunicatorError(
                                        f"size mismatch from rank {p}: got "
                                        f"{nbytes}, want {len(view)} "
                                        f"(chunk {idx})"
                                    )
                                st["view"] = view
                        elif st["off"] < len(st["view"]):
                            n = sock.recv_into(st["view"][st["off"] :])
                            if n == 0:
                                raise PeerGoneError(
                                    f"connection to rank {p} closed"
                                )
                            st["off"] += n
                    except BlockingIOError:
                        break
                    except (OSError, CommunicatorError) as e:
                        _mark_dead(
                            p,
                            e
                            if isinstance(e, CommunicatorError)
                            else CommunicatorError(str(e)),
                        )
                        break
                    if len(st["hdr"]) == _HDR.size and st["off"] == len(
                        st.get("view", b"")
                    ):
                        idx = queues[p].pop(0)
                        received.add(idx)
                        per_source[p] += len(st["view"])
                        recv_st[p] = None
            if paced_block:
                time.sleep(0.0005)

        # release surviving senders from their steal-service loops
        # (best-effort, bounded: a wedged survivor must not park the heal)
        for p in [p for p in queues if p not in dead]:
            _enqueue_ctrl(p, done_blob)
        flush_deadline = min(deadline, time.monotonic() + 5.0)
        while any(
            pending_ctrl[p] for p in queues if p not in dead
        ) and time.monotonic() < flush_deadline:
            self._check_abort()
            wlist = [
                socks[p]
                for p in queues
                if p not in dead and pending_ctrl[p]
            ]
            if not wlist:
                break
            _, writable, _ = select.select([], wlist, [], 0.1)
            if _flush_writes(writable):
                time.sleep(0.0005)

        return {"per_source": per_source, "dead": dead, "stolen": stolen[0]}


def _recv_exact(
    sock: socket.socket, n: int, aborted: threading.Event, timeout_s: float
) -> bytes:
    # poll in short slices (capped by the remaining deadline) so an abort
    # latched by a peer propagates in ~250 ms instead of parking in the
    # kernel for the full op timeout before ``aborted`` is re-checked
    deadline = time.monotonic() + timeout_s
    out = b""
    while len(out) < n:
        if aborted.is_set():
            raise CommunicatorAborted("communicator aborted")
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(f"recv timed out after {timeout_s}s")
        sock.settimeout(min(0.25, remaining))
        try:
            chunk = sock.recv(n - len(out))
        except socket.timeout:
            continue
        if not chunk:
            raise CommunicatorError("connection closed during recv")
        out += chunk
    return out


# ---------------------------------------------------------------------------
# TCPCommunicator
# ---------------------------------------------------------------------------


class TCPCommunicator(Communicator):
    """Host-driven collectives over TCP with ring allreduce.

    The CPU-anywhere tier (the reference's Gloo analog,
    ``process_group.py:643-711``) and the semantic model for the DCN tier:
    bandwidth-optimal ring reduce-scatter + allgather on numpy buffers, all
    ops serialized on a per-epoch op thread, per-op userspace timeouts that
    ``abort()`` the communicator on expiry.

    Ring collectives stripe every frame across ``TORCHFT_RING_LANES``
    parallel connections per peer (``_TcpMesh``/``_lane_parts``) — the cure
    for cwnd-limited single TCP streams on long-RTT DCN links — with
    bit-identical results at any lane count and the same epoch/abort
    semantics (peer death on any lane latches the epoch error exactly
    once).
    """

    def __init__(
        self,
        timeout_s: float = 60.0,
        host_id: Optional[str] = None,
        hierarchical: Optional[str] = None,
    ) -> None:
        """``host_id`` / ``hierarchical`` override the ``TORCHFT_HOST_ID``
        and ``TORCHFT_HIERARCHICAL`` env knobs per instance — the hook
        thread-plane harnesses (where ranks share one process env) use to
        build emulated multi-host topologies."""
        self._timeout_s = timeout_s
        self._host_id = host_id
        self._hier = hierarchical
        # runtime-armed fault program (chaos hook); None = follow the
        # TORCHFT_NET_FAULTS env
        self._fault_override: Optional[_FaultProgram] = None
        self._mesh: Optional[_TcpMesh] = None
        self._rank = 0
        self._world_size = 1
        self._quorum_id = -1
        self._errored: Optional[Exception] = None
        self._ops: "queue.Queue[Optional[Tuple[Callable[[], object], Future, bool, Optional[float]]]]" = (
            queue.Queue()
        )
        self._op_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._epoch = 0
        # count of ops currently executing on the op thread (plus queued
        # ones via self._ops.qsize) — the foreground-busy probe behind
        # busy(), which idle-priority traffic (spare warm serving) polls to
        # yield to live collectives.  Updated under its own lock: an old
        # epoch's op thread can overlap the new epoch's (teardown queues a
        # sentinel but never joins), and an unsynchronized += / -= pair
        # racing across threads can lose an update, sticking the counter
        # above zero (warm serving waits the full yield window forever) or
        # below (warm serving never yields).
        self._inflight_ops = 0
        self._inflight_lock = threading.Lock()
        # flight recorder attachment point: the owning Manager sets this to
        # its per-replica recorder; epoch lifecycle (configure / abort /
        # poison) and the mesh's lane-recovery machinery record into it
        self.flight: Optional[FlightRecorder] = None

    # -- lifecycle -----------------------------------------------------------

    def configure(
        self,
        store_addr: str,
        replica_id: str,
        rank: int,
        world_size: int,
        quorum_id: int = 0,
        group_rank: int = 0,
        group_world_size: int = 1,
        global_ranks: Sequence[int] = (),
    ) -> None:
        # Rendezvous can block up to timeout_s waiting for peers; it must
        # happen OUTSIDE self._lock so timers/aborts stay responsive.
        with self._lock:
            self._teardown_locked(reason="superseded by reconfigure")
            self._epoch += 1
            epoch = self._epoch
            self._rank = rank
            self._world_size = world_size
            self._quorum_id = quorum_id
            self._errored = None
            self._mesh = None

        mesh: Optional[_TcpMesh] = None
        if world_size > 1:
            with obs_span("comm::rendezvous", epoch=epoch):
                mesh = _TcpMesh(
                    store_addr,
                    rank,
                    world_size,
                    self._timeout_s,
                    host_id=self._host_id,
                    hier=self._hier,
                    faults=self._fault_override,
                    flight=self.flight,
                )

        with self._lock:
            if self._epoch != epoch:
                # superseded while we were rendezvousing
                if mesh is not None:
                    mesh.abort()
                raise CommunicatorAborted(
                    "configure superseded by a newer configure/abort"
                )
            self._mesh = mesh
            self._ops = queue.Queue()
            self._op_thread = threading.Thread(
                target=self._run_ops,
                args=(self._ops, epoch),
                name=f"tpuft_comm_ops_{epoch}",
                daemon=True,
            )
            self._op_thread.start()
        if self.flight:
            self.flight.set_comm_epoch(epoch)
            self.flight.record(
                FlightEvent.COMM_CONFIGURE,
                comm_epoch=epoch,
                quorum_id=quorum_id,
                rank=rank,
                world=world_size,
                lanes=mesh.lanes if mesh is not None else 0,
            )
        logger.info(
            "communicator configured: replica_id=%s rank=%d/%d quorum_id=%d",
            replica_id,
            rank,
            world_size,
            quorum_id,
        )

    def _teardown_locked(self, reason: str) -> None:
        if self._mesh is not None:
            self._mesh.abort()  # unblocks any op mid-IO with CommunicatorAborted
            self._mesh = None
        # fail everything still queued (items the old op thread also races for
        # just fail against the closed mesh instead — either way they error)
        try:
            while True:
                item = self._ops.get_nowait()
                if item is not None:
                    item[1].set_exception(CommunicatorAborted(reason))
        except queue.Empty:
            pass
        if self._op_thread is not None:
            self._ops.put(None)  # exit sentinel, consumed after any in-flight op
            self._op_thread = None

    def abort(self, reason: str = "aborted") -> None:
        """Unblock in-flight collectives and poison until reconfigure."""
        with self._lock:
            newly_poisoned = self._errored is None
            lane_summary = self._lane_summary_locked()
            self._abort_locked(reason)
        if self.flight:
            self.flight.record(FlightEvent.COMM_ABORT, reason=reason)
        self._flight_poison(reason, newly_poisoned, lane_summary)
        logger.warning("communicator aborted: %s", reason)

    def _lane_summary_locked(self) -> Dict[str, int]:
        """Counter summary of the (dying) epoch's mesh, captured under the
        lock BEFORE teardown clears it — the stall/fault evidence a
        postmortem chains from injection to poison."""
        mesh = self._mesh
        if mesh is None:
            return {}
        return {
            "stalls": sum(mesh.lane_stalls),
            "reconnects": mesh.lane_reconnects,
            "failovers": mesh.lane_failovers,
            "faults_injected": mesh.faults_injected,
        }

    def _flight_poison(
        self,
        reason: str,
        newly_poisoned: bool,
        lane_summary: Dict[str, int],
    ) -> None:
        """Record the epoch poison (when an error actually latched) plus a
        rate-limited flight dump.  Runs OUTSIDE every communicator lock:
        dumps do file IO."""
        flight = self.flight
        if flight is None:
            return
        if newly_poisoned and reason != "shutdown":
            flight.record(
                FlightEvent.COMM_POISON, reason=reason, **lane_summary
            )
            flight.maybe_dump("comm_poison")

    def _abort_locked(self, reason: str) -> None:
        if self._errored is None:
            self._errored = CommunicatorAborted(reason)
        self._teardown_locked(reason=reason)
        self._epoch += 1  # invalidates in-flight configure/timers

    def errored(self) -> Optional[Exception]:
        return self._errored

    def shutdown(self) -> None:
        self.abort("shutdown")

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._world_size

    def set_timeout(self, timeout_s: float) -> None:
        self._timeout_s = timeout_s

    def busy(self) -> bool:
        """True while a collective/p2p op is executing or queued in the
        current epoch.  Idle-priority consumers (the manager server's
        spare warm-range handler) poll this to yield the NIC to foreground
        collectives; a racy read only costs one brief extra yield."""
        if self._inflight_ops > 0:
            return True
        ops = self._ops
        return ops is not None and not ops.empty()

    def _op_started(self) -> None:
        """Enter the in-flight window of :meth:`busy`.  The counter rides
        its own lock because old and new epoch op threads overlap (teardown
        queues a sentinel but never joins), and an unsynchronized ``+=`` /
        ``-=`` pair can lose an update either way — sticking ``busy()``
        above zero forever or letting warm serving never yield (the PR-6
        third-round fix; pinned by a contention regression test)."""
        with self._inflight_lock:
            self._inflight_ops += 1

    def _op_finished(self) -> None:
        with self._inflight_lock:
            self._inflight_ops -= 1

    def arm_faults(self, spec: Union[str, _FaultProgram, None]) -> None:
        """Arm (or with ``None`` disarm) a per-link fault program at
        runtime — the chaos hook that flips a healthy link flaky
        mid-collective.  Applies to the CURRENT epoch's mesh immediately
        and to every future epoch of this communicator; ``None`` falls back
        to the ``TORCHFT_NET_FAULTS`` env program."""
        prog = parse_fault_spec(spec) if isinstance(spec, str) else spec
        self._fault_override = prog
        mesh = self._mesh
        if mesh is not None:
            mesh.faults = prog if prog is not None else _net_faults_from_env()
        if self.flight:
            self.flight.record(
                FlightEvent.CHAOS_INJECT,
                via="arm_faults",
                armed=prog is not None,
                spec=spec if isinstance(spec, str) else None,
            )
        logger.info(
            "fault program %s", "armed" if prog is not None else "disarmed"
        )

    def lane_stats(self) -> Dict[str, object]:
        """Per-lane observability of the current epoch's mesh: lane count,
        payload bytes sent/received per lane, stall events (pacer denials /
        kernel would-block) per lane, and the gray-failure counters
        (in-epoch lane reconnects/failovers, injected faults).  Empty when
        unconfigured or single-member."""
        mesh = self._mesh
        if mesh is None:
            return {}
        stats: Dict[str, object] = {
            "lanes": mesh.lanes,
            "stripe_floor_bytes": mesh.stripe_floor,
            "lane_tx_bytes": list(mesh.lane_tx_bytes),
            "lane_rx_bytes": list(mesh.lane_rx_bytes),
            "lane_stalls": list(mesh.lane_stalls),
            "lane_reconnects": mesh.lane_reconnects,
            "lane_failovers": mesh.lane_failovers,
            "faults_injected": mesh.faults_injected,
            "dead_lanes": sum(len(v) for v in mesh.dead_lanes.values()),
        }
        if mesh.topo is not None:
            stats.update(
                topo_hosts=mesh.topo.num_hosts,
                topo_local_world=mesh.topo.local_world,
                topo_is_leader=mesh.topo.is_leader,
                shm_tx_bytes=mesh.shm_tx_bytes,
                shm_rx_bytes=mesh.shm_rx_bytes,
            )
        return stats

    # -- hierarchical topology surface (collectives.py consumes this) --------

    def hier_topology(self) -> Optional[Dict[str, object]]:
        """Facts of the current epoch's ACTIVE hierarchical topology, or
        None when the epoch runs the flat ring.  Identical on every rank
        (derived from the shared host map), so callers may branch on it to
        pick collective schedules without desynchronizing."""
        mesh = self._mesh
        if mesh is None or mesh.topo is None:
            return None
        t = mesh.topo
        return {
            "hosts": t.num_hosts,
            "local_world": t.local_world,
            "is_leader": t.is_leader,
            "leader": t.leader,
            "leader_ring": list(t.leader_ring),
            "local_group": list(t.local),
        }

    def intra_reduce(self, flat: np.ndarray, op: ReduceOp = ReduceOp.SUM) -> Work:
        """Intra-host SUM (default) reduce of ``flat`` over shared memory:
        the host leader's Work resolves to the host-reduced array (the
        input, reduced in place on a private copy), members' to None.
        No-socket op — safe to interleave with cross-host collectives."""
        arr = np.array(flat, copy=True).reshape(-1)

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                mesh = ctx.mesh
                if mesh is None or mesh.topo is None:
                    return arr
                mesh.shm_reduce(arr, op, ctx.deadline())
                return arr if mesh.topo.is_leader else None

            return _run

        return self._submit(_make)

    def intra_broadcast(
        self,
        flat: Optional[np.ndarray],
        count: int,
        dtype: "np.dtype" = np.float32,
    ) -> Work:
        """Intra-host broadcast from the host leader (which passes the
        array; members pass None and receive a fresh one of ``count``
        elements of ``dtype``)."""

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                mesh = ctx.mesh
                if mesh is None or mesh.topo is None:
                    return flat
                arr = (
                    np.ascontiguousarray(flat).reshape(-1)
                    if flat is not None
                    else np.empty(count, dtype=dtype)
                )
                mesh.shm_bcast(arr, ctx.deadline())
                return arr

            return _run

        return self._submit(_make)

    def leader_comm(self) -> "Communicator":
        """A communicator view over the per-host leader subgroup of the
        CURRENT epoch: size() = host count, rank() = this host's position
        in the leader ring.  Valid only on leaders (members have no
        business on the DCN in a hierarchical schedule); collectives ride
        the same mesh, epoch and abort semantics as the parent."""
        topo = self.hier_topology()
        if topo is None:
            return self
        return _LeaderComm(self, list(topo["leader_ring"]))  # type: ignore[arg-type]

    # -- op submission -------------------------------------------------------

    def _abort_if_epoch(self, epoch: int, reason: str) -> None:
        # Check-and-abort atomically so a stale timer can never poison a
        # newer epoch; runs on a spawned thread so the shared timer thread
        # is never blocked on this lock.
        def _do() -> None:
            with self._lock:
                if self._epoch != epoch:
                    return
                newly_poisoned = self._errored is None
                lane_summary = self._lane_summary_locked()
                self._abort_locked(reason)
            if self.flight:
                self.flight.record(FlightEvent.COMM_ABORT, reason=reason)
            self._flight_poison(reason, newly_poisoned, lane_summary)
            logger.warning("communicator aborted: %s", reason)

        threading.Thread(target=_do, name="tpuft_comm_abort", daemon=True).start()

    def _run_ops(
        self,
        ops: "queue.Queue[Optional[Tuple[Callable[[], object], Future, bool, Optional[float]]]]",
        epoch: int,
    ) -> None:
        while True:
            item = ops.get()
            if item is None:
                return
            fn, fut, peer_fail_stop, op_timeout_s = item
            if not fut.set_running_or_notify_cancel():
                continue
            # Userspace per-op watchdog: a wedged collective aborts the
            # communicator (unblocking the socket IO) instead of hanging the
            # train loop or killing the process.  A long-running op (a
            # striped heal drain) may carry its own bound.
            timeout_s = op_timeout_s if op_timeout_s is not None else self._timeout_s
            handle: TimerHandle = schedule_timeout(
                timeout_s,
                lambda: self._abort_if_epoch(
                    epoch, f"op timed out after {timeout_s}s"
                ),
            )
            self._op_started()
            try:
                with obs_span("comm::op", epoch=epoch):
                    result = fn()
            except BaseException as e:  # noqa: BLE001
                # A fail-stop PEER death on a point-to-point byte op (dead
                # socket — the striped-heal failover case) stays scoped to
                # that op: the pair's socket is permanently closed, other
                # pairs' streams are untouched, so poisoning the epoch would
                # only turn a survivable source loss into a failed heal.
                # Everything else still latches: collective failures leave
                # OTHER pairs mid-frame, protocol errors (tag/size mismatch)
                # leave THIS pair's stream desynchronized on a live socket,
                # and op timeouts already abort via the watchdog above.
                peer_scoped = peer_fail_stop and isinstance(e, PeerGoneError)
                latched = False
                lane_summary: Dict[str, int] = {}
                if not peer_scoped:
                    with self._lock:
                        if self._epoch == epoch and self._errored is None:
                            self._errored = (
                                e
                                if isinstance(e, Exception)
                                else RuntimeError(str(e))
                            )
                            latched = True
                            lane_summary = self._lane_summary_locked()
                if latched:
                    self._flight_poison(str(e), True, lane_summary)
                fut.set_exception(e)
            else:
                fut.set_result(result)
            finally:
                self._op_finished()
                handle.cancel()

    def _submit(
        self,
        make_fn: Callable[["_CommCtx"], Callable[[], object]],
        peer_fail_stop: bool = False,
        op_timeout_s: Optional[float] = None,
    ) -> Work:
        # Ops capture an epoch-pinned snapshot of (mesh, rank, ws) so an op
        # drained late from a superseded queue can never touch the sockets of
        # a newer epoch.
        with self._lock:
            if self._errored is not None:
                fut: Future = Future()
                fut.set_exception(self._errored)
                return Work(fut)
            if self._op_thread is None:
                fut = Future()
                fut.set_exception(
                    CommunicatorError("communicator not configured")
                )
                return Work(fut)
            ctx = _CommCtx(
                mesh=self._mesh,
                rank=self._rank,
                world_size=self._world_size,
                timeout_s=(
                    op_timeout_s if op_timeout_s is not None else self._timeout_s
                ),
            )
            fut = Future()
            self._ops.put((make_fn(ctx), fut, peer_fail_stop, op_timeout_s))
            return Work(fut)

    # -- collectives ---------------------------------------------------------

    @staticmethod
    def _as_list(buffers: Buffers) -> List[np.ndarray]:
        if isinstance(buffers, np.ndarray):
            return [buffers]
        return [np.asarray(b) for b in buffers]

    def allreduce(
        self,
        buffers: Buffers,
        op: ReduceOp = ReduceOp.SUM,
        in_place: bool = False,
    ) -> Work:
        arrays = self._as_list(buffers)
        single = isinstance(buffers, np.ndarray)

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                out = _allreduce_sync(ctx, arrays, op, in_place=in_place)
                return out[0] if single else out

            return _run

        return self._submit(_make)

    def broadcast(self, buffers: Buffers, root: int = 0) -> Work:
        arrays = self._as_list(buffers)
        single = isinstance(buffers, np.ndarray)

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                out = _broadcast_sync(ctx, arrays, root)
                return out[0] if single else out

            return _run

        return self._submit(_make)

    def reduce_scatter(
        self, data: np.ndarray, op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        arr = np.asarray(data)

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                ws = ctx.world_size
                flat = np.array(arr, copy=True).reshape(-1)
                topo = ctx.mesh.topo if ctx.mesh is not None else None
                if topo is not None and len(topo.leader_ring) < ws:
                    # hierarchical: full two-level allreduce (host-shm +
                    # leader ring), then slice this rank's chunk.  Cross-
                    # host bytes are 2(H-1)/H·n per host vs the flat ring's
                    # L(ws-1)/ws·n — a win from L >= 2 replicas/host, a
                    # wash at exactly 2; a leader-ring reduce-scatter with
                    # an shm scatter would halve it again but needs
                    # host-contiguous rank chunks, deferred until profiles
                    # demand it.
                    _hier_allreduce(
                        ctx, flat, op, tag_base=wire_tags.RING_REDUCE_TAG_BASE
                    )
                    bounds = _ring_bounds(flat.size, ws)
                    own = flat[bounds[ctx.rank] : bounds[ctx.rank + 1]]
                else:
                    # flat, and also the forced one-replica-per-host
                    # topology (leader ring == all ranks): the plain ring
                    # reduce-scatter moves HALF the allreduce's bytes
                    own = _ring_reduce_scatter(
                        ctx, flat, op, tag_base=wire_tags.RING_REDUCE_TAG_BASE
                    )
                if op == ReduceOp.AVG:
                    if np.issubdtype(own.dtype, np.integer):
                        own //= ws
                    else:
                        np.divide(own, ws, out=own)
                # compact: own is a view of the full-size working copy;
                # returning it would pin all n elements for the Work's life
                return own.copy()

            return _run

        return self._submit(_make)

    def send_bytes(self, data, dst: int, tag: int = 0) -> Work:
        """Send any contiguous buffer (bytes, memoryview, numpy array) with
        no intermediate copy."""
        if isinstance(data, np.ndarray):
            view = _bytes_view(np.ascontiguousarray(data))
        else:
            view = memoryview(data)
            if view.format != "B":
                view = view.cast("B")

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                mesh = ctx.require_peer(dst)
                # whole frame on the designated p2p lane: the receive paths
                # (recv_dynamic*/striped_drain) read that one socket
                mesh.exchange(
                    [(dst, tag, view)], [], ctx.deadline(), lane=mesh.p2p_lane
                )
                return view.nbytes

            return _run

        return self._submit(_make, peer_fail_stop=True)

    def recv_bytes(self, src: int, tag: int = 0) -> Work:
        """Receive one frame from ``src``; the size rides in the frame header
        so this pairs directly with :meth:`send_bytes` of any length."""

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                mesh = ctx.require_peer(src)
                return mesh.recv_dynamic(src, tag, ctx.deadline())

            return _run

        return self._submit(_make, peer_fail_stop=True)

    def recv_bytes_into(self, src: int, out: np.ndarray, tag: int = 0) -> Work:
        view = _bytes_view(out)

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                mesh = ctx.require_peer(src)
                # cap semantics (payload may be smaller than the buffer),
                # matching the native tier's recv_into contract
                return mesh.recv_dynamic_into(src, tag, view, ctx.deadline())

            return _run

        return self._submit(_make, peer_fail_stop=True)

    def heal_drain(
        self,
        chunk_views: List[memoryview],
        expected: Dict[int, List[int]],
        orphans: List[int],
        chunk_tag: Callable[[int], int],
        ctrl_tag: int,
        make_need: Callable[[List[int]], bytes],
        done_blob: bytes,
        timeout_s: Optional[float] = None,
    ) -> Work:
        """Striped-heal receive: concurrently drain disjoint chunk frames
        from every source peer straight into ``chunk_views`` as ONE op (see
        :meth:`_TcpMesh.striped_drain`) — per-chunk recv ops would
        serialize on the op thread and cap the heal at a single link's
        bandwidth.  ``timeout_s`` (default: the communicator op timeout)
        bounds the whole drain, watchdog included — a heal given a longer
        deadline than one collective must not be aborted mid-transfer."""

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                for p in expected:
                    ctx.require_peer(p)
                assert ctx.mesh is not None
                return ctx.mesh.striped_drain(
                    chunk_views,
                    expected,
                    orphans,
                    chunk_tag,
                    ctrl_tag,
                    make_need,
                    done_blob,
                    ctx.deadline(),
                )

            return _run

        return self._submit(_make, peer_fail_stop=True, op_timeout_s=timeout_s)

    def _all_exchange(
        self,
        send_for_peer: Callable[[int], np.ndarray],
        recv_template: Callable[[int], np.ndarray],
        own: np.ndarray,
        tag: int,
    ) -> Work:
        """Shared skeleton for alltoall/allgather: send ``send_for_peer(p)``
        to every peer, receive into ``empty_like(recv_template(p))``, pass
        our own buffer through at index ``rank``."""

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                return _all_exchange_sync(
                    ctx, send_for_peer, recv_template, own, tag
                )

            return _run

        return self._submit(_make)

    def alltoall(self, chunks: List[np.ndarray], tag: int = 0) -> Work:
        """Exchange ``chunks[j]`` with rank j (keeping our own); the Work's
        value is the list of received chunks indexed by source rank.  Chunk j
        must have the shape rank j expects back (symmetric splits)."""
        arrays = [np.ascontiguousarray(c) for c in chunks]
        assert len(arrays) == self._world_size, "need one chunk per rank"
        rank = self._rank
        return self._all_exchange(
            send_for_peer=lambda p: arrays[p],
            recv_template=lambda p: arrays[p],
            own=arrays[rank],
            tag=wire_tags.ALLTOALL_TAG_OFFSET + tag,
        )

    def allgather(self, data: np.ndarray, tag: int = 0) -> Work:
        """Gather every rank's buffer (same shape/dtype on all ranks); the
        Work's value is a list indexed by rank.  On a hierarchical topology
        the gather runs host-blocked: shm to the host leader, leader-block
        exchange across the DCN, shm broadcast back out."""
        array = np.ascontiguousarray(data)

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                if (
                    ctx.world_size > 1
                    and ctx.mesh is not None
                    and ctx.mesh.topo is not None
                ):
                    return _hier_allgather_sync(
                        ctx, array, wire_tags.ALLGATHER_TAG_OFFSET + tag
                    )
                return _all_exchange_sync(
                    ctx,
                    send_for_peer=lambda p: array,
                    recv_template=lambda p: array,
                    own=array,
                    tag=wire_tags.ALLGATHER_TAG_OFFSET + tag,
                )

            return _run

        return self._submit(_make)

    def barrier(self) -> Work:
        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                _allreduce_sync(ctx, [np.zeros(1, dtype=np.float32)], ReduceOp.SUM)
                return None

            return _run

        return self._submit(_make)


def _all_exchange_sync(
    ctx: "_CommCtx",
    send_for_peer: Callable[[int], np.ndarray],
    recv_template: Callable[[int], np.ndarray],
    own: np.ndarray,
    tag: int,
    ring: Optional[List[int]] = None,
) -> List[np.ndarray]:
    """All-to-all exchange body shared by alltoall, the non-hierarchical
    allgather path, and (via ``ring`` — participating global ranks in
    order, results indexed by ring position) the leader-subgroup views."""
    if ring is None:
        ring = list(range(ctx.world_size))
    ws = len(ring)
    if ws == 1:
        return [own]
    mesh = ctx.mesh
    assert mesh is not None
    pos = ring.index(ctx.rank)
    out = [np.empty_like(recv_template(p)) for p in range(ws)]
    out[pos] = own
    sends = [
        (ring[p], tag, _bytes_view(send_for_peer(p)))
        for p in range(ws)
        if p != pos
    ]
    recvs = [
        (ring[p], tag, _bytes_view(out[p])) for p in range(ws) if p != pos
    ]
    mesh.exchange(sends, recvs, ctx.deadline())
    return out


class _CommCtx:
    """Epoch-pinned op context: the mesh and layout captured at submit time."""

    __slots__ = ("mesh", "rank", "world_size", "timeout_s")

    def __init__(
        self,
        mesh: Optional[_TcpMesh],
        rank: int,
        world_size: int,
        timeout_s: float,
    ) -> None:
        self.mesh = mesh
        self.rank = rank
        self.world_size = world_size
        self.timeout_s = timeout_s

    def deadline(self) -> float:
        return time.monotonic() + self.timeout_s

    def require_peer(self, peer: int) -> _TcpMesh:
        if self.mesh is None or peer not in self.mesh.peers:
            raise CommunicatorError(f"no peer {peer} in communicator")
        return self.mesh


class _LeaderComm(Communicator):
    """Leader-subgroup view of a :class:`TCPCommunicator` for one epoch.

    The quantized DiLoCo pipeline runs its alltoall/allgather windows on
    this view so only HOST LEADERS touch the DCN — one quantized stream per
    host instead of one per replica.  Ops ride the parent's mesh, op
    thread, epoch and abort semantics; rank()/size() are the leader-ring
    position and host count.  Distinct tag bases (7000/8000) keep leader
    frames un-confusable with flat alltoall/allgather frames."""

    def __init__(self, parent: TCPCommunicator, ring: List[int]) -> None:
        self._parent = parent
        self._ring = ring

    def configure(self, *args, **kwargs) -> None:  # type: ignore[override]
        raise RuntimeError("_LeaderComm is a per-epoch view; configure the parent")

    def rank(self) -> int:
        return self._ring.index(self._parent.rank())

    def size(self) -> int:
        return len(self._ring)

    def alltoall(self, chunks: List[np.ndarray], tag: int = 0) -> Work:
        arrays = [np.ascontiguousarray(c) for c in chunks]
        assert len(arrays) == len(self._ring), "need one chunk per leader"
        ring = self._ring
        pos = self.rank()

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                return _all_exchange_sync(
                    ctx,
                    send_for_peer=lambda p: arrays[p],
                    recv_template=lambda p: arrays[p],
                    own=arrays[pos],
                    tag=wire_tags.LEADER_ALLTOALL_TAG_OFFSET + tag,
                    ring=ring,
                )

            return _run

        return self._parent._submit(_make)

    def allgather(self, data: np.ndarray, tag: int = 0) -> Work:
        array = np.ascontiguousarray(data)
        ring = self._ring

        def _make(ctx: "_CommCtx") -> Callable[[], object]:
            def _run() -> object:
                return _all_exchange_sync(
                    ctx,
                    send_for_peer=lambda p: array,
                    recv_template=lambda p: array,
                    own=array,
                    tag=wire_tags.LEADER_ALLGATHER_TAG_OFFSET + tag,
                    ring=ring,
                )

            return _run

        return self._parent._submit(_make)

    def allreduce(
        self,
        buffers: Buffers,
        op: ReduceOp = ReduceOp.SUM,
        in_place: bool = False,
    ) -> Work:
        raise NotImplementedError("leader view carries alltoall/allgather only")

    def broadcast(self, buffers: Buffers, root: int = 0) -> Work:
        raise NotImplementedError("leader view carries alltoall/allgather only")

    def send_bytes(self, data: bytes, dst: int, tag: int = 0) -> Work:
        raise NotImplementedError("leader view carries alltoall/allgather only")

    def recv_bytes(self, src: int, tag: int = 0) -> Work:
        raise NotImplementedError("leader view carries alltoall/allgather only")

    def barrier(self) -> Work:
        raise NotImplementedError("leader view carries alltoall/allgather only")

    def abort(self, reason: str = "aborted") -> None:
        self._parent.abort(reason)

    def errored(self) -> Optional[Exception]:
        return self._parent.errored()


def _allreduce_sync(
    ctx: _CommCtx,
    arrays: List[np.ndarray],
    op: ReduceOp,
    in_place: bool = False,
) -> List[np.ndarray]:
    ws = ctx.world_size
    out = [
        a
        if in_place
        and isinstance(a, np.ndarray)
        and a.flags.c_contiguous
        and a.flags.writeable
        else np.array(a, copy=True)
        for a in arrays
    ]
    if ws > 1:
        assert ctx.mesh is not None
        # topology-aware dispatch: hierarchical when the epoch discovered a
        # multi-host topology (mesh.topo is uniform across ranks), else the
        # byte-for-byte legacy flat ring
        reduce_flat = (
            _hier_allreduce if ctx.mesh.topo is not None else _ring_allreduce
        )
        # one flat ring per dtype — concatenating mixed dtypes would silently
        # promote (f32+i64 → f64) and return wrong-dtype buffers
        by_dtype: Dict[str, List[int]] = {}
        for i, a in enumerate(out):
            by_dtype.setdefault(a.dtype.name, []).append(i)
        for ring_idx, idxs in enumerate(by_dtype.values()):
            if len(idxs) == 1 and out[idxs[0]].flags.c_contiguous:
                flat = out[idxs[0]].reshape(-1)
                reduce_flat(
                    ctx, flat, op,
                    tag_base=ring_idx * wire_tags.RING_BUFFER_TAG_STRIDE,
                )
                out[idxs[0]] = flat.reshape(out[idxs[0]].shape)
                continue
            flat = np.concatenate([out[i].reshape(-1) for i in idxs])
            reduce_flat(
                    ctx, flat, op,
                    tag_base=ring_idx * wire_tags.RING_BUFFER_TAG_STRIDE,
                )
            offset = 0
            for i in idxs:
                n = out[i].size
                out[i] = flat[offset : offset + n].reshape(out[i].shape)
                offset += n
    if op == ReduceOp.AVG:
        for a in out:
            if np.issubdtype(a.dtype, np.integer):
                a //= ws
            else:
                # bfloat16/fp8 are not np.inexact subdtypes; true-divide all
                # non-integer dtypes in place
                np.divide(a, ws, out=a)
    return out


def _ring_bounds(n: int, ws: int) -> List[int]:
    bounds = [0]
    base, extra = divmod(n, ws)
    for i in range(ws):
        bounds.append(bounds[-1] + base + (1 if i < extra else 0))
    return bounds


def _ring_reduce_scatter(
    ctx: _CommCtx,
    flat: np.ndarray,
    op: ReduceOp,
    tag_base: int = 0,
    ring: Optional[List[int]] = None,
) -> np.ndarray:
    """In-place ring reduce-scatter phase: after ws-1 duplex steps, this
    rank's chunk (``_ring_bounds`` chunk ``rank``) holds the full reduction;
    returns a view of it.  The schedule is shifted by one vs the textbook
    ring so rank r ends up owning chunk r (the conventional contract).

    ``ring`` (global ranks in ring order; default = all ranks) restricts
    the ring to a subset — the hierarchical leader ring.  The flat default
    compiles to the identical schedule (position == rank), so the legacy
    wire behavior is byte-for-byte unchanged."""
    if ring is None:
        ring = list(range(ctx.world_size))
    ws = len(ring)
    if ws == 1:
        return flat
    mesh = ctx.mesh
    assert mesh is not None
    pos = ring.index(ctx.rank)
    right = ring[(pos + 1) % ws]
    left = ring[(pos - 1) % ws]
    deadline = ctx.deadline()
    bounds = _ring_bounds(flat.size, ws)

    def chunk(i: int) -> np.ndarray:
        i %= ws
        return flat[bounds[i] : bounds[i + 1]]

    scratch = np.empty(bounds[1], dtype=flat.dtype)
    itemsize = flat.dtype.itemsize
    for step in range(ws - 1):
        send_idx = (pos - step - 1) % ws
        recv_idx = (pos - step - 2) % ws
        send_chunk = chunk(send_idx)
        recv_chunk = chunk(recv_idx)
        recv_buf = scratch[: recv_chunk.size]

        # reduce each completed lane sub-range as it lands, while the other
        # lanes are still streaming — sub-frame boundaries are 64-byte
        # aligned so element ranges never split, and every element still
        # sees exactly one add per step: bit-identical at any lane count
        def _reduce_part(
            start: int, stop: int, _dst=recv_chunk, _src=recv_buf
        ) -> None:
            lo, hi = start // itemsize, stop // itemsize
            _reduce_into(op, _dst[lo:hi], _src[lo:hi])

        mesh.exchange(
            [(right, tag_base + 1000 + step, _bytes_view(send_chunk))],
            [(left, tag_base + 1000 + step, _bytes_view(recv_buf), _reduce_part)],
            deadline,
        )
    return chunk(pos)


def _ring_allreduce(
    ctx: _CommCtx,
    flat: np.ndarray,
    op: ReduceOp,
    tag_base: int = 0,
    ring: Optional[List[int]] = None,
) -> None:
    """In-place bandwidth-optimal ring allreduce.

    Reduce-scatter then allgather, ws-1 steps each; every step exchanges one
    chunk with both neighbors concurrently via duplex IO (deadlock-free even
    at world size 2, where both directions share one socket pair).  Each
    chunk's frame is lane-striped by ``exchange``; the per-element reduction
    order is fixed by the chunk schedule alone, so lane count never changes
    the bits.  ``ring`` restricts to a rank subset (the hierarchical leader
    ring); the default is the byte-for-byte legacy flat ring.
    """
    if ring is None:
        ring = list(range(ctx.world_size))
    ws = len(ring)
    if ws == 1:
        return
    mesh = ctx.mesh
    assert mesh is not None
    pos = ring.index(ctx.rank)
    right = ring[(pos + 1) % ws]
    left = ring[(pos - 1) % ws]
    deadline = ctx.deadline()

    _ring_reduce_scatter(ctx, flat, op, tag_base, ring=ring)
    bounds = _ring_bounds(flat.size, ws)

    def chunk(i: int) -> np.ndarray:
        i %= ws
        return flat[bounds[i] : bounds[i + 1]]

    # allgather phase: ring position p starts owning reduced chunk p
    for step in range(ws - 1):
        send_idx = (pos - step) % ws
        recv_idx = (pos - step - 1) % ws
        mesh.exchange(
            [(right, tag_base + 2000 + step, _bytes_view(chunk(send_idx)))],
            [(left, tag_base + 2000 + step, _bytes_view(chunk(recv_idx)))],
            deadline,
        )


def _hier_allreduce(
    ctx: _CommCtx, flat: np.ndarray, op: ReduceOp, tag_base: int = 0
) -> None:
    """Two-level in-place allreduce over the discovered host topology:
    intra-host shared-memory reduce (fixed ascending-rank order) → striped
    multi-lane cross-host ring among the per-host leaders → intra-host
    broadcast.  Each byte crosses the DCN once per HOST instead of once per
    replica; results are deterministic (fixed reduction order) and
    bit-identical across lane counts at a fixed topology, though not
    bit-identical to the flat ring (different reduction ORDER — allclose)."""
    mesh = ctx.mesh
    assert mesh is not None and mesh.topo is not None
    topo = mesh.topo
    deadline = ctx.deadline()
    mesh.shm_reduce(flat, op, deadline)
    if topo.is_leader and len(topo.leader_ring) > 1:
        _ring_allreduce(ctx, flat, op, tag_base, ring=topo.leader_ring)
    mesh.shm_bcast(flat, deadline)


def _hier_allgather_sync(
    ctx: _CommCtx, array: np.ndarray, tag: int
) -> List[np.ndarray]:
    """Hierarchical allgather: shm-gather each host's buffers to its
    leader, exchange whole host BLOCKS among leaders (each byte crosses the
    DCN once per host pair, not once per replica pair), then shm-broadcast
    the assembled result.  Same value contract as the flat path: a list
    indexed by global rank, own entry aliasing the input."""
    mesh = ctx.mesh
    assert mesh is not None and mesh.topo is not None
    topo = mesh.topo
    ws, rank = ctx.world_size, ctx.rank
    deadline = ctx.deadline()
    n = array.nbytes
    total = np.empty(ws * n, dtype=np.uint8)

    gathered = mesh.shm_gather(array, deadline)
    if topo.is_leader:
        if len(topo.leader_ring) > 1:
            assert gathered is not None
            my_block = np.concatenate(
                [
                    np.frombuffer(_bytes_view(a), dtype=np.uint8)
                    for a in gathered
                ]
            )
            other = [g for g in topo.hosts if rank not in g]
            blocks = {g[0]: np.empty(len(g) * n, dtype=np.uint8) for g in other}
            sends = [
                (g[0], wire_tags.HIER_HOST_BLOCK_TAG_OFFSET + tag, _bytes_view(my_block))
                for g in other
            ]
            recvs = [
                (g[0], wire_tags.HIER_HOST_BLOCK_TAG_OFFSET + tag, _bytes_view(blocks[g[0]]))
                for g in other
            ]
            mesh.exchange(sends, recvs, deadline)
            for g in other:
                block = blocks[g[0]]
                for k, member in enumerate(g):
                    total[member * n : (member + 1) * n] = block[
                        k * n : (k + 1) * n
                    ]
        assert gathered is not None
        for k, member in enumerate(topo.local):
            total[member * n : (member + 1) * n] = _bytes_view(gathered[k])
    mesh.shm_bcast(total, deadline)

    out: List[np.ndarray] = []
    for p in range(ws):
        if p == rank:
            out.append(array)
        else:
            out.append(
                total[p * n : (p + 1) * n]
                .view(array.dtype)
                .reshape(array.shape)
                .copy()
            )
    return out


def _hier_broadcast_sync(
    ctx: _CommCtx, arrays: List[np.ndarray], root: int
) -> List[np.ndarray]:
    """Hierarchical broadcast: the root pushes each buffer once per OTHER
    host (to its leader); delivery inside every host is a shared-memory
    broadcast.  Wire bytes drop by the local-group factor vs the flat
    root-to-every-peer fanout."""
    mesh = ctx.mesh
    assert mesh is not None and mesh.topo is not None
    topo = mesh.topo
    out = [np.ascontiguousarray(a) for a in arrays]
    deadline = ctx.deadline()
    root_local = root in topo.local
    src_idx = topo.local.index(root) if root_local else 0
    for i, a in enumerate(out):
        view = _bytes_view(a)
        if ctx.rank == root:
            other_leads = [g[0] for g in topo.hosts if root not in g]
            if other_leads:
                mesh.exchange(
                    [
                        (lead, wire_tags.BROADCAST_TAG_OFFSET + i, view)
                        for lead in other_leads
                    ],
                    [],
                    deadline,
                )
        elif topo.is_leader and not root_local:
            mesh.exchange(
                [], [(root, wire_tags.BROADCAST_TAG_OFFSET + i, view)], deadline
            )
        mesh.shm_bcast(a, deadline, src_idx=src_idx)
    return out


def _broadcast_sync(ctx: _CommCtx, arrays: List[np.ndarray], root: int) -> List[np.ndarray]:
    ws = ctx.world_size
    out = [np.ascontiguousarray(a) for a in arrays]
    if ws == 1:
        return out
    mesh = ctx.mesh
    assert mesh is not None
    if mesh.topo is not None:
        return _hier_broadcast_sync(ctx, out, root)
    deadline = ctx.deadline()
    if ctx.rank == root:
        for i, a in enumerate(out):
            view = _bytes_view(a)
            sends = [
                (p, wire_tags.BROADCAST_TAG_OFFSET + i, view) for p in mesh.peers
            ]
            mesh.exchange(sends, [], deadline)
    else:
        for i, a in enumerate(out):
            mesh.exchange(
                [], [(root, wire_tags.BROADCAST_TAG_OFFSET + i, _bytes_view(a))], deadline
            )
    return out


# ---------------------------------------------------------------------------
# Test / adapter communicators
# ---------------------------------------------------------------------------


class DummyCommunicator(Communicator):
    """World-size-1 no-op communicator (``process_group.py:1005-1134``):
    returns inputs unchanged; soaks up wrapper init in tests.

    ``is_passthrough`` marks the "collectives return my own contribution"
    fiction so shard-structured pipelines (quantized allreduce) can take an
    equivalent local path instead of mis-assembling shards."""

    is_passthrough = True

    def __init__(self, rank: int = 0, world_size: int = 1) -> None:
        self._rank = rank
        self._world_size = world_size
        self.configure_count = 0

    def configure(self, store_addr: str, replica_id: str, rank: int, world_size: int, **kw) -> None:  # type: ignore[override]
        self._rank = rank
        self._world_size = world_size
        self.configure_count += 1

    def allreduce(
        self,
        buffers: Buffers,
        op: ReduceOp = ReduceOp.SUM,
        in_place: bool = False,
    ) -> Work:
        return DummyWork(buffers)

    def broadcast(self, buffers: Buffers, root: int = 0) -> Work:
        return DummyWork(buffers)

    def reduce_scatter(
        self, data: np.ndarray, op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        flat = np.asarray(data).reshape(-1)
        bounds = _ring_bounds(flat.size, self._world_size)
        return DummyWork(flat[bounds[self._rank] : bounds[self._rank + 1]])

    def send_bytes(self, data, dst: int, tag: int = 0) -> Work:
        nbytes = data.nbytes if hasattr(data, "nbytes") else len(data)
        return DummyWork(nbytes)

    def recv_bytes(self, src: int, tag: int = 0) -> Work:
        return DummyWork(b"")

    def recv_bytes_into(self, src, out, tag: int = 0) -> Work:
        return DummyWork(0)

    def alltoall(self, chunks, tag: int = 0) -> Work:
        # mirror-world fiction: every peer sends us what we'd send ourselves
        return DummyWork([chunks[self._rank]] * self._world_size)

    def allgather(self, data, tag: int = 0) -> Work:
        return DummyWork([data] * self._world_size)

    def barrier(self) -> Work:
        return DummyWork(None)

    def abort(self, reason: str = "aborted") -> None:
        pass

    def errored(self) -> Optional[Exception]:
        return None

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._world_size


class FakeCommunicatorWrapper(Communicator):
    """Error-injection wrapper for tests (``process_group.py:1252-1317``):
    ``report_future_error`` makes the next collective's *future* fail while
    the underlying collective still runs, so peers are not wedged — matching
    the reference semantics (``process_group.py:1290-1317``)."""

    def __init__(self, comm: Communicator) -> None:
        self._comm = comm
        self._next_error: Optional[Exception] = None
        self._errored: Optional[Exception] = None

    def report_future_error(self, err: Exception) -> None:
        self._next_error = err

    def _wrap(self, work: Work) -> Work:
        if self._next_error is not None:
            err, self._next_error = self._next_error, None
            self._errored = err

            def _fail(_value: object) -> object:
                raise err

            return work.then(_fail)
        return work

    def configure(self, *args, **kwargs) -> None:  # type: ignore[override]
        self._errored = None
        self._comm.configure(*args, **kwargs)

    def allreduce(
        self,
        buffers: Buffers,
        op: ReduceOp = ReduceOp.SUM,
        in_place: bool = False,
    ) -> Work:
        return self._wrap(self._comm.allreduce(buffers, op, in_place=in_place))

    def broadcast(self, buffers: Buffers, root: int = 0) -> Work:
        return self._wrap(self._comm.broadcast(buffers, root))

    def reduce_scatter(
        self, data: np.ndarray, op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        return self._wrap(self._comm.reduce_scatter(data, op))

    def send_bytes(self, data: bytes, dst: int, tag: int = 0) -> Work:
        return self._wrap(self._comm.send_bytes(data, dst, tag))

    def recv_bytes(self, src: int, tag: int = 0) -> Work:
        return self._wrap(self._comm.recv_bytes(src, tag))

    def recv_bytes_into(self, src: int, out, tag: int = 0) -> Work:
        return self._wrap(self._comm.recv_bytes_into(src, out, tag))

    def heal_drain(self, *args, **kwargs) -> Work:
        return self._wrap(self._comm.heal_drain(*args, **kwargs))

    def alltoall(self, chunks, tag: int = 0) -> Work:
        return self._wrap(self._comm.alltoall(chunks, tag))

    def allgather(self, data, tag: int = 0) -> Work:
        return self._wrap(self._comm.allgather(data, tag))

    def lane_stats(self) -> Dict[str, object]:
        return self._comm.lane_stats()

    def arm_faults(self, spec) -> None:
        self._comm.arm_faults(spec)  # type: ignore[attr-defined]

    def hier_topology(self) -> Optional[Dict[str, object]]:
        return self._comm.hier_topology()

    def intra_reduce(self, flat, op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._wrap(self._comm.intra_reduce(flat, op))  # type: ignore[attr-defined]

    def intra_broadcast(self, flat, count: int, dtype=np.float32) -> Work:
        return self._wrap(
            self._comm.intra_broadcast(flat, count, dtype)  # type: ignore[attr-defined]
        )

    def leader_comm(self) -> "Communicator":
        return self._comm.leader_comm()  # type: ignore[attr-defined]

    def barrier(self) -> Work:
        return self._wrap(self._comm.barrier())

    def abort(self, reason: str = "aborted") -> None:
        self._comm.abort(reason)

    def errored(self) -> Optional[Exception]:
        return self._errored or self._comm.errored()

    def rank(self) -> int:
        return self._comm.rank()

    def size(self) -> int:
        return self._comm.size()

    def set_timeout(self, timeout_s: float) -> None:
        self._comm.set_timeout(timeout_s)

    def shutdown(self) -> None:
        self._comm.shutdown()


class ManagedCommunicator(Communicator):
    """Routes collectives through a Manager so unmodified data-parallel code
    sees fault-tolerant semantics transparently
    (``process_group.py:1320-1353``): ``allreduce`` goes through
    ``manager.allreduce`` (error-swallowing, participation-aware) and
    ``size()`` reports the participating world size."""

    def __init__(self, manager) -> None:  # type: ignore[no-untyped-def]
        self._manager = manager

    def configure(self, *args, **kwargs) -> None:  # type: ignore[override]
        raise RuntimeError("ManagedCommunicator is configured by its Manager")

    def allreduce(
        self,
        buffers: Buffers,
        op: ReduceOp = ReduceOp.SUM,
        in_place: bool = False,
    ) -> Work:
        return self._manager.allreduce(buffers)

    def broadcast(self, buffers: Buffers, root: int = 0) -> Work:
        return self._manager._comm.broadcast(buffers, root)

    def reduce_scatter(
        self, data: np.ndarray, op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        return self._manager._comm.reduce_scatter(data, op)

    def send_bytes(self, data: bytes, dst: int, tag: int = 0) -> Work:
        return self._manager._comm.send_bytes(data, dst, tag)

    def recv_bytes(self, src: int, tag: int = 0) -> Work:
        return self._manager._comm.recv_bytes(src, tag)

    def recv_bytes_into(self, src: int, out, tag: int = 0) -> Work:
        return self._manager._comm.recv_bytes_into(src, out, tag)

    def heal_drain(self, *args, **kwargs) -> Work:
        return self._manager._comm.heal_drain(*args, **kwargs)

    def lane_stats(self) -> Dict[str, object]:
        return self._manager._comm.lane_stats()

    def arm_faults(self, spec) -> None:
        self._manager._comm.arm_faults(spec)

    def hier_topology(self) -> Optional[Dict[str, object]]:
        return self._manager._comm.hier_topology()

    def intra_reduce(self, flat, op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._manager._comm.intra_reduce(flat, op)

    def intra_broadcast(self, flat, count: int, dtype=np.float32) -> Work:
        return self._manager._comm.intra_broadcast(flat, count, dtype)

    def leader_comm(self) -> "Communicator":
        return self._manager._comm.leader_comm()

    def barrier(self) -> Work:
        return self._manager._comm.barrier()

    def abort(self, reason: str = "aborted") -> None:
        self._manager._comm.abort(reason)

    def errored(self) -> Optional[Exception]:
        return self._manager._comm.errored()

    def rank(self) -> int:
        return self._manager.participating_rank() or 0

    def size(self) -> int:
        return self._manager.num_participants()
