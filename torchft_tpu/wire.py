"""Framed binary wire protocol for the torchft_tpu control plane.

The reference implements its control plane as gRPC/protobuf services
(``proto/torchft.proto:37-130``, tonic servers in ``src/lighthouse.rs`` /
``src/manager.rs``).  We use a purpose-built framed binary protocol instead:
it needs no code generation, is trivially implementable from both Python and
C++ (``native/``), and the control plane traffic is tiny (a few KB per step).

Framing
-------
Every message is one frame::

    u32  payload_len          (little endian, excludes these 4 bytes)
    u8   msg_type             (MsgType)
    ...  body                 (fields in fixed order per message type)

Primitive encodings (all little endian):

- ``u8`` / ``u32`` / ``u64`` / ``i64``: fixed width integers
- ``f64``: IEEE double
- ``str``: ``u32`` length + UTF-8 bytes
- ``bytes``: ``u32`` length + raw bytes
- ``bool``: ``u8`` 0/1
- ``list<T>``: ``u32`` count + items
- ``optional<T>``: ``u8`` present flag + value when present

Request deadlines ride in the request body as ``timeout_ms`` (u64) — the
server honors the client's deadline on blocking RPCs the same way the
reference parses the ``grpc-timeout`` header server-side
(``src/timeout.rs:26-69``).

Errors are returned as an ``ERROR`` frame carrying an error code and a
message; clients raise ``TimeoutError`` for deadline errors, mirroring the
pyo3 timeout mapping in ``src/lib.rs:673-685``.
"""

from __future__ import annotations

import os
import random
import socket
import struct
import time
from dataclasses import dataclass, field
from enum import IntEnum
from typing import List, Optional, Tuple

MAX_FRAME_BYTES = 64 * 1024 * 1024

# Dial attempts for control-plane connections (``connect()``), with
# jittered exponential backoff between attempts, all inside the caller's
# timeout budget — the analog of the reference's retry-with-backoff channel
# helper (``src/net.rs:16-42``), so replicas racing a restarting
# lighthouse/store don't die at dial time.
CONNECT_RETRIES_ENV = "TORCHFT_CONNECT_RETRIES"
_CONNECT_RETRIES_DEFAULT = 3
_CONNECT_BACKOFF_BASE_S = 0.1

# Wire version of the MGR_QUORUM_RESP body.  v1 is the original fixed field
# order; v2 appends the striped-healing fields (every healthy peer's replica
# rank + manager address, and the full recovery-destination set) AFTER the v1
# fields, prefixed by this version number.  v3 adds the spare-replica fields
# (is_spare, registered spare ids, participant manager addresses) in the
# same tail.  v4 adds the hierarchical coordination plane: LH_QUORUM_REQ
# grows a delta-base tail (the requester's last-seen quorum digest, so the
# lighthouse can answer with a LH_QUORUM_DELTA_RESP instead of the full
# membership), heartbeats may carry a spare warm-step tail, and the
# aggregated-beat messages (AGG_BEAT / LH_AGG_BEAT) exist at all.  v5 adds
# degraded-mode capacity: a replica that lost in-replica devices and
# re-lowered onto the survivors advertises a capacity fraction (0, 1] on
# its quorum registration and its heartbeats, the Quorum broadcast carries
# per-participant capacities, and MGR_QUORUM_RESP fans them out to every
# rank (data-shard rescale + weighted outer reduce inputs).  v1 decoders
# ignore trailing bytes and v2+ decoders treat their absence as "no
# striping/spare/delta/capacity info", so mixed fleets interoperate during
# a rolling upgrade; pin TORCHFT_WIRE_COMPAT=1/2/3/4 on upgraded processes
# until every peer understands the newer version (a v4 pin keeps every
# frame byte-identical to the pre-v5 protocol).  The v3 spare fields are
# additionally emitted only when spare content EXISTS (a spare-free fleet
# stays byte-for-byte on the v2 layout), the v5 capacity fields only when
# some replica is actually degraded (a full-capacity fleet stays
# byte-for-byte on the v4 layout), and a delta response is only ever sent
# to a requester that advertised a v4 delta base.
MANAGER_QUORUM_WIRE_VERSION = 5
WIRE_COMPAT_ENV = "TORCHFT_WIRE_COMPAT"

# QuorumMember roles (wire v3).  ACTIVE members count toward min_replicas /
# majority and run collectives; SPARE members pre-join the control plane and
# keep a warm shadow of the fleet state but contribute nothing until the
# lighthouse promotes them.  The role rides as a version-gated TAIL byte on
# LH_QUORUM_REQ (after timeout_ms) and the spare list as a tail on the
# Quorum broadcast — legacy decoders ignore trailing bytes, and the tails
# are emitted only when a spare is actually involved, so role-free fleets
# stay byte-identical to v2.
ROLE_ACTIVE = 0
ROLE_SPARE = 1


def manager_quorum_wire_version() -> int:
    compat = os.environ.get(WIRE_COMPAT_ENV)
    if compat:
        try:
            pinned = int(compat)
        except ValueError as e:
            # name the knob: a bare int() error deep in the quorum RPC path
            # would hide which env var is at fault
            raise ValueError(
                f"unparseable {WIRE_COMPAT_ENV}={compat!r} (expected an "
                f"integer wire version <= {MANAGER_QUORUM_WIRE_VERSION})"
            ) from e
        return max(1, min(MANAGER_QUORUM_WIRE_VERSION, pinned))
    return MANAGER_QUORUM_WIRE_VERSION


class MsgType(IntEnum):
    # Store ops (store.py)
    STORE_SET = 0x01
    STORE_GET = 0x02
    STORE_ADD = 0x03
    STORE_EXISTS = 0x04
    STORE_DELETE = 0x05
    STORE_OK = 0x0E
    # Lighthouse service (reference proto/torchft.proto:69-73)
    LH_QUORUM_REQ = 0x10
    LH_QUORUM_RESP = 0x11
    LH_HEARTBEAT_REQ = 0x12
    LH_HEARTBEAT_RESP = 0x13
    LH_STATUS_REQ = 0x14
    LH_STATUS_RESP = 0x15
    # Hierarchical coordination plane (wire v4, coord/aggregator.py):
    # AGG_BEAT is one member's heartbeat to its zone aggregator;
    # LH_AGG_BEAT is the aggregator's batched upstream flush (one RPC per
    # tick carrying every member beat collected since the last flush).
    # LH_QUORUM_DELTA_RESP answers a quorum request whose v4 tail
    # advertised a delta base the server still holds: membership deltas +
    # compact per-index step updates instead of the full member list.
    LH_AGG_BEAT_REQ = 0x16
    LH_AGG_BEAT_RESP = 0x17
    LH_QUORUM_DELTA_RESP = 0x18
    AGG_BEAT_REQ = 0x19
    AGG_BEAT_RESP = 0x1A
    # Manager service (reference proto/torchft.proto:124-130)
    MGR_QUORUM_REQ = 0x20
    MGR_QUORUM_RESP = 0x21
    MGR_CKPT_META_REQ = 0x22
    MGR_CKPT_META_RESP = 0x23
    MGR_SHOULD_COMMIT_REQ = 0x24
    MGR_SHOULD_COMMIT_RESP = 0x25
    MGR_KILL_REQ = 0x26
    MGR_KILL_RESP = 0x27
    # Spare warm channels (manager_server.py): chunk-addressable snapshot
    # index + ranges (per-chunk version watermarks ride the staged step),
    # and the outer-sync delta feed spares subscribe to.
    MGR_WARM_INDEX_REQ = 0x28
    MGR_WARM_INDEX_RESP = 0x29
    MGR_WARM_RANGE_REQ = 0x2A
    MGR_WARM_RANGE_RESP = 0x2B
    MGR_DELTA_REQ = 0x2C
    MGR_DELTA_RESP = 0x2D
    # Communicator data plane (communicator.py)
    COMM_HELLO = 0x30
    COMM_DATA = 0x31
    # Error frame (any service)
    ERROR = 0x7F


class ErrCode(IntEnum):
    UNKNOWN = 0
    TIMEOUT = 1
    NOT_FOUND = 2
    INVALID = 3
    SHUTDOWN = 4


# ---------------------------------------------------------------------------
# Data-plane collective tag registry
# ---------------------------------------------------------------------------
#
# Every COMM_DATA frame carries a u64 tag that pairs sends with receives
# within one mesh epoch.  The tag space used to be allocated by scattered
# literals (103, 880/881, 900, 4000/5000, 7000/8000, ...); this registry is
# now the single place tags are assigned, and the ftlint wire checker
# (torchft_tpu/analysis/wireproto.py) fails the build on any tag literal
# that is not declared here or any two allocations that collide.
#
# Two kinds of entry:
#
# - USER allocations: tag values callers pass to alltoall/allgather &c.
#   Declared as (base, span) — the caller may use [base, base+span).
# - WIRE offsets: namespace offsets the communicator adds to a user tag so
#   different primitives' frames can never pair up (alltoall vs allgather
#   vs leader-ring variants).
#
# Ring collectives allocate internally (RING_BUFFER_TAG_STRIDE per buffer,
# +1000/+2000 phase offsets) and the striped heal salts per step in a
# 10M-wide range (HEAL_STEP_TAG_STRIDE) on the dedicated p2p lane, so
# neither can collide with user allocations.

# -- USER tag allocations (value space: what callers pass as `tag=`) --------
STREAM_OUTER_TAG_BASE = 8  # streamed DiLoCo fragment sync (collectives.py):
STREAM_OUTER_TAG_SPAN = 88  # 8..95, carved into STREAM_FRAG_WINDOWS rotating
#   per-fragment windows so consecutive streamed fragment syncs can never
#   alias tags even if a late frame lingers past its sync's resolution.
#   Kept below every legacy allocation (and far below the wire offsets) so
#   the namespace-composition properties match the proven blocking path —
#   but ABOVE ftlint's ad-hoc literal ceiling (tags <= 7 are lint-legal
#   without registration; carving the window into that range would let an
#   unflagged literal alias streamed frames).
STREAM_FRAG_WINDOWS = 4  # a streamed sync frames in window key % WINDOWS
#   (key = outer step + fragment index — see Manager.outer_shard_allreduce)
STREAM_FRAG_WINDOW_SPAN = STREAM_OUTER_TAG_SPAN // STREAM_FRAG_WINDOWS  # 22
#   tags per window = 11 pipeline chunks (2 tags/chunk); the chunk planner
#   grows the chunk size past TORCHFT_OUTER_CHUNK_MB when a fragment would
#   need more chunks than its window holds.
QUANT_RING_TAG = 103  # quantized ring allreduce (collectives.py)
QUANT_PIPELINE_TAG_BASE = 110  # windowed quant pipeline, 2 tags/window
QUANT_PIPELINE_TAG_SPAN = 770  # 110..879 (384 windows ≈ 1.5 GB @ 4 MB)
RESHARD_LEN_TAG = 880  # outer-shard reshard: length exchange (local_sgd.py)
RESHARD_BLOB_TAG = 881  # outer-shard reshard: blob exchange (local_sgd.py)
OUTER_SHARD_TAG_BASE = 900  # sharded outer sync, 2 tags/chunk, <=64 chunks
OUTER_SHARD_TAG_SPAN = 128  # 900..1027
DEVICE_QUANT_PIPELINE_TAG_BASE = 1050  # on-device dequant+reduce pipeline
DEVICE_QUANT_PIPELINE_TAG_SPAN = 1950  # 1050..2999 (user tags stay below
#   every wire offset; the pipeline warns when a payload would need more
#   windows than its span covers)

# -- WIRE namespace offsets (added by the communicator, never by callers) ---
BROADCAST_TAG_OFFSET = 3000  # broadcast: offset + buffer index
ALLTOALL_TAG_OFFSET = 4000  # alltoall frames: offset + user tag
ALLGATHER_TAG_OFFSET = 5000  # allgather frames: offset + user tag
LEADER_ALLTOALL_TAG_OFFSET = 7000  # leader-ring alltoall (hierarchical)
LEADER_ALLGATHER_TAG_OFFSET = 8000  # leader-ring allgather (hierarchical)
HIER_HOST_BLOCK_TAG_OFFSET = 9000  # hier allgather host-block exchange
#   (applied ON TOP of ALLGATHER_TAG_OFFSET, so host-block frames live at
#   14000 + user tag — clear of every first-order namespace)

# -- internal allocators ----------------------------------------------------
RING_REDUCE_TAG_BASE = 30_000  # explicit reduce_scatter API calls
RING_BUFFER_TAG_STRIDE = 10_000  # multi-buffer allreduce: buffer i at i*stride
HEAL_TAG_BASE = 9000  # striped heal (comm_transport.py): base*1000 +
HEAL_STEP_TAG_STRIDE = 10_000_000  # step*stride salting, p2p lane only

# The machine-readable allocation table the ftlint wire checker enforces:
# name -> (base, span).  USER allocations must be pairwise disjoint and must
# stay below the smallest WIRE offset; WIRE offsets must be pairwise
# >= 1000 apart (the nominal per-namespace width).
#
# Honest limit of the static proof: the namespaces are nominal-width, so a
# user tag above 1000 composed with an offset spills past the next
# namespace boundary (e.g. allgather(1050+2w) -> 6051+2w crosses 7000 at
# w >= 475).  Pairing stays unambiguous in practice because within one
# pipeline the alltoall and allgather window tags have opposite parities
# and collectives on one communicator epoch are serialized per op thread —
# but the checker cannot prove that, which is why the quantized pipelines
# WARN at runtime when a payload would exceed the declared span (see
# collectives._allreduce_pipelined_sync).
USER_TAG_ALLOCATIONS = {
    "STREAM_OUTER": (STREAM_OUTER_TAG_BASE, STREAM_OUTER_TAG_SPAN),
    "QUANT_RING": (QUANT_RING_TAG, 1),
    "QUANT_PIPELINE": (QUANT_PIPELINE_TAG_BASE, QUANT_PIPELINE_TAG_SPAN),
    "RESHARD_LEN": (RESHARD_LEN_TAG, 1),
    "RESHARD_BLOB": (RESHARD_BLOB_TAG, 1),
    "OUTER_SHARD": (OUTER_SHARD_TAG_BASE, OUTER_SHARD_TAG_SPAN),
    "DEVICE_QUANT_PIPELINE": (
        DEVICE_QUANT_PIPELINE_TAG_BASE,
        DEVICE_QUANT_PIPELINE_TAG_SPAN,
    ),
}
WIRE_TAG_OFFSETS = {
    "BROADCAST": BROADCAST_TAG_OFFSET,
    "ALLTOALL": ALLTOALL_TAG_OFFSET,
    "ALLGATHER": ALLGATHER_TAG_OFFSET,
    "LEADER_ALLTOALL": LEADER_ALLTOALL_TAG_OFFSET,
    "LEADER_ALLGATHER": LEADER_ALLGATHER_TAG_OFFSET,
    "HIER_HOST_BLOCK": HIER_HOST_BLOCK_TAG_OFFSET,
}
INTERNAL_TAG_BASES = {
    "RING_REDUCE": RING_REDUCE_TAG_BASE,
    "RING_BUFFER_STRIDE": RING_BUFFER_TAG_STRIDE,
    "HEAL": HEAL_TAG_BASE,
    "HEAL_STEP_STRIDE": HEAL_STEP_TAG_STRIDE,
}


def stream_frag_tag_window(key: int) -> "tuple[int, int]":
    """``(tag_base, tag_span)`` of the rotating STREAM_OUTER window a
    streamed fragment sync must frame its chunk collectives in.  A pure
    function of the caller's window key, so every replica picks the
    identical window with no wire metadata.  The scheduler keys on
    ``outer step + fragment index`` (quorum-shared state, so a healed
    replica agrees with the survivors): consecutive streamed syncs land
    in disjoint windows — including at ``num_fragments=1``, where the
    advancing step alone rotates them — so a streamed sync can never
    pair a lingering frame from the previous (already-resolved) sync."""
    window = key % STREAM_FRAG_WINDOWS
    return (
        STREAM_OUTER_TAG_BASE + window * STREAM_FRAG_WINDOW_SPAN,
        STREAM_FRAG_WINDOW_SPAN,
    )


class WireError(RuntimeError):
    def __init__(self, code: ErrCode, msg: str) -> None:
        super().__init__(msg)
        self.code = code


class Writer:
    """Append-only little-endian message builder."""

    __slots__ = ("_buf",)

    def __init__(self) -> None:
        self._buf = bytearray()

    def u8(self, v: int) -> "Writer":
        self._buf += struct.pack("<B", v)
        return self

    def u32(self, v: int) -> "Writer":
        self._buf += struct.pack("<I", v)
        return self

    def u64(self, v: int) -> "Writer":
        self._buf += struct.pack("<Q", v)
        return self

    def i64(self, v: int) -> "Writer":
        self._buf += struct.pack("<q", v)
        return self

    def f64(self, v: float) -> "Writer":
        self._buf += struct.pack("<d", v)
        return self

    def boolean(self, v: bool) -> "Writer":
        return self.u8(1 if v else 0)

    def string(self, v: str) -> "Writer":
        raw = v.encode("utf-8")
        self.u32(len(raw))
        self._buf += raw
        return self

    def blob(self, v: bytes) -> "Writer":
        self.u32(len(v))
        self._buf += v
        return self

    def opt_i64(self, v: Optional[int]) -> "Writer":
        if v is None:
            return self.u8(0)
        return self.u8(1).i64(v)

    def payload(self) -> bytes:
        return bytes(self._buf)


class Reader:
    """Sequential little-endian message parser."""

    __slots__ = ("_view", "_off")

    def __init__(self, data: bytes) -> None:
        self._view = memoryview(data)
        self._off = 0

    def _take(self, n: int) -> memoryview:
        if self._off + n > len(self._view):
            raise WireError(ErrCode.INVALID, "truncated frame")
        out = self._view[self._off : self._off + n]
        self._off += n
        return out

    def u8(self) -> int:
        return struct.unpack("<B", self._take(1))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def boolean(self) -> bool:
        return self.u8() != 0

    def string(self) -> str:
        n = self.u32()
        return bytes(self._take(n)).decode("utf-8")

    def blob(self) -> bytes:
        n = self.u32()
        return bytes(self._take(n))

    def opt_i64(self) -> Optional[int]:
        if self.u8() == 0:
            return None
        return self.i64()

    def done(self) -> bool:
        return self._off == len(self._view)


# ---------------------------------------------------------------------------
# Shared control-plane dataclasses
# ---------------------------------------------------------------------------


@dataclass
class QuorumMember:
    """One replica group in a quorum.

    Mirrors ``QuorumMember`` in the reference wire protocol
    (``proto/torchft.proto:37-47``): identity, RPC address, store address for
    communicator rendezvous, current step, group world size, and the
    shrink_only / commit_failures / opaque-data knobs.
    """

    replica_id: str
    address: str = ""
    store_address: str = ""
    step: int = 0
    world_size: int = 1
    shrink_only: bool = False
    commit_failures: int = 0
    data: str = ""
    # NOT part of the fixed encode layout (legacy compatibility): the role
    # rides as a version-gated tail on the messages that carry members —
    # see ROLE_ACTIVE/ROLE_SPARE above.
    role: int = ROLE_ACTIVE
    # Degraded-mode capacity fraction (wire v5), also a version-gated tail:
    # 1.0 = full width; a replica that lost devices and re-lowered onto the
    # survivors advertises the surviving fraction.  Inputs to data-shard
    # rescale, the weighted outer reduce, and the lighthouse's
    # wound→swap→evict policy ladder.
    capacity: float = 1.0

    def encode(self, w: Writer) -> None:
        (
            w.string(self.replica_id)
            .string(self.address)
            .string(self.store_address)
            .i64(self.step)
            .u64(self.world_size)
            .boolean(self.shrink_only)
            .i64(self.commit_failures)
            .string(self.data)
        )

    @staticmethod
    def decode(r: Reader) -> "QuorumMember":
        return QuorumMember(
            replica_id=r.string(),
            address=r.string(),
            store_address=r.string(),
            step=r.i64(),
            world_size=r.u64(),
            shrink_only=r.boolean(),
            commit_failures=r.i64(),
            data=r.string(),
        )


@dataclass
class CommHealth:
    """Compact cumulative comm-health summary one replica reports with its
    heartbeats (derived from ``Communicator.lane_stats()``): data-plane
    stall events, in-epoch lane reconnects/failovers, injected faults, and
    payload bytes moved.  Counters are job-lifetime cumulative so the
    lighthouse can difference consecutive beats into rates.

    Rides OPTIONALLY at the tail of ``LH_HEARTBEAT_REQ`` (flag byte +
    fixed-width fields): a legacy server reads the replica id and ignores
    the tail; a new server treats absence as "no health report"."""

    stalls: int = 0
    reconnects: int = 0
    failovers: int = 0
    faults: int = 0
    tx_bytes: int = 0
    rx_bytes: int = 0

    def encode(self, w: Writer) -> None:
        (
            w.u64(self.stalls)
            .u64(self.reconnects)
            .u64(self.failovers)
            .u64(self.faults)
            .u64(self.tx_bytes)
            .u64(self.rx_bytes)
        )

    @staticmethod
    def decode(r: Reader) -> "CommHealth":
        return CommHealth(
            stalls=r.u64(),
            reconnects=r.u64(),
            failovers=r.u64(),
            faults=r.u64(),
            tx_bytes=r.u64(),
            rx_bytes=r.u64(),
        )


@dataclass
class Quorum:
    """A computed quorum (``proto/torchft.proto`` ``Quorum`` message).

    ``spares`` (wire v3) rides as a version-gated tail AFTER the
    participant list: registered spare replicas that pre-joined the control
    plane but are NOT participants — they never count toward membership,
    never affect ``quorum_id``, and a v1/v2 decoder never sees them (it
    stops after the participants).  The tail is emitted only when spares
    exist, so spare-free quorums stay byte-identical to v2.

    Per-participant capacities (wire v5) ride a second tail AFTER the
    spares tail, emitted only when some participant is actually degraded
    (full-capacity quorums stay byte-identical to v4); when emitted, the
    spares tail is always emitted too (possibly with zero spares) so v3/v4
    decoders — which read the first tail as spares — stop cleanly before
    the capacity bytes."""

    quorum_id: int
    participants: List[QuorumMember] = field(default_factory=list)
    created: float = 0.0  # unix seconds
    spares: List[QuorumMember] = field(default_factory=list)

    def encode(self, w: Writer) -> None:
        w.i64(self.quorum_id).f64(self.created).u32(len(self.participants))
        for p in self.participants:
            p.encode(w)
        wire_version = manager_quorum_wire_version()
        has_capacity_tail = wire_version >= 5 and any(
            p.capacity != 1.0 for p in self.participants
        )
        # the capacity tail implies the spares tail (possibly empty): v3/v4
        # decoders read the first tail as spares and stop before the
        # capacity bytes
        has_spare_tail = wire_version >= 3 and (
            bool(self.spares) or has_capacity_tail
        )
        if has_spare_tail:
            w.u32(3)
            w.u32(len(self.spares))
            for s in self.spares:
                s.encode(w)
        if has_capacity_tail:
            w.u32(5)
            w.u32(len(self.participants))
            for p in self.participants:
                w.f64(p.capacity)

    @staticmethod
    def decode(r: Reader) -> "Quorum":
        quorum_id = r.i64()
        created = r.f64()
        n = r.u32()
        out = Quorum(
            quorum_id=quorum_id,
            created=created,
            participants=[QuorumMember.decode(r) for _ in range(n)],
        )
        if not r.done() and r.u32() >= 3:
            out.spares = [QuorumMember.decode(r) for _ in range(r.u32())]
            for s in out.spares:
                s.role = ROLE_SPARE
        if not r.done() and r.u32() >= 5:
            capacities = [r.f64() for _ in range(r.u32())]
            for p, cap in zip(out.participants, capacities):
                p.capacity = cap
        return out


def _member_sig(m: QuorumMember) -> tuple:
    """Canonical identity of one member for digest/delta math: the fixed
    wire-layout fields only.  ``role`` is deliberately excluded — it never
    rides the fixed layout (which list a member appears in IS its role), so
    including it would make server-side digests (which may hold a promoted
    spare's original role) disagree with a client's decoded view.

    ``capacity`` (wire v5) is appended ONLY when degraded: a full-capacity
    member's sig is byte-for-byte what a v4 peer computes, so mixed v4/v5
    fleets keep agreeing on digests (and riding deltas) until somebody is
    actually wounded — at which point the v4 peer's digest mismatch
    degrades it to full snapshots, never to a wrong membership view."""
    sig = (
        m.replica_id,
        m.address,
        m.store_address,
        m.step,
        m.world_size,
        m.shrink_only,
        m.commit_failures,
        m.data,
    )
    return sig if m.capacity == 1.0 else sig + (m.capacity,)


def _member_static_sig(m: QuorumMember) -> tuple:
    """Like :func:`_member_sig` minus the per-round movers (step,
    commit_failures) — members equal under this sig ride a quorum delta as
    a compact per-index step update instead of a full record.  ``capacity``
    rides here too (conditionally, like :func:`_member_sig`): a capacity
    change must travel as a full upsert, never be lost in a step update."""
    sig = (
        m.replica_id,
        m.address,
        m.store_address,
        m.world_size,
        m.shrink_only,
        m.data,
    )
    return sig if m.capacity == 1.0 else sig + (m.capacity,)


def quorum_digest(quorum: "Quorum") -> int:
    """Stable 64-bit content digest of a quorum's membership (participants
    + spares, canonical sorted order), independent of wire version and of
    ``quorum_id``/``created`` (those ride the delta header).  Both ends of
    a delta-coded broadcast verify against it."""
    import hashlib

    h = hashlib.blake2b(digest_size=8)
    for m in quorum.participants:
        h.update(repr(_member_sig(m)).encode())
    h.update(b"|spares|")
    for s in quorum.spares:
        h.update(repr(_member_sig(s)).encode())
    return int.from_bytes(h.digest(), "little")


@dataclass
class MemberBeat:
    """One member's heartbeat as carried to (and batched by) a zone
    aggregator (wire v4).  ``warm_step`` is the spare warm watermark
    (-1 for actives / unknown) so spare warm-progress rides the aggregate
    instead of requiring a quorum-RPC re-registration; ``health`` is the
    same cumulative :class:`CommHealth` summary a direct heartbeat
    carries."""

    replica_id: str
    role: int = ROLE_ACTIVE
    warm_step: int = -1
    health: Optional[CommHealth] = None

    def encode(self, w: Writer) -> None:
        w.string(self.replica_id).u8(self.role).i64(self.warm_step)
        w.boolean(self.health is not None)
        if self.health is not None:
            self.health.encode(w)

    @staticmethod
    def decode(r: Reader) -> "MemberBeat":
        return MemberBeat(
            replica_id=r.string(),
            role=r.u8(),
            warm_step=r.i64(),
            health=CommHealth.decode(r) if r.boolean() else None,
        )


@dataclass
class AggBeat:
    """One aggregator→lighthouse flush (wire v4): the aggregator's id plus
    every member beat collected since the previous flush (latest per
    member).  One upstream RPC per tick replaces one RPC per member per
    heartbeat interval."""

    agg_id: str
    beats: List[MemberBeat] = field(default_factory=list)

    def encode(self, w: Writer) -> None:
        w.string(self.agg_id)
        w.u32(len(self.beats))
        for b in self.beats:
            b.encode(w)

    @staticmethod
    def decode(r: Reader) -> "AggBeat":
        return AggBeat(
            agg_id=r.string(),
            beats=[MemberBeat.decode(r) for _ in range(r.u32())],
        )


@dataclass
class QuorumDelta:
    """Delta-coded quorum broadcast (wire v4): the edit from a base quorum
    (identified by content digest) to the new one.  Membership changes ride
    as removals + full upserted member records; members whose only movers
    are ``step``/``commit_failures`` (the common case — everyone advances
    one step per round) ride as compact ``(base_index, step,
    commit_failures)`` triples against the base's canonical sorted order.
    The receiver applies the edit to its cached base and verifies
    ``new_digest`` — a mismatch is a protocol error, and the client falls
    back to a full snapshot on its next request.

    Upserted members' degraded capacities (wire v5) ride a version-gated
    tail aligned with ``upserts`` (a capacity change always travels as a
    full upsert — ``_member_static_sig`` includes capacity); emitted only
    when some upsert is actually degraded, so full-capacity deltas stay
    byte-identical to v4."""

    quorum_id: int = 0
    created: float = 0.0
    base_digest: int = 0
    new_digest: int = 0
    removed: List[str] = field(default_factory=list)
    upserts: List[QuorumMember] = field(default_factory=list)
    step_updates: List[Tuple[int, int, int]] = field(default_factory=list)
    spare_removed: List[str] = field(default_factory=list)
    spare_upserts: List[QuorumMember] = field(default_factory=list)

    def encode(self, w: Writer) -> None:
        w.i64(self.quorum_id).f64(self.created)
        w.u64(self.base_digest).u64(self.new_digest)
        w.u32(len(self.removed))
        for rid in self.removed:
            w.string(rid)
        w.u32(len(self.upserts))
        for m in self.upserts:
            m.encode(w)
        w.u32(len(self.step_updates))
        for idx, step, cf in self.step_updates:
            w.u32(idx)
            w.i64(step)
            w.i64(cf)
        w.u32(len(self.spare_removed))
        for rid in self.spare_removed:
            w.string(rid)
        w.u32(len(self.spare_upserts))
        for s in self.spare_upserts:
            s.encode(w)
        if manager_quorum_wire_version() >= 5 and any(
            m.capacity != 1.0 for m in self.upserts
        ):
            w.u32(5)
            w.u32(len(self.upserts))
            for m in self.upserts:
                w.f64(m.capacity)

    @staticmethod
    def decode(r: Reader) -> "QuorumDelta":
        out = QuorumDelta(
            quorum_id=r.i64(),
            created=r.f64(),
            base_digest=r.u64(),
            new_digest=r.u64(),
        )
        out.removed = [r.string() for _ in range(r.u32())]
        out.upserts = [QuorumMember.decode(r) for _ in range(r.u32())]
        n_steps = r.u32()
        for _ in range(n_steps):
            idx = r.u32()
            step = r.i64()
            cf = r.i64()
            out.step_updates.append((idx, step, cf))
        out.spare_removed = [r.string() for _ in range(r.u32())]
        out.spare_upserts = [QuorumMember.decode(r) for _ in range(r.u32())]
        for s in out.spare_upserts:
            s.role = ROLE_SPARE
        if not r.done() and r.u32() >= 5:
            capacities = [r.f64() for _ in range(r.u32())]
            for m, cap in zip(out.upserts, capacities):
                m.capacity = cap
        return out


def make_quorum_delta(base: "Quorum", new: "Quorum") -> QuorumDelta:
    """Compute the delta turning ``base`` into ``new`` (both in canonical
    sorted order, as the lighthouse issues them)."""
    base_map = {m.replica_id: (i, m) for i, m in enumerate(base.participants)}
    new_ids = {m.replica_id for m in new.participants}
    delta = QuorumDelta(
        quorum_id=new.quorum_id,
        created=new.created,
        base_digest=quorum_digest(base),
        new_digest=quorum_digest(new),
        removed=[rid for rid in base_map if rid not in new_ids],
    )
    for m in new.participants:
        entry = base_map.get(m.replica_id)
        if entry is None:
            delta.upserts.append(m)
            continue
        idx, bm = entry
        if _member_sig(m) == _member_sig(bm):
            continue
        if _member_static_sig(m) == _member_static_sig(bm):
            delta.step_updates.append((idx, m.step, m.commit_failures))
        else:
            delta.upserts.append(m)
    base_spares = {s.replica_id: s for s in base.spares}
    new_spare_ids = {s.replica_id for s in new.spares}
    delta.spare_removed = [
        rid for rid in base_spares if rid not in new_spare_ids
    ]
    delta.spare_upserts = [
        s
        for s in new.spares
        if s.replica_id not in base_spares
        or _member_sig(s) != _member_sig(base_spares[s.replica_id])
    ]
    return delta


def apply_quorum_delta(
    base: Optional["Quorum"],
    delta: QuorumDelta,
    base_digest: Optional[int] = None,
) -> "Quorum":
    """Apply one :class:`QuorumDelta` to the cached base quorum, verifying
    both digests.  Raises :class:`WireError` (INVALID) on any mismatch —
    the caller must clear its cache so its next request advertises no base
    and receives a full snapshot."""
    import dataclasses

    if base is None:
        raise WireError(ErrCode.INVALID, "quorum delta without a cached base")
    if base_digest is None:
        base_digest = quorum_digest(base)
    if base_digest != delta.base_digest:
        raise WireError(
            ErrCode.INVALID,
            f"quorum delta base digest mismatch "
            f"(have {base_digest:#x}, delta wants {delta.base_digest:#x})",
        )
    parts = list(base.participants)
    for idx, step, cf in delta.step_updates:
        if idx >= len(parts):
            raise WireError(
                ErrCode.INVALID,
                f"quorum delta step update index {idx} out of range "
                f"({len(parts)} base participants)",
            )
        parts[idx] = dataclasses.replace(
            parts[idx], step=step, commit_failures=cf
        )
    by_id = {m.replica_id: m for m in parts}
    for rid in delta.removed:
        by_id.pop(rid, None)
    for m in delta.upserts:
        by_id[m.replica_id] = m
    spares_by_id = {s.replica_id: s for s in base.spares}
    for rid in delta.spare_removed:
        spares_by_id.pop(rid, None)
    for s in delta.spare_upserts:
        spares_by_id[s.replica_id] = s
    out = Quorum(
        quorum_id=delta.quorum_id,
        created=delta.created,
        participants=sorted(by_id.values(), key=lambda m: m.replica_id),
        spares=sorted(spares_by_id.values(), key=lambda m: m.replica_id),
    )
    if quorum_digest(out) != delta.new_digest:
        raise WireError(
            ErrCode.INVALID,
            "quorum delta digest mismatch after apply (divergent base)",
        )
    return out


@dataclass
class ManagerQuorumResult:
    """Per-rank quorum view computed by the manager server.

    Mirrors ``ManagerQuorumResponse`` (``proto/torchft.proto:84-100``) and the
    pyo3 ``QuorumResult`` (``src/lib.rs:284-319``): the deterministic
    replica_rank, recovery source/destinations, the primary store address for
    communicator rendezvous, and max-step participation facts.
    """

    quorum_id: int = 0
    replica_rank: int = 0
    replica_world_size: int = 1
    recover_src_manager_address: str = ""
    recover_src_replica_rank: Optional[int] = None
    recover_dst_replica_ranks: List[int] = field(default_factory=list)
    store_address: str = ""
    max_step: int = 0
    max_replica_rank: Optional[int] = None
    max_world_size: int = 1
    heal: bool = False
    commit_failures: int = 0
    replica_ids: List[str] = field(default_factory=list)
    # -- v2 (striped healing) ------------------------------------------------
    # Canonical ascending list of every max-step replica rank able to serve a
    # heal, with matching manager addresses.  The ORDER is load-bearing: the
    # CommTransport chunk assignment is `chunk_idx % len(sources)` against
    # this exact list on both the sending and healing side.  Empty on v1
    # peers and when nobody is recovering.
    recover_src_replica_ranks: List[int] = field(default_factory=list)
    recover_src_manager_addresses: List[str] = field(default_factory=list)
    # Every recovering replica rank (the union of all sources' recover_dst
    # assignments) so EVERY healthy peer — not just the round-robin primary —
    # stages/serves its checkpoint for a striped heal.
    all_recover_dst_replica_ranks: List[int] = field(default_factory=list)
    # -- v3 (hot spares) -----------------------------------------------------
    # True when THIS replica is a registered spare of the quorum (not a
    # participant): it must warm, not train.  ``spare_replica_ids`` lists
    # the registered spares (actives use it to keep a warm snapshot
    # staged); ``all_manager_addresses`` aligns with ``replica_ids`` so a
    # spare can reach every participant's manager for warm fetches and the
    # outer-delta feed.  Emitted only when spare content exists — a
    # spare-free fleet stays byte-for-byte on the v2 layout.
    is_spare: bool = False
    spare_replica_ids: List[str] = field(default_factory=list)
    all_manager_addresses: List[str] = field(default_factory=list)
    # -- v5 (degraded-mode capacity) -----------------------------------------
    # Per-participant capacity fractions aligned with ``replica_ids`` so
    # every rank can rescale its data shard and weight the outer reduce.
    # Emitted only when some participant is actually degraded — a
    # full-capacity fleet stays byte-for-byte on the v4 layout.
    participant_capacities: List[float] = field(default_factory=list)

    def heal_sources(self) -> List[Tuple[int, str]]:
        """(replica_rank, manager_address) of every peer able to serve this
        replica's heal, canonical order; falls back to the single v1
        recover_src when the v2 fields are absent."""
        if self.recover_src_replica_ranks:
            return list(
                zip(self.recover_src_replica_ranks, self.recover_src_manager_addresses)
            )
        if self.recover_src_replica_rank is not None:
            return [(self.recover_src_replica_rank, self.recover_src_manager_address)]
        return []

    def encode(self, w: Writer) -> None:
        w.i64(self.quorum_id)
        w.i64(self.replica_rank)
        w.i64(self.replica_world_size)
        w.string(self.recover_src_manager_address)
        w.opt_i64(self.recover_src_replica_rank)
        w.u32(len(self.recover_dst_replica_ranks))
        for rank in self.recover_dst_replica_ranks:
            w.i64(rank)
        w.string(self.store_address)
        w.i64(self.max_step)
        w.opt_i64(self.max_replica_rank)
        w.i64(self.max_world_size)
        w.boolean(self.heal)
        w.i64(self.commit_failures)
        w.u32(len(self.replica_ids))
        for rid in self.replica_ids:
            w.string(rid)
        wire_version = manager_quorum_wire_version()
        has_capacity_tail = wire_version >= 5 and any(
            c != 1.0 for c in self.participant_capacities
        )
        has_spare_tail = wire_version >= 3 and (
            self.is_spare or bool(self.spare_replica_ids) or has_capacity_tail
        )
        if wire_version >= 2:
            w.u32(
                5 if has_capacity_tail else 3 if has_spare_tail else 2
            )
            w.u32(len(self.recover_src_replica_ranks))
            for rank in self.recover_src_replica_ranks:
                w.i64(rank)
            w.u32(len(self.recover_src_manager_addresses))
            for addr in self.recover_src_manager_addresses:
                w.string(addr)
            w.u32(len(self.all_recover_dst_replica_ranks))
            for rank in self.all_recover_dst_replica_ranks:
                w.i64(rank)
        if has_spare_tail:
            w.boolean(self.is_spare)
            w.u32(len(self.spare_replica_ids))
            for rid in self.spare_replica_ids:
                w.string(rid)
            w.u32(len(self.all_manager_addresses))
            for addr in self.all_manager_addresses:
                w.string(addr)
        if has_capacity_tail:
            w.u32(len(self.participant_capacities))
            for cap in self.participant_capacities:
                w.f64(cap)

    @staticmethod
    def decode(r: Reader) -> "ManagerQuorumResult":
        out = ManagerQuorumResult()
        out.quorum_id = r.i64()
        out.replica_rank = r.i64()
        out.replica_world_size = r.i64()
        out.recover_src_manager_address = r.string()
        out.recover_src_replica_rank = r.opt_i64()
        out.recover_dst_replica_ranks = [r.i64() for _ in range(r.u32())]
        out.store_address = r.string()
        out.max_step = r.i64()
        out.max_replica_rank = r.opt_i64()
        out.max_world_size = r.i64()
        out.heal = r.boolean()
        out.commit_failures = r.i64()
        out.replica_ids = [r.string() for _ in range(r.u32())]
        if not r.done():
            tail_version = r.u32()
            if tail_version >= 2:
                out.recover_src_replica_ranks = [
                    r.i64() for _ in range(r.u32())
                ]
                out.recover_src_manager_addresses = [
                    r.string() for _ in range(r.u32())
                ]
                out.all_recover_dst_replica_ranks = [
                    r.i64() for _ in range(r.u32())
                ]
            if tail_version >= 3:
                out.is_spare = r.boolean()
                out.spare_replica_ids = [r.string() for _ in range(r.u32())]
                out.all_manager_addresses = [
                    r.string() for _ in range(r.u32())
                ]
            if tail_version >= 5:
                out.participant_capacities = [
                    r.f64() for _ in range(r.u32())
                ]
        return out


# ---------------------------------------------------------------------------
# Socket framing helpers
# ---------------------------------------------------------------------------


def send_frame(sock: socket.socket, msg_type: int, payload: bytes = b"") -> None:
    header = struct.pack("<IB", len(payload) + 1, msg_type)
    sock.sendall(header + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining > 0:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> tuple[int, Reader]:
    """Receive one frame, returning (msg_type, body reader).

    Raises ``ConnectionError`` on EOF and ``socket.timeout`` on socket
    timeouts (callers translate to ``TimeoutError``).
    """
    (length,) = struct.unpack("<I", _recv_exact(sock, 4))
    if length < 1 or length > MAX_FRAME_BYTES:
        raise WireError(ErrCode.INVALID, f"bad frame length {length}")
    body = _recv_exact(sock, length)
    return body[0], Reader(body[1:])


def send_error(sock: socket.socket, code: ErrCode, msg: str) -> None:
    send_frame(sock, MsgType.ERROR, Writer().u8(int(code)).string(msg).payload())


def raise_if_error(msg_type: int, r: Reader) -> None:
    """Translate an ERROR frame into the appropriate Python exception."""
    if msg_type != MsgType.ERROR:
        return
    code = ErrCode(r.u8())
    msg = r.string()
    if code == ErrCode.TIMEOUT:
        raise TimeoutError(msg)
    raise WireError(code, msg)


def read_http_path(sock: socket.socket, timeout: float = 5.0) -> Optional[str]:
    """Read one HTTP request head off ``sock`` and return its path (None
    when the peer closes before a full head arrives).  Shared by the
    lighthouse dashboard and the ManagerServer /metrics endpoint — both
    sniff HTTP off their framed-RPC ports."""
    sock.settimeout(timeout)
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = sock.recv(4096)
        if not chunk:
            return None
        data += chunk
    request_line = data.split(b"\r\n", 1)[0].decode("latin-1")
    parts = request_line.split()
    return parts[1] if len(parts) >= 2 else "/"


def send_http_response(
    sock: socket.socket, status: str, ctype: str, body: bytes
) -> None:
    """One complete connection-close HTTP response (best-effort: a dead
    client must not raise into the serving loop)."""
    resp = (
        f"HTTP/1.1 {status}\r\nContent-Type: {ctype}\r\n"
        f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
    ).encode() + body
    try:
        sock.sendall(resp)
    except OSError:
        pass


def create_listener(bind: str, backlog: int = 512) -> socket.socket:
    """Bound+listening server socket from a ``host:port`` string, dual-stack
    where possible (the reference binds ``[::]`` with v6only off so one
    socket serves both families, ``torchft/http.py:11-13``).

    ``0.0.0.0`` / ``[::]`` / empty host → wildcard dual-stack (falls back to
    IPv4-only on kernels without IPv6); an explicit IPv6 literal (in
    brackets) or any address that resolves to v6 binds AF_INET6; everything
    else AF_INET."""
    raw_host, _, port_str = bind.rpartition(":")
    host = raw_host.strip("[]")
    port = int(port_str)
    wildcard = host in ("", "0.0.0.0", "::")
    candidates = []
    if wildcard:
        candidates.append((socket.AF_INET6, "::", True))
        candidates.append((socket.AF_INET, "0.0.0.0", False))
    else:
        try:
            infos = socket.getaddrinfo(
                host, port, type=socket.SOCK_STREAM, flags=socket.AI_PASSIVE
            )
        except socket.gaierror:
            infos = [(socket.AF_INET, None, None, None, (host, port))]
        # v4 results first: a hostname like "localhost" resolving to ::1
        # first must not silently become a v6-only listener that refuses
        # the v4 clients it served before (an explicit [v6] literal still
        # resolves to AF_INET6 only)
        infos = sorted(infos, key=lambda i: i[0] != socket.AF_INET)
        for family, *_rest, sockaddr in infos:
            candidates.append((family, sockaddr[0], False))
    last_err: Optional[OSError] = None
    for family, bind_host, dual in candidates:
        sock = socket.socket(family, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if dual and hasattr(socket, "IPV6_V6ONLY"):
                # dual-stack: one wildcard socket accepts v4-mapped peers too
                sock.setsockopt(socket.IPPROTO_IPV6, socket.IPV6_V6ONLY, 0)
            sock.bind((bind_host, port))
            sock.listen(backlog)
            return sock
        except OSError as e:
            last_err = e
            sock.close()
    raise last_err if last_err else OSError(f"cannot bind {bind!r}")


def connect(addr: str, timeout: float, retries: Optional[int] = None) -> socket.socket:
    """Dial ``host:port`` with a connect deadline and bounded jittered
    retry (the reference's channel helper retries with exponential backoff
    and HTTP2 keepalives, ``src/net.rs:16-42``; TCP keepalive serves the
    same dead-server-detection role here).

    A refused/unreachable dial is retried up to ``retries`` times
    (``TORCHFT_CONNECT_RETRIES``, default 3) with jittered exponential
    backoff, never exceeding the overall ``timeout`` budget — so a replica
    racing a restarting lighthouse/store rides out the restart instead of
    dying at dial time."""
    host, port_str = addr.rsplit(":", 1)
    host = host.strip("[]")
    if retries is None:
        from torchft_tpu import knobs

        retries = knobs.get_int(CONNECT_RETRIES_ENV, _CONNECT_RETRIES_DEFAULT)
    deadline = time.monotonic() + timeout
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        try:
            sock = socket.create_connection(
                (host, int(port_str)), timeout=max(0.05, remaining)
            )
            break
        except OSError:
            attempt += 1
            backoff = (
                _CONNECT_BACKOFF_BASE_S
                * (2 ** (attempt - 1))
                * (0.5 + random.random())
            )
            if attempt > retries or time.monotonic() + backoff >= deadline:
                raise
            time.sleep(backoff)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    return sock


def configure_server_socket(conn: socket.socket) -> None:
    """Options for server-accepted connections: keepalive mirrors connect()
    so a silently-dead peer can't park a handler thread forever."""
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    conn.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)


class RpcClient:
    """Single-socket request/response client with reconnect-on-timeout.

    Shared base for the store / lighthouse / manager clients.  After a
    client-side timeout the server's late response may still arrive; reusing
    the socket would mispair it with the next rpc, so the socket is dropped
    and re-dialed on the next call.  ``headroom_s`` keeps the client deadline
    behind the server-honored deadline so the server's TIMEOUT error frame
    (the analog of honoring ``grpc-timeout`` server-side) wins the race.
    """

    def __init__(
        self, addr: str, connect_timeout: float, headroom_s: float = 5.0
    ) -> None:
        import threading

        self._addr = addr
        self._connect_timeout = connect_timeout
        self._headroom_s = headroom_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = connect(addr, connect_timeout)

    @property
    def addr(self) -> str:
        return self._addr

    def _drop_socket(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(
        self,
        msg_type: int,
        payload: bytes,
        timeout: float,
        idempotent: bool = False,
    ) -> tuple[int, Reader]:
        """One rpc round-trip; raises ``TimeoutError`` on deadline and drops
        the socket on any transport fault.

        ``idempotent=True`` grants ONE bounded reconnect-retry after a
        transport fault (reset/refused — never a timeout, which may mean
        the server acted): safe only for rpcs whose re-execution is
        harmless (heartbeat, status, store get/exists), and exactly what
        keeps a replica alive through a lighthouse connection blip."""
        # The three blocking-under-lock pragmas below share one reason: this
        # lock EXISTS to serialize the single-connection round-trip (one
        # outstanding rpc per client), every call sets a socket deadline
        # first, and interrupt() closes the socket from another thread to
        # sever a wedged call — the lock is never held indefinitely.
        with self._lock:
            attempts = 2 if idempotent else 1
            for attempt in range(attempts):
                if self._sock is None:
                    # ftlint: ignore[blocking-under-lock] — see above
                    self._sock = connect(self._addr, self._connect_timeout)
                self._sock.settimeout(timeout + self._headroom_s)
                try:
                    # ftlint: ignore[blocking-under-lock] — see above
                    send_frame(self._sock, msg_type, payload)
                    return recv_frame(self._sock)  # ftlint: ignore[blocking-under-lock] — see above
                except socket.timeout as e:
                    self._drop_socket()
                    raise TimeoutError(
                        f"rpc 0x{msg_type:x} to {self._addr} timed out"
                    ) from e
                except WireError:
                    self._drop_socket()
                    raise
                except (ConnectionError, OSError):
                    self._drop_socket()
                    if attempt + 1 >= attempts:
                        raise
            raise AssertionError("unreachable")  # pragma: no cover

    def interrupt(self) -> None:
        """Sever the live socket WITHOUT taking the rpc lock: a call parked
        in recv on another thread errors out immediately instead of waiting
        its full deadline.  Used when the caller KNOWS the server went away
        and came back (e.g. a lighthouse restart detected by the heartbeat
        loop); the interrupted call's error path drops and re-dials."""
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            self._drop_socket()
