"""Central metric-name registry + Prometheus text-format rendering.

Every name served on a ``/metrics`` endpoint (the lighthouse's and every
ManagerServer's) is declared here EXACTLY ONCE — the ftlint
``metrics-registry`` checker enforces that each declared name is legal
Prometheus (``[a-z_:][a-z0-9_:]*``, counters end in ``_total``), unique,
documented in ``docs/operations.md`` §17, and that every
``metric_sample("...")`` call site in the package names a declared metric.
:func:`metric_sample` also enforces it at runtime, so an undeclared name
can never reach a scrape.

Naming: ``torchft_lh_*`` = lighthouse (fleet view, served from the
TTL-cached status snapshot — zero new lock traffic), ``torchft_mgr_*`` =
per-replica ManagerServer gauges (the same registry that feeds
``last_quorum_timings``).

:func:`parse_prometheus_text` is the strict parser the CI scrape smoke
test runs against both endpoints.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Tuple

_NAME_RE = re.compile(r"^[a-z_:][a-z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


@dataclass(frozen=True)
class Metric:
    name: str
    kind: str  # "gauge" | "counter"
    doc: str


REGISTRY: Dict[str, Metric] = {}


def _m(name: str, kind: str, doc: str) -> None:
    if name in REGISTRY:
        raise ValueError(f"duplicate metric declaration: {name}")
    if not _NAME_RE.match(name):
        raise ValueError(f"illegal Prometheus metric name: {name}")
    if kind not in ("gauge", "counter"):
        raise ValueError(f"unknown metric kind {kind!r} for {name}")
    if kind == "counter" and not name.endswith("_total"):
        raise ValueError(f"counter {name} must end in _total")
    REGISTRY[name] = Metric(name=name, kind=kind, doc=doc)


# --- lighthouse (fleet view; served from the TTL-cached /status snapshot) ---
_m("torchft_lh_quorum_id", "gauge", "Current quorum id (bumps on membership change / commit failure)")
_m("torchft_lh_max_step", "gauge", "Commit front: max step across the previous quorum's participants")
_m("torchft_lh_participants", "gauge", "Participants in the previous quorum")
_m("torchft_lh_heartbeating", "gauge", "Replicas with a registered heartbeat (actives + spares)")
_m("torchft_lh_spares", "gauge", "Registered hot spares (never counted toward membership)")
_m("torchft_lh_lagging_replicas", "gauge", "Participants behind the commit front (will heal next quorum)")
_m("torchft_lh_heal_sources", "gauge", "Up-to-date participants able to serve a striped heal")
_m("torchft_lh_promotions_total", "counter", "Spare promotions issued by the lighthouse")
_m("torchft_lh_evictions_total", "counter", "Straggler (slow-NIC) evictions issued")
_m("torchft_lh_degraded_evictions_total", "counter", "Evictions of replicas wounded below the capacity floor")
_m("torchft_lh_swaps_total", "counter", "Wounded-replica-for-warm-spare swaps issued")
_m("torchft_lh_status_rebuilds_total", "counter", "Status/metrics snapshot rebuilds (state-lock acquires; the scrape-storm regression gate)")
_m("torchft_lh_heartbeat_age_seconds", "gauge", "Seconds since each replica's last heartbeat")
_m("torchft_lh_replica_step", "gauge", "Last registered step per participant")
_m("torchft_lh_replica_capacity", "gauge", "Degraded-mode capacity fraction per participant (1 = full width)")
_m("torchft_lh_stall_rate", "gauge", "EWMA data-plane stall rate per replica (events/s, from heartbeat CommHealth)")
_m("torchft_lh_replica_flagged", "gauge", "1 when the straggler detector currently flags the replica")
_m("torchft_lh_spare_warm_lag_steps", "gauge", "Warm-watermark lag behind the commit front per spare")
_m("torchft_lh_rpc_inbound_total", "counter", "Inbound RPC frames by message type")
_m("torchft_lh_aggregated_members", "gauge", "Members whose last beat arrived via a zone aggregator")
_m("torchft_lh_agg_flush_age_seconds", "gauge", "Seconds since each zone aggregator's last flush")

# --- per-replica ManagerServer ---------------------------------------------
_m("torchft_mgr_step", "gauge", "This replica's committed step")
_m("torchft_mgr_quorum_id", "gauge", "Quorum id this replica last adopted")
_m("torchft_mgr_capacity", "gauge", "Degraded-mode capacity fraction this replica advertises")
_m("torchft_mgr_batches_committed_total", "counter", "Global batches committed (sum of participants over committed steps)")
_m("torchft_mgr_commit_failures", "gauge", "Consecutive failed commit votes (resets on commit)")
_m("torchft_mgr_quorum_rpc_seconds", "gauge", "Quorum RPC wall time of the most recent round")
_m("torchft_mgr_configure_seconds", "gauge", "Communicator reconfigure wall time of the most recent membership change")
_m("torchft_mgr_heal_send_seconds", "gauge", "Checkpoint-serve wall time of the most recent heal this replica sourced")
_m("torchft_mgr_heal_recv_seconds", "gauge", "Checkpoint-fetch wall time of the most recent heal this replica ran")
_m("torchft_mgr_heal_bytes_per_sec", "gauge", "Throughput of the most recent striped heal fetch")
_m("torchft_mgr_ring_lanes", "gauge", "TCP lanes per peer of the current data-plane epoch")
_m("torchft_mgr_outer_shard_overlap_ratio", "gauge", "Fraction of the last sharded outer update hidden under wire time")
_m("torchft_mgr_beats_via_agg_total", "counter", "Heartbeats routed through a zone aggregator")
_m("torchft_mgr_beats_direct_total", "counter", "Heartbeats sent directly to the lighthouse")
_m("torchft_mgr_agg_fallbacks_total", "counter", "Aggregator-unreachable fallbacks to direct beats")
_m("torchft_mgr_comm_tx_bytes_total", "counter", "Cumulative data-plane payload bytes sent (all epochs)")
_m("torchft_mgr_comm_rx_bytes_total", "counter", "Cumulative data-plane payload bytes received (all epochs)")
_m("torchft_mgr_comm_stalls_total", "counter", "Cumulative data-plane stall events (pacer denials / would-block)")
_m("torchft_mgr_comm_reconnects_total", "counter", "Cumulative in-epoch lane reconnects")
_m("torchft_mgr_comm_failovers_total", "counter", "Cumulative in-epoch lane failovers")
_m("torchft_mgr_comm_faults_total", "counter", "Cumulative injected data-plane faults (chaos)")
_m("torchft_mgr_flight_events", "gauge", "Events currently held in this replica's flight-recorder ring")
_m("torchft_mgr_flight_dumps_total", "counter", "Flight-recorder dumps written by this replica")


@dataclass(frozen=True)
class Sample:
    name: str
    value: float
    labels: Tuple[Tuple[str, str], ...] = ()


def metric_sample(
    name: str, value: object, labels: Optional[Mapping[str, str]] = None
) -> Optional[Sample]:
    """Build one sample of a DECLARED metric (raises KeyError on an
    undeclared name — the runtime half of the registry contract).  Returns
    None for a None/unparseable value so optional gauges drop out of the
    scrape instead of serving garbage."""
    if name not in REGISTRY:
        raise KeyError(
            f"{name} is not declared in torchft_tpu/obs/metrics.py — every "
            "/metrics name must be registered exactly once"
        )
    if value is None:
        return None
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    items: Tuple[Tuple[str, str], ...] = ()
    if labels:
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"illegal Prometheus label name: {k}")
        items = tuple(sorted((k, str(v2)) for k, v2 in labels.items()))
    return Sample(name=name, value=v, labels=items)


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render(samples: List[Optional[Sample]]) -> str:
    """Prometheus text exposition (version 0.0.4): samples grouped by
    metric with one ``# HELP`` / ``# TYPE`` header each, None entries
    (optional gauges with no value yet) dropped."""
    by_name: Dict[str, List[Sample]] = {}
    order: List[str] = []
    for s in samples:
        if s is None:
            continue
        if s.name not in by_name:
            by_name[s.name] = []
            order.append(s.name)
        by_name[s.name].append(s)
    lines: List[str] = []
    for name in order:
        metric = REGISTRY[name]
        lines.append(f"# HELP {name} {metric.doc}")
        lines.append(f"# TYPE {name} {metric.kind}")
        for s in by_name[name]:
            if s.labels:
                label_str = ",".join(
                    f'{k}="{_escape_label(v)}"' for k, v in s.labels
                )
                lines.append(f"{name}{{{label_str}}} {_format_value(s.value)}")
            else:
                lines.append(f"{name} {_format_value(s.value)}")
    return "\n".join(lines) + "\n" if lines else ""


# -- strict parser (the CI scrape smoke test) --------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-z_:][a-z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|\.\d+|Inf|NaN))$"
)
_LABEL_PAIR_RE = re.compile(
    r'^(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"$'
)


def parse_prometheus_text(text: str) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Strictly parse Prometheus text exposition: every non-comment line
    must be a well-formed sample, every sampled metric must carry HELP and
    TYPE headers that PRECEDE its first sample, and names/labels must be
    legal.  Raises ``ValueError`` on any violation; returns
    ``{name: [(labels, value), ...]}``."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    helped: Dict[str, bool] = {}
    typed: Dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 4 or not _NAME_RE.match(parts[2]):
                raise ValueError(f"line {lineno}: malformed HELP: {line!r}")
            helped[parts[2]] = True
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if (
                len(parts) < 4
                or not _NAME_RE.match(parts[2])
                or parts[3] not in ("gauge", "counter", "histogram", "summary", "untyped")
            ):
                raise ValueError(f"line {lineno}: malformed TYPE: {line!r}")
            typed[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue  # plain comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        name = m.group("name")
        if name not in helped or name not in typed:
            raise ValueError(
                f"line {lineno}: sample {name} not preceded by HELP+TYPE"
            )
        labels: Dict[str, str] = {}
        raw = m.group("labels")
        if raw is not None:
            if raw.strip():
                for pair in _split_label_pairs(raw, lineno):
                    pm = _LABEL_PAIR_RE.match(pair)
                    if not pm:
                        raise ValueError(
                            f"line {lineno}: malformed label pair {pair!r}"
                        )
                    labels[pm.group("k")] = (
                        pm.group("v")
                        .replace("\\n", "\n")
                        .replace('\\"', '"')
                        .replace("\\\\", "\\")
                    )
        out.setdefault(name, []).append((labels, float(m.group("value"))))
    return out


def _split_label_pairs(raw: str, lineno: int) -> List[str]:
    """Split ``k1="v1",k2="v2"`` respecting escaped quotes inside values."""
    pairs: List[str] = []
    depth_in_string = False
    start = 0
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\" and depth_in_string:
            i += 2
            continue
        if c == '"':
            depth_in_string = not depth_in_string
        elif c == "," and not depth_in_string:
            pairs.append(raw[start:i])
            start = i + 1
        i += 1
    if depth_in_string:
        raise ValueError(f"line {lineno}: unterminated label value")
    pairs.append(raw[start:])
    return [p for p in pairs if p]


def operations_md_table() -> str:
    """The docs/operations.md §17 metric-reference table, generated from
    this registry (the ftlint metrics-registry checker cross-checks it)."""
    lines = [
        "| Metric | Type | What it reports |",
        "|---|---|---|",
    ]
    for metric in sorted(REGISTRY.values(), key=lambda m: m.name):
        lines.append(f"| `{metric.name}` | {metric.kind} | {metric.doc} |")
    return "\n".join(lines)


if __name__ == "__main__":  # pragma: no cover - doc regeneration helper
    print(operations_md_table())
