"""Flight recorder: a lock-cheap per-replica ring of typed protocol events.

Every fault-tolerance mechanism in the stack emits scattered counters
(``lane_stats``, ``CommHealth``, ``last_quorum_timings``, the structured
loggers) — none of which answers the question operators actually ask after
an incident: *what exactly happened, in what order, across which replicas?*
The flight recorder answers it: each replica appends typed, monotonic-
stamped events keyed by ``(step, quorum_id, comm_epoch)`` to a bounded ring
(``TORCHFT_FLIGHT_EVENTS`` slots; ``collections.deque`` appends ride the
GIL, so the hot path takes no lock and costs ~a microsecond), and the ring
is dumped — newest state wins, written atomically — when something goes
wrong:

- **comm-epoch poison** (the communicator latched an error),
- the **Manager error funnel** (``report_error``),
- **SIGUSR2** (operator-requested snapshot of every live recorder),
- **atexit** / ``Manager.shutdown`` (the final complete ring).

Dumps land as ``flight_{replica_id}.jsonl`` under ``TORCHFT_FLIGHT_DIR``
(one JSON object per line, schema below) and announce themselves on the
``torchft_flight`` structured logger.  ``scripts/flight_merge.py`` aligns
several replicas' dumps on shared ``(quorum_id, step)`` anchors into one
Perfetto-loadable fleet timeline — the postmortem view.

The native tier records its epoch lifecycle into a C-side fixed-slot ring
(``native/comm.h``); :meth:`FlightRecorder.register_native_source` merges
those events into every dump via ``tpuft_comm_flight_drain`` (the ftlint
``native-mirror`` checker pins the event-id enum across the tiers).

Event schema (one JSON object per line)::

    {"seq": 17, "t": 1234.567890, "ev": 2, "name": "QUORUM_ADOPT",
     "step": 40, "quorum_id": 3, "comm_epoch": 5, "replica_id": "train_0",
     ...detail keys, "native": true when drained from the C ring}
"""

from __future__ import annotations

import atexit
import collections
import enum
import itertools
import json
import logging
import os
import signal
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from torchft_tpu import knobs

logger = logging.getLogger(__name__)

FLIGHT_EVENTS_ENV = "TORCHFT_FLIGHT_EVENTS"
FLIGHT_DIR_ENV = "TORCHFT_FLIGHT_DIR"
FLIGHT_DUMP_MIN_S_ENV = "TORCHFT_FLIGHT_DUMP_MIN_S"


class FlightEvent(enum.IntEnum):
    """Typed flight-recorder events.  Values are STABLE WIRE IDS: dumps
    carry them numerically, the merge tool keys on them, and the native
    tier mirrors the data-plane block (20..29) as ``kFlight*`` constants in
    ``native/comm.h`` — the ftlint ``native-mirror`` checker fails the
    build on any drift.  Add new events at the end of their block; never
    renumber."""

    # -- Manager state machine ---------------------------------------------
    QUORUM_START = 1  # start_quorum called (step)
    QUORUM_ADOPT = 2  # quorum adopted / reconfigured (quorum_id, world)
    COMMIT_FENCE = 3  # pending works + recovery fenced before the vote
    COMMIT_VOTE = 4  # this replica's local vote (detail: local)
    COMMIT_RESULT = 5  # the fleet's AND-decision (detail: committed)
    ERROR = 6  # error funnel (detail: error)
    # -- heal phases ---------------------------------------------------------
    HEAL_SEND_BEGIN = 7
    HEAL_SEND_END = 8  # detail: dst_ranks, duration_s
    HEAL_RECV_BEGIN = 9
    HEAL_RECV_END = 10  # detail: bytes, sources, duration_s
    HEAL_APPLY = 11  # pending state dict applied on the train thread
    # -- hot spares ----------------------------------------------------------
    SPARE_WARM = 12  # warm progress (detail: warm_step, lag)
    SPARE_PROMOTE = 13  # promotion (replica side AND lighthouse side)
    # -- degraded mode -------------------------------------------------------
    RELOWER_BEGIN = 14  # device loss: commit fence raised
    RELOWER_COMPLETE = 15  # re-lowered (detail: capacity)
    DEGRADED_SWAP = 16  # lighthouse: wounded replica traded for a spare
    DEGRADED_EVICT = 17  # lighthouse: wounded below the capacity floor
    # -- chaos / coordination ------------------------------------------------
    CHAOS_INJECT = 18  # a fault program / failure class armed (both planes)
    QUORUM_ISSUE = 19  # lighthouse: quorum issued (quorum_id, world)
    # -- data plane (native/comm.h mirrors kFlight* of this block) -----------
    COMM_CONFIGURE = 20  # epoch configured (rank, world, lanes)
    COMM_ABORT = 21  # abort() tore the epoch down
    COMM_POISON = 22  # the epoch latched an error (detail: reason + lane
    # counters of the dying epoch — the stall evidence a postmortem chains)
    LANE_RECONNECT = 23  # one lane re-dialed in-epoch
    LANE_FAILOVER = 24  # one lane failed over to a survivor
    # -- lighthouse policy (python only) -------------------------------------
    EVICT_SLOW = 25  # straggler shed from the quorum
    # -- streamed fragment sync (python only) --------------------------------
    FRAG_SUBMIT = 26  # streamed fragment outer sync submitted (detail: frag)
    FRAG_COMMIT = 27  # streamed fragment delta applied on a committed vote
    FRAG_ABORT = 28  # streamed fragment sync discarded (failed vote / error)


# data-plane events the native tier may record; the ftlint checker requires
# every kFlight* constant in comm.h to name one of these with the same value
NATIVE_EVENT_BLOCK = (
    FlightEvent.COMM_CONFIGURE,
    FlightEvent.COMM_ABORT,
    FlightEvent.COMM_POISON,
    FlightEvent.LANE_RECONNECT,
    FlightEvent.LANE_FAILOVER,
)

# live recorders, for the SIGUSR2 / atexit fleet-wide dump triggers
_RECORDERS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()
_signal_installed = False
_atexit_installed = False
_install_lock = threading.Lock()


def _flight_cap() -> int:
    return max(0, knobs.get_int(FLIGHT_EVENTS_ENV, 4096))


def flight_dir() -> Optional[str]:
    return knobs.get_str(FLIGHT_DIR_ENV) or None


class FlightRecorder:
    """One replica's bounded event ring.

    ``record()`` is the hot path: a tuple append onto a ``deque(maxlen=cap)``
    (GIL-atomic — no lock) plus a monotonic stamp.  Context (``step`` /
    ``quorum_id`` from the manager, ``comm_epoch`` from the communicator)
    is sticky: events recorded without explicit keys inherit the last
    ``set_context`` / ``set_comm_epoch`` values, so data-plane threads need
    no plumbing to stay correlated."""

    def __init__(
        self, replica_id: str = "", cap: Optional[int] = None
    ) -> None:
        self.replica_id = replica_id
        self._cap = _flight_cap() if cap is None else max(0, cap)
        self._events: "collections.deque" = collections.deque(
            maxlen=self._cap or 1
        )
        self._seq = itertools.count()
        # sticky correlation context (single-writer per field in practice;
        # a racy read only mis-stamps one event's context, never corrupts)
        self._step = -1
        self._quorum_id = -1
        self._comm_epoch = -1
        # native-ring sources: weakrefs to objects exposing flight_drain()
        self._native_sources: List["weakref.ref"] = []
        self._last_auto_dump = float("-inf")
        self.dumps_total = 0
        _RECORDERS.add(self)
        _install_triggers()

    # -- recording ----------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._cap > 0

    def __len__(self) -> int:
        return len(self._events) if self._cap else 0

    def __bool__(self) -> bool:
        # an EMPTY recorder is still a recorder: `if self.flight:` guards
        # attachment, not ring occupancy (len() would otherwise leak into
        # truthiness and silently skip the first events)
        return True

    def set_replica_id(self, replica_id: str) -> None:
        self.replica_id = replica_id

    def set_context(
        self, step: Optional[int] = None, quorum_id: Optional[int] = None
    ) -> None:
        if step is not None:
            self._step = step
        if quorum_id is not None:
            self._quorum_id = quorum_id

    def set_comm_epoch(self, epoch: int) -> None:
        self._comm_epoch = epoch

    def record(
        self,
        ev: FlightEvent,
        step: Optional[int] = None,
        quorum_id: Optional[int] = None,
        comm_epoch: Optional[int] = None,
        **detail: Any,
    ) -> None:
        if not self._cap:
            return
        self._events.append(
            (
                next(self._seq),
                time.monotonic(),
                int(ev),
                self._step if step is None else step,
                self._quorum_id if quorum_id is None else quorum_id,
                self._comm_epoch if comm_epoch is None else comm_epoch,
                detail or None,
            )
        )

    def record_raw(self, event: Dict[str, Any]) -> None:
        """Append one pre-built event dict (a drained native slot): stamped
        with its OWN clock/seq fields, stored verbatim."""
        if not self._cap:
            return
        self._events.append(dict(event))

    # -- native ring merge ---------------------------------------------------

    def register_native_source(self, obj: object) -> None:
        """Register an object exposing ``flight_drain() -> List[dict]``
        (the CppCommunicator binding over ``tpuft_comm_flight_drain``).
        Held by weakref; drained into the ring at every dump."""
        self._native_sources.append(weakref.ref(obj))

    def _drain_native(self) -> int:
        drained = 0
        live: List["weakref.ref"] = []
        for ref in self._native_sources:
            obj = ref()
            if obj is None:
                continue
            live.append(ref)
            try:
                events = obj.flight_drain()  # type: ignore[attr-defined]
            except Exception as e:  # noqa: BLE001 — a dead source must not
                # kill the dump that exists to explain the death
                logger.warning("native flight drain failed: %s", e)
                continue
            for event in events:
                event.setdefault("native", True)
                self.record_raw(event)
                drained += 1
        self._native_sources = live
        return drained

    # -- snapshot / dump -----------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        """The ring as a list of event dicts, oldest first.  Non-destructive."""
        out: List[Dict[str, Any]] = []
        for item in list(self._events):
            if isinstance(item, dict):
                out.append(dict(item))
                continue
            seq, t, ev, step, quorum_id, comm_epoch, detail = item
            event: Dict[str, Any] = {
                "seq": seq,
                "t": round(t, 6),
                "ev": ev,
                "name": (
                    FlightEvent(ev).name
                    if ev in FlightEvent._value2member_map_
                    else f"EV_{ev}"
                ),
                "step": step,
                "quorum_id": quorum_id,
                "comm_epoch": comm_epoch,
            }
            if detail:
                event.update(detail)
            out.append(event)
        return out

    def dump(self, reason: str) -> Optional[str]:
        """Write the full current ring (native sources merged) as
        ``flight_{replica_id}.jsonl`` under ``TORCHFT_FLIGHT_DIR``.  Each
        dump REWRITES the file atomically (tmp + rename) — the newest dump
        holds the most complete ring, and a reader never sees a torn file.
        Returns the path, or None when recording/dumping is disabled."""
        if not self._cap:
            return None
        native_events = self._drain_native()
        directory = flight_dir()
        path: Optional[str] = None
        events = self.snapshot()
        if directory:
            os.makedirs(directory, exist_ok=True)
            safe_id = (
                "".join(
                    c if c.isalnum() or c in "-_." else "_"
                    for c in (self.replica_id or "unnamed")
                )
                or "unnamed"
            )
            path = os.path.join(directory, f"flight_{safe_id}.jsonl")
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "w") as f:
                f.write(
                    json.dumps(
                        {
                            "flight_meta": 1,
                            "replica_id": self.replica_id,
                            "reason": reason,
                            "dump_ts": round(time.time(), 3),
                            "dump_t_mono": round(time.monotonic(), 6),
                            "events": len(events),
                        }
                    )
                    + "\n"
                )
                for event in events:
                    event["replica_id"] = self.replica_id
                    f.write(json.dumps(event) + "\n")
            os.replace(tmp, path)
        self.dumps_total += 1
        logging.getLogger("torchft_flight").info(
            "",
            extra={
                "replica_id": self.replica_id,
                "flight_reason": reason,
                "flight_events": len(events),
                "flight_native_events": native_events,
                "flight_path": path or "",
            },
        )
        return path

    def maybe_dump(self, reason: str) -> Optional[str]:
        """Rate-limited automatic dump (the poison / error-funnel triggers):
        a poison storm must not turn into an fsync storm.  Manual triggers
        (SIGUSR2, shutdown) call :meth:`dump` directly."""
        if not self._cap:
            return None
        min_s = knobs.get_float(FLIGHT_DUMP_MIN_S_ENV, 1.0)
        now = time.monotonic()
        if now - self._last_auto_dump < min_s:
            return None
        self._last_auto_dump = now
        try:
            return self.dump(reason)
        except OSError as e:  # a full disk must not fail the train loop
            logger.warning("flight dump failed: %s", e)
            return None


# -- process-wide default recorder + fleet triggers --------------------------

_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()


def default_recorder() -> FlightRecorder:
    """The process-global recorder, for process-plane callers without a
    Manager-owned instance (one replica per process).  Thread-plane
    harnesses attach per-Manager recorders instead."""
    global _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder(
                replica_id=os.environ.get("JOB_ID", "")
                or f"pid_{os.getpid()}"
            )
        return _default


def dump_all(reason: str) -> List[str]:
    """Dump every live recorder (the SIGUSR2 / atexit trigger body)."""
    paths = []
    for rec in list(_RECORDERS):
        try:
            path = rec.dump(reason)
        except OSError as e:
            logger.warning("flight dump failed: %s", e)
            continue
        if path:
            paths.append(path)
    return paths


def _on_sigusr2(signum, frame) -> None:  # pragma: no cover — signal path
    # NEVER dump inline: the handler runs on the main thread between
    # bytecodes, and a dump drains native rings under their communicator
    # locks — if the main thread already holds one (mid-configure, mid-op
    # enqueue), the inline drain would self-deadlock the process the
    # operator was trying to debug.  A daemon thread takes the locks from
    # a context that can actually wait for them.
    threading.Thread(
        target=dump_all, args=("sigusr2",), name="tpuft_flight_sigusr2",
        daemon=True,
    ).start()


def _install_triggers() -> None:
    """Install the SIGUSR2 handler and the atexit hook once per process.
    Signal installation only works on the main thread (and some embedders
    forbid it) — failure downgrades to the remaining triggers."""
    global _signal_installed, _atexit_installed
    with _install_lock:
        if not _atexit_installed:
            _atexit_installed = True
            atexit.register(_atexit_dump)
        if not _signal_installed:
            try:
                signal.signal(signal.SIGUSR2, _on_sigusr2)
                _signal_installed = True
            except (ValueError, OSError, AttributeError):
                # not the main thread / no SIGUSR2 on this platform
                _signal_installed = True  # don't retry per recorder


def _atexit_dump() -> None:  # pragma: no cover — interpreter teardown
    if flight_dir():
        dump_all("atexit")
