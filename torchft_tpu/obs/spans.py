"""Per-step trace spans with Chrome trace-event export.

A span is a named wall-clock window recorded into a process-global bounded
buffer; nested calls on one thread render as a flame because Chrome's
``"X"`` (complete) events nest by ``(tid, ts, dur)`` containment — no
parent bookkeeping needed.  The instrumented protocol tree::

    step
    └─ quorum_rpc            (manager._async_quorum)
       └─ comm_configure     (manager._adopt_quorum)
    └─ comm_op               (communicator op thread, one per collective)
       └─ lane_window        (striped exchange: one per lane part batch)
    └─ outer_shard_chunk     (collectives.outer_sharded_sync pipeline)
    └─ heal_send / heal_recv (checkpoint transfers)

Spans are OFF by default (``TORCHFT_FLIGHT_SPANS=1`` opts in; the bench's
``obs_overhead_frac`` gate measures recorder+spans enabled at <= 1% step
time).  When disabled, :func:`span` returns a shared no-op context manager
— one truthiness check on the hot path.

Export: :func:`export_chrome_trace` writes ``{"traceEvents": [...]}`` JSON
loadable in Perfetto / chrome://tracing; ``scripts/flight_merge.py`` merges
several replicas' span files and flight dumps into one fleet timeline.

The buffer is process-global (thread-plane drills mix their replicas'
spans onto distinct tids, which is exactly what a one-process fleet is);
per-replica separation comes from one process per replica in production.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

import collections

from torchft_tpu import knobs

SPANS_ENV = "TORCHFT_FLIGHT_SPANS"

# None = resolve from env on first use; configure() pins it for the process
_enabled: Optional[bool] = None
_spans: "collections.deque" = collections.deque(maxlen=8192)
_lock = threading.Lock()


def spans_enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = knobs.get_bool(SPANS_ENV, False)
    return _enabled


def configure(enabled: Optional[bool], cap: Optional[int] = None) -> None:
    """Pin span collection on/off for the process (``None`` re-reads the
    env on next use).  ``cap`` resizes the buffer (drops collected spans)."""
    global _enabled, _spans
    _enabled = enabled
    if cap is not None:
        with _lock:
            _spans = collections.deque(maxlen=max(1, cap))


def clear() -> None:
    with _lock:
        _spans.clear()


class _NullSpan:
    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: object) -> None:
        return None


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0")

    def __init__(self, name: str, attrs: Optional[Dict[str, Any]]) -> None:
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0

    def __enter__(self) -> "_Span":
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc: object) -> None:
        t1 = time.monotonic()
        _spans.append(  # deque append: GIL-atomic, no lock on the hot path
            (self.name, self.t0, t1 - self.t0, threading.get_ident(), self.attrs)
        )


def span(name: str, **attrs: Any):
    """Context manager recording one named wall-clock window.  Free (a
    shared no-op object) when spans are disabled."""
    if not spans_enabled():
        return _NULL
    return _Span(name, attrs or None)


def snapshot() -> List[Dict[str, Any]]:
    """Collected spans as dicts, oldest first (non-destructive)."""
    out = []
    for name, t0, dur, tid, attrs in list(_spans):
        rec: Dict[str, Any] = {
            "name": name,
            "t": round(t0, 6),
            "dur": round(dur, 6),
            "tid": tid,
        }
        if attrs:
            rec["attrs"] = attrs
        out.append(rec)
    return out


def export_chrome_trace(path: str, replica_id: str = "") -> int:
    """Write the collected spans as Chrome trace-event JSON (``"X"``
    complete events, microsecond units) at ``path``.  Returns the span
    count.  The file is Perfetto-loadable standalone; the fleet view comes
    from ``scripts/flight_merge.py``."""
    events: List[Dict[str, Any]] = []
    pid = abs(hash(replica_id)) % 100000 if replica_id else 1
    if replica_id:
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": replica_id},
            }
        )
    spans = snapshot()
    for rec in spans:
        event = {
            "name": rec["name"],
            "ph": "X",
            "ts": round(rec["t"] * 1e6, 1),
            "dur": round(rec["dur"] * 1e6, 1),
            "pid": pid,
            "tid": rec["tid"],
        }
        if "attrs" in rec:
            event["args"] = rec["attrs"]
        events.append(event)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(spans)
