"""Unified observability plane: flight recorder, trace spans, /metrics.

Three pillars riding one event substrate (see ``docs/operations.md`` §17):

- :mod:`.flight` — a lock-cheap per-replica ring of typed, monotonic-
  stamped events keyed by ``(step, quorum_id, comm_epoch)``, dumped on
  comm-epoch poison, the Manager error funnel, SIGUSR2, and atexit; the
  native tier's C-side ring merges in via ``tpuft_comm_flight_drain``.
- :mod:`.spans` — context-manager trace spans nested under the step,
  exported as Chrome trace-event JSON; ``scripts/flight_merge.py`` aligns
  multiple replicas into one Perfetto-loadable fleet timeline.
- :mod:`.metrics` — the central metric-name registry behind the
  Prometheus-text ``/metrics`` endpoints on the lighthouse (TTL-cached
  snapshot, zero new lock traffic) and every ManagerServer.
"""

from torchft_tpu.obs.flight import (  # noqa: F401
    FlightEvent,
    FlightRecorder,
    default_recorder,
    dump_all,
    flight_dir,
)
from torchft_tpu.obs.metrics import (  # noqa: F401
    REGISTRY as METRICS_REGISTRY,
    metric_sample,
    parse_prometheus_text,
    render as render_metrics,
)
from torchft_tpu.obs.spans import (  # noqa: F401
    export_chrome_trace,
    span,
    spans_enabled,
)
