"""Fault-tolerant data parallelism over the replica dimension.

The reference hooks torch DDP's bucket reducer into ``manager.allreduce``
(``torchft/ddp.py:31-78``).  JAX has no module/buckets: gradients are a
pytree produced by ``jax.grad`` inside a compiled step.  The replica-dim
average runs host-side — leaves are fetched to host, flattened into one
contiguous buffer per dtype (the bucketization DDP gets from its reducer),
ring-allreduced over DCN/TCP, and pushed back to device with the original
shardings.  Compiled programs never see the replica count (SURVEY.md §7).
"""

from __future__ import annotations

import functools
import os
import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchft_tpu.checkpointing.serialization import (
    ShardedHostArray,
    shard_key as _shard_key,
)
from torchft_tpu.manager import Manager
from torchft_tpu.work import DummyWork, Work

# Split gradient buckets at this size (reference: TORCHFT_USE_BUCKETIZATION /
# bucket_cap_mb, ``local_sgd.py:28``); pipelines D2H transfer with the rings.
# MUST be uniform across replicas: bucket boundaries shape the collective
# sequence (mismatches fail fast via the ring's frame-size validation, like
# the reference's frozen DDP bucket layout requirement, ``ddp.py:46-62``).
# The env is read per call with the parse memoized on the raw string: the
# same raw value always yields the same cap (uniform within a process AND
# across replicas that agree on the env), while tests can flip the env to
# exercise bucket boundaries without re-importing the module.  Malformed
# values fall back to the default rather than raising into the train loop.
BUCKET_CAP_MB_ENV = "TORCHFT_BUCKET_CAP_MB"
DEFAULT_BUCKET_CAP_MB = 32


@functools.lru_cache(maxsize=None)
def _parse_bucket_cap(raw: str) -> int:
    try:
        mb = float(raw) if raw else float(DEFAULT_BUCKET_CAP_MB)
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "invalid %s=%r; using %d MB", BUCKET_CAP_MB_ENV, raw, DEFAULT_BUCKET_CAP_MB
        )
        mb = float(DEFAULT_BUCKET_CAP_MB)
    return max(1, int(mb * (1 << 20)))


def _bucket_cap_bytes() -> int:
    return _parse_bucket_cap(os.environ.get(BUCKET_CAP_MB_ENV, ""))


def allreduce_pytree_result(tree: Any) -> Work:
    return DummyWork(tree)


def _unique_local_shards(leaf: Any) -> Dict[Tuple, Any]:
    """This host's addressable shards deduped by canonical global index
    (replicated shards — same index on several local devices — appear once),
    in deterministic key order shared by this host's twin in every replica
    group."""
    unique: Dict[Tuple, Any] = {}
    for s in leaf.addressable_shards:
        unique.setdefault(_shard_key(s.index, leaf.shape), s)
    return dict(sorted(unique.items()))


def _assemble_sharded(
    shape: Tuple[int, ...],
    sharding: Any,
    dtype: Any,
    addressable_shards: Any,
    lookup,
) -> Any:
    """Rebuild a (possibly non-fully-addressable) jax Array from host data:
    ``lookup(shard_key, shard)`` returns the numpy block for that shard.  The
    global array is never materialized on one host."""
    per_device = []
    for s in addressable_shards:
        buf = np.asarray(lookup(_shard_key(s.index, shape), s)).astype(
            dtype, copy=False
        )
        per_device.append(jax.device_put(buf, s.device))
    return jax.make_array_from_single_device_arrays(shape, sharding, per_device)


def _host_contribution(leaf: Any) -> Tuple[np.ndarray, Any]:
    """This host's flat (1-D) contribution to the replica-dim average, plus
    a ``restore(avg_flat) -> leaf`` function.

    Fully-addressable leaves ship whole.  For multi-host arrays (a replica
    group spanning hosts, the v5p reality) each host ships only its UNIQUE
    addressable shards: host h of every replica group addresses the same
    logical region (identical mesh + shardings across groups), so
    shard-local averaging over the per-``group_rank`` DCN ring is exact —
    same math, sharded bytes.  Restore rebuilds the global array from
    per-device buffers without ever materializing it unsharded.
    """
    if not isinstance(leaf, jax.Array) or leaf.is_fully_addressable:
        arr = np.asarray(leaf)
        shape, is_jax = arr.shape, isinstance(leaf, jax.Array)
        sharding = leaf.sharding if is_jax else None

        def _restore_full(avg_flat: np.ndarray) -> Any:
            host_val = avg_flat.reshape(shape)
            if is_jax:
                return jax.device_put(host_val, sharding)
            return host_val

        return arr.reshape(-1), _restore_full

    shards = list(leaf.addressable_shards)
    unique = _unique_local_shards(leaf)
    segments: List[np.ndarray] = []
    offsets: Dict[Tuple, Tuple[int, int, tuple]] = {}
    off = 0
    for k, s in unique.items():
        data = np.asarray(s.data)
        offsets[k] = (off, data.size, data.shape)
        segments.append(data.reshape(-1))
        off += data.size
    flat = np.concatenate(segments) if segments else np.empty(0, leaf.dtype)
    shape, sharding, dtype = leaf.shape, leaf.sharding, leaf.dtype

    def _restore_sharded(avg_flat: np.ndarray) -> Any:
        def _lookup(key: Tuple, _s: Any) -> np.ndarray:
            o, n, shp = offsets[key]
            return avg_flat[o : o + n].reshape(shp)

        return _assemble_sharded(shape, sharding, dtype, shards, _lookup)

    return flat, _restore_sharded


def allreduce_pytree(
    manager: Manager,
    tree: Any,
    should_quantize: bool = False,
    stream: Optional[int] = None,
) -> Work:
    """Average a pytree of gradients across participating replicas.

    Returns a Work whose value is the averaged pytree with original leaf
    types restored (jax leaves come back as device arrays with their
    original sharding).  Error swallowing and participation zeroing happen
    inside ``manager.allreduce``.

    ``stream``, when given, marks this as an ASYNC streamed fragment submit
    (the TORCHFT_STREAM_SYNC LocalSGD scheduler): exactly one work — the
    composite covering every bucket ring AND the restore — registers in the
    Manager's stream-fence registry instead of ``_pending_works``, same
    contract as ``Manager.outer_shard_allreduce(stream=)``; the per-bucket
    works are owned by the composite and register nowhere.  Not supported
    on the device-quantized path (no streamed caller quantizes here — the
    quantized streamed wire is DiLoCo's, via ``Manager.allreduce(stream=)``).
    """

    def _streamed(w: Work) -> Work:
        return w if stream is None else manager.stream_submitted(stream, w)

    if manager.errored():
        return _streamed(allreduce_pytree_result(tree))
    if manager.allreduce_is_identity():
        # single-member quorum: averaging is the identity; skip the
        # device→host→device round trip entirely
        return _streamed(allreduce_pytree_result(tree))

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return _streamed(allreduce_pytree_result(tree))

    if stream is None and should_quantize and all(
        isinstance(l, jax.Array) and l.is_fully_addressable for l in leaves
    ):
        # (multi-host arrays fall through to the bucketed path, which ships
        # shard-local contributions; int8 wire quantization still applies
        # via manager.allreduce(should_quantize=True))
        # Quantize ON DEVICE (Pallas on TPU): only int8 payload + rowwise
        # scales cross HBM→host→DCN — ~4x fewer bytes than shipping floats
        # and quantizing host-side.
        return _allreduce_pytree_device_quantized(manager, leaves, treedef)

    original = list(leaves)

    # Kick off every device→host transfer asynchronously up front so DMA
    # overlaps the bucket assembly and the first ring.
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            try:
                leaf.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass

    # Bucket by dtype (each dtype needs its own ring), then split large
    # buckets at ``bucket_cap`` bytes and submit each as its own collective:
    # the op thread rings bucket k while we fetch/assemble bucket k+1 —
    # transfer/communication pipelining, the reference's bucket_cap_mb
    # (``local_sgd.py:28,477-566``) in jax form.
    bucket_cap = _bucket_cap_bytes()
    order: Dict[str, List[int]] = {}
    leaf_bytes: List[int] = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            # bucket by what actually crosses the wire: this host's unique
            # shard bytes (identical on twin hosts, so bucket boundaries —
            # and therefore ring frame sizes — stay uniform)
            dtype_name = leaf.dtype.name
            nbytes = sum(
                int(s.data.nbytes) for s in _unique_local_shards(leaf).values()
            )
        elif hasattr(leaf, "dtype") and hasattr(leaf, "nbytes"):
            dtype_name, nbytes = leaf.dtype.name, int(leaf.nbytes)
        else:
            arr = np.asarray(leaf)
            dtype_name, nbytes = arr.dtype.name, int(arr.nbytes)
        leaf_bytes.append(nbytes)
        order.setdefault(dtype_name, []).append(i)

    works: List[Work] = []
    bucket_layouts: List[List[Tuple[int, int, int, tuple]]] = []
    for _dtype_name, idxs in order.items():
        group: List[int] = []
        group_bytes = 0
        groups: List[List[int]] = []
        for i in idxs:
            if group and group_bytes + leaf_bytes[i] > bucket_cap:
                groups.append(group)
                group, group_bytes = [], 0
            group.append(i)
            group_bytes += leaf_bytes[i]
        if group:
            groups.append(group)

        for group in groups:
            # waits async copies; sharded leaves contribute local shards only
            contribs = [_host_contribution(leaves[i]) for i in group]
            total = sum(c[0].size for c in contribs)
            flat = np.empty(total, dtype=contribs[0][0].dtype)
            layout = []
            off = 0
            for i, (arr, restore) in zip(group, contribs):
                n = arr.size
                flat[off : off + n] = arr
                layout.append((i, off, n, restore))
                off += n
            # submit immediately: this bucket's ring overlaps the next
            # bucket's fetch/assembly; in_place — the bucket is ours and
            # discarded after the restore, so the ring reduces straight into
            # it (no defensive copy; on this host class that copy costs as
            # much as half the ring itself)
            works.append(
                manager.allreduce(
                    flat,
                    should_quantize=should_quantize,
                    in_place=True,
                    register_pending=stream is None,
                )
            )
            bucket_layouts.append(layout)

    def _gather() -> Any:
        out = list(original)
        for work, layout in zip(works, bucket_layouts):
            flat = work.wait()
            for i, off, n, restore in layout:
                out[i] = restore(flat[off : off + n])
        return jax.tree_util.tree_unflatten(treedef, out)

    fut: "Future[Any]" = Future()

    def _finish() -> None:
        try:
            fut.set_result(_gather())
        except Exception as e:  # noqa: BLE001 — funnel, never raise
            manager.report_error(e)
            fut.set_result(jax.tree_util.tree_unflatten(treedef, original))

    threading.Thread(
        target=_finish, name="tpuft_ddp_gather", daemon=True
    ).start()
    out = Work(fut)
    # fence the WHOLE pipeline (including restore/device_put) at commit, not
    # just the wire collectives — a restore failure after the vote would
    # otherwise apply unaveraged gradients on this replica only.  Streamed
    # submits register the same composite in the stream-fence registry
    # instead, where the vote REFUSES (rather than waits) while it's in
    # flight.
    if stream is None:
        manager._register_pending(out)
    else:
        manager.stream_submitted(stream, out)
    return out


@jax.jit
def _flatten_f32(leaves: Any) -> jax.Array:
    return jnp.concatenate([l.astype(jnp.float32).reshape(-1) for l in leaves])


def _allreduce_pytree_device_quantized(
    manager: Manager, leaves: list, treedef: Any
) -> Work:
    """Device quantize → Manager-orchestrated wire pipeline → device put.

    The fault-tolerance orchestration (quorum wait, participation zeroing,
    normalization, error funnel) lives in ``Manager.allreduce_prequantized``
    — this function only handles device-side quantization and pytree
    reassembly.  Returns a pending Work (the wire pipeline runs off-thread).
    """
    from torchft_tpu.ops.pallas_quant import quantize_rowwise_device
    from torchft_tpu.quantization import quant_kind

    try:
        flat = _flatten_f32(leaves)
        # wire kind (int8 / fp8) from TORCHFT_QUANT_KIND; everything
        # downstream — the pipelined ring, the reduce kernels, the
        # dequantize — dispatches on the payload dtype
        q, scales = quantize_rowwise_device(flat, kind=quant_kind())
        # the only HBM→host bytes: 1-byte payload + f32 rowwise scales
        q_np, s_np = np.asarray(q), np.asarray(scales)
        work = manager.allreduce_prequantized(q_np, s_np, int(flat.shape[0]))
    except Exception as e:  # noqa: BLE001 — errors never reach the train loop
        manager.report_error(e)
        return DummyWork(jax.tree_util.tree_unflatten(treedef, leaves))

    def _reassemble(avg: np.ndarray) -> Any:
        out = []
        off = 0
        for leaf in leaves:
            n = leaf.size
            host_val = avg[off : off + n].reshape(leaf.shape)
            out.append(jax.device_put(host_val.astype(leaf.dtype), leaf.sharding))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    out = manager.wrap_work(
        work.then(_reassemble), jax.tree_util.tree_unflatten(treedef, leaves)
    )
    manager._register_pending(out)  # fence reassembly at commit too
    return out


def ft_allreduce(manager: Manager, tree: Any, should_quantize: bool = False) -> Any:
    """Synchronous convenience: averaged pytree, or the input unchanged if
    this step already errored (the vote will discard it)."""
    return allreduce_pytree(manager, tree, should_quantize).wait()


class DistributedDataParallel:
    """Object-style facade matching the reference class name
    (``torchft/ddp.py:31-78``): holds the manager and averages gradient
    pytrees produced by a compiled step."""

    def __init__(self, manager: Manager) -> None:
        self.manager = manager

    def average_gradients(self, grads: Any, should_quantize: bool = False) -> Any:
        return ft_allreduce(self.manager, grads, should_quantize)

    def average_gradients_async(self, grads: Any, should_quantize: bool = False) -> Work:
        return allreduce_pytree(self.manager, grads, should_quantize)


def restore_like(new: Any, old: Any) -> Any:
    """Place one healed host-side leaf back on device in ``old``'s layout.

    ``new`` is what the checkpoint transport delivered: a numpy array, or a
    :class:`ShardedHostArray` when the sender was a multi-host replica group
    (its host shipped only its addressable shards — which are exactly the
    shards THIS host addresses, since mesh + shardings are identical across
    replica groups).
    """
    if isinstance(new, ShardedHostArray):
        assert isinstance(old, jax.Array), "sharded leaf healed into non-jax leaf"
        return _assemble_sharded(
            old.shape,
            old.sharding,
            old.dtype,
            old.addressable_shards,
            lambda key, _s: new.shards[key],
        )
    if isinstance(old, jax.Array):
        return jax.device_put(np.asarray(new), old.sharding)
    return new


def restore_tree_like(new_tree: Any, old_tree: Any) -> Any:
    """``restore_like`` over a pytree (``ShardedHostArray`` leaves kept
    atomic)."""
    return jax.tree_util.tree_map(
        restore_like,
        new_tree,
        old_tree,
        is_leaf=lambda x: isinstance(x, ShardedHostArray),
    )
