"""Fault-tolerant data parallelism over the replica dimension.

The reference hooks torch DDP's bucket reducer into ``manager.allreduce``
(``torchft/ddp.py:31-78``).  JAX has no module/buckets: gradients are a
pytree produced by ``jax.grad`` inside a compiled step.  The replica-dim
average runs host-side — leaves are fetched to host, flattened into one
contiguous buffer per dtype (the bucketization DDP gets from its reducer),
ring-allreduced over DCN/TCP, and pushed back to device with the original
shardings.  Compiled programs never see the replica count (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchft_tpu.manager import Manager
from torchft_tpu.work import DummyWork, Work


def allreduce_pytree_result(tree: Any) -> Work:
    return DummyWork(tree)


def _to_host(leaf: Any) -> np.ndarray:
    # np.asarray on a jax.Array device_gets; numpy passes through
    return np.asarray(leaf)


def allreduce_pytree(manager: Manager, tree: Any, should_quantize: bool = False) -> Work:
    """Average a pytree of gradients across participating replicas.

    Returns a Work whose value is the averaged pytree with original leaf
    types restored (jax leaves come back as device arrays with their
    original sharding).  Error swallowing and participation zeroing happen
    inside ``manager.allreduce``.
    """
    if manager.errored():
        return allreduce_pytree_result(tree)
    if manager.allreduce_is_identity():
        # single-member quorum: averaging is the identity; skip the
        # device→host→device round trip entirely
        return allreduce_pytree_result(tree)

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return allreduce_pytree_result(tree)

    if should_quantize and all(isinstance(l, jax.Array) for l in leaves):
        # Quantize ON DEVICE (Pallas on TPU): only int8 payload + rowwise
        # scales cross HBM→host→DCN — ~4x fewer bytes than shipping floats
        # and quantizing host-side.
        return _allreduce_pytree_device_quantized(manager, leaves, treedef)

    original = list(leaves)

    # bucket by dtype so each dtype rides one ring (DDP-style flat buckets)
    host: List[np.ndarray] = [_to_host(leaf) for leaf in leaves]
    order: Dict[str, List[int]] = {}
    for i, arr in enumerate(host):
        order.setdefault(arr.dtype.name, []).append(i)

    buckets: List[np.ndarray] = []
    bucket_layout: List[List[Tuple[int, int, int, tuple]]] = []
    for dtype_name, idxs in order.items():
        total = sum(host[i].size for i in idxs)
        flat = np.empty(total, dtype=host[idxs[0]].dtype)
        layout = []
        off = 0
        for i in idxs:
            n = host[i].size
            flat[off : off + n] = host[i].reshape(-1)
            layout.append((i, off, n, host[i].shape))
            off += n
        buckets.append(flat)
        bucket_layout.append(layout)

    work = manager.allreduce(buckets, should_quantize=should_quantize)

    def _unbucket(reduced: Any) -> Any:
        arrays: List[np.ndarray] = (
            reduced if isinstance(reduced, list) else [reduced]
        )
        out = list(original)
        for flat, layout in zip(arrays, bucket_layout):
            for i, off, n, shape in layout:
                host_val = flat[off : off + n].reshape(shape)
                leaf = original[i]
                if isinstance(leaf, jax.Array):
                    out[i] = jax.device_put(
                        host_val,
                        leaf.sharding if hasattr(leaf, "sharding") else None,
                    )
                else:
                    out[i] = host_val
        return jax.tree_util.tree_unflatten(treedef, out)

    return work.then(_unbucket)


@jax.jit
def _flatten_f32(leaves: Any) -> jax.Array:
    return jnp.concatenate([l.astype(jnp.float32).reshape(-1) for l in leaves])


def _allreduce_pytree_device_quantized(
    manager: Manager, leaves: list, treedef: Any
) -> Work:
    """Device quantize → Manager-orchestrated wire pipeline → device put.

    The fault-tolerance orchestration (quorum wait, participation zeroing,
    normalization, error funnel) lives in ``Manager.allreduce_prequantized``
    — this function only handles device-side quantization and pytree
    reassembly.  Returns a pending Work (the wire pipeline runs off-thread).
    """
    from torchft_tpu.ops.pallas_quant import quantize_int8_rowwise_device

    try:
        flat = _flatten_f32(leaves)
        q, scales = quantize_int8_rowwise_device(flat)
        # the only HBM→host bytes: int8 payload + f32 rowwise scales
        q_np, s_np = np.asarray(q), np.asarray(scales)
        work = manager.allreduce_prequantized(q_np, s_np, int(flat.shape[0]))
    except Exception as e:  # noqa: BLE001 — errors never reach the train loop
        manager.report_error(e)
        return DummyWork(jax.tree_util.tree_unflatten(treedef, leaves))

    def _reassemble(avg: np.ndarray) -> Any:
        out = []
        off = 0
        for leaf in leaves:
            n = leaf.size
            host_val = avg[off : off + n].reshape(leaf.shape)
            out.append(jax.device_put(host_val.astype(leaf.dtype), leaf.sharding))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    return manager.wrap_work(
        work.then(_reassemble), jax.tree_util.tree_unflatten(treedef, leaves)
    )


def ft_allreduce(manager: Manager, tree: Any, should_quantize: bool = False) -> Any:
    """Synchronous convenience: averaged pytree, or the input unchanged if
    this step already errored (the vote will discard it)."""
    return allreduce_pytree(manager, tree, should_quantize).wait()


class DistributedDataParallel:
    """Object-style facade matching the reference class name
    (``torchft/ddp.py:31-78``): holds the manager and averages gradient
    pytrees produced by a compiled step."""

    def __init__(self, manager: Manager) -> None:
        self.manager = manager

    def average_gradients(self, grads: Any, should_quantize: bool = False) -> Any:
        return ft_allreduce(self.manager, grads, should_quantize)

    def average_gradients_async(self, grads: Any, should_quantize: bool = False) -> Work:
        return allreduce_pytree(self.manager, grads, should_quantize)
