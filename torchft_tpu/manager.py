"""Manager: the per-replica fault-tolerance state machine.

Behavioral twin of the reference Manager (``torchft/manager.py``), driving
the per-step protocol from an otherwise ordinary train loop:

- ``start_quorum()`` — compute a quorum (usually asynchronously, overlapped
  with the forward pass), reconfigure the communicator when membership
  changed, send live weights to recovering peers, and stage a healing
  checkpoint when this replica is behind (``manager.py:560-813``).
- ``allreduce()`` — average gradients across participating replicas with
  error swallowing and zero-contribution for non-participants
  (``manager.py:410-493``).
- ``should_commit()`` — fence recovery and collectives, pick up async
  errors, vote; commit advances the step, failure discards it
  (``manager.py:855-943``).

TPU-first notes: gradients arrive as numpy views of (shards of) jax arrays
— the replica dimension runs host-side over DCN so the compiled XLA step
never sees the replica count; the gradient divisor ``num_participants()`` is
a runtime scalar.  There are no user streams: XLA dispatch is async on its
own, so the reference's stream/event choreography collapses to thread joins
(the ``_quorum_future``) and a plain recovery event.
"""

from __future__ import annotations

import concurrent.futures
import logging
import os
import socket
import threading
import time
import uuid
from enum import Enum
from typing import Callable, Dict, List, Optional, Tuple, TypeVar, Union, cast

import numpy as np

from torchft_tpu import knobs
from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.obs.flight import FlightEvent, FlightRecorder, flight_dir
from torchft_tpu.obs.spans import span as obs_span
from torchft_tpu.observability import QuorumTracer, traced
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.communicator import Communicator, ReduceOp
from torchft_tpu.manager_server import ManagerClient, ManagerServer
from torchft_tpu.store import StoreClient, StoreServer
from torchft_tpu.work import DummyWork, Event, Work

logger = logging.getLogger(__name__)

T = TypeVar("T")

MANAGER_ADDR_KEY = "manager_addr"
REPLICA_ID_KEY = "replica_id"

# Env knobs (same names as the reference, ``manager.py:74-109``)
MANAGER_PORT_ENV = "TORCHFT_MANAGER_PORT"
LIGHTHOUSE_ENV = "TORCHFT_LIGHTHOUSE"
TIMEOUT_SEC_ENV = "TORCHFT_TIMEOUT_SEC"
QUORUM_TIMEOUT_SEC_ENV = "TORCHFT_QUORUM_TIMEOUT_SEC"
CONNECT_TIMEOUT_SEC_ENV = "TORCHFT_CONNECT_TIMEOUT_SEC"
QUORUM_RETRIES_ENV = "TORCHFT_QUORUM_RETRIES"
# Striped healing: fetch the recovery checkpoint as disjoint chunk ranges
# from EVERY up-to-date peer instead of one round-robin source (on by
# default; "0" pins the legacy single-peer heal).  See also
# TORCHFT_HEAL_CHUNK_MB (serialization), TORCHFT_HEAL_MAX_SOURCES
# (manager_server) and TORCHFT_HEAL_SOURCE_TIMEOUT_S (http_transport).
HEAL_STRIPED_ENV = "TORCHFT_HEAL_STRIPED"
# Hot spares: minimum seconds between warm-snapshot restagings on an
# active replica that has registered spares (each restage host-copies the
# state dict once; spares pull chunk ranges from whatever is staged).
SPARE_WARM_REFRESH_S_ENV = "TORCHFT_SPARE_WARM_REFRESH_S"


def _heal_striped_enabled() -> bool:
    return knobs.get_bool(HEAL_STRIPED_ENV, True)


def _env_timeout(env: str, default_s: float) -> float:
    return knobs.get_float(env, default_s)


def extract_trailing_digits(s: str) -> int:
    """Trailing integer of a replica-group name (``manager.py:112-121``),
    used to map replica ids like ``train_ddp_7`` to global rank math."""
    i = len(s) - 1
    while i >= 0 and s[i].isdigit():
        i -= 1
    return int(s[i + 1 :]) if i < len(s) - 1 else 0


class WorldSizeMode(Enum):
    """Numerics when more than ``min_replica_size`` replicas are healthy
    (``manager.py:123-139``): DYNAMIC grows the divisor with membership;
    FIXED_WITH_SPARES keeps exactly ``min_replica_size`` participants and
    spares contribute zero gradients."""

    DYNAMIC = 0
    FIXED_WITH_SPARES = 1


class ExceptionWithTraceback(Exception):
    def __init__(self, e: Exception) -> None:
        import traceback

        self.original_exception = e
        self.stack_trace: str = traceback.format_exc()
        super().__init__(f"{e}\n{self.stack_trace}")


class Manager:
    """Fault-tolerant training loop manager (``torchft/manager.py:148+``)."""

    def __init__(
        self,
        comm: Optional[Communicator] = None,
        load_state_dict: Optional[Callable[[T], None]] = None,
        state_dict: Optional[Callable[[], T]] = None,
        min_replica_size: int = 1,
        use_async_quorum: bool = True,
        timeout: float = 60.0,
        quorum_timeout: float = 60.0,
        connect_timeout: float = 60.0,
        rank: Optional[int] = None,
        world_size: Optional[int] = None,
        world_size_mode: WorldSizeMode = WorldSizeMode.DYNAMIC,
        store_addr: Optional[str] = None,
        store_port: Optional[int] = None,
        lighthouse_addr: Optional[str] = None,
        replica_id: Optional[str] = None,
        port: Optional[int] = None,
        hostname: Optional[str] = None,
        heartbeat_interval: float = 0.1,
        checkpoint_transport: Optional[CheckpointTransport] = None,
        init_sync: bool = True,
        max_retries: Optional[int] = None,
        quorum_retries: int = 0,
        _manager_client: Optional[ManagerClient] = None,
        _peer_client_factory: Optional[Callable[[str], ManagerClient]] = None,
        server_cls: Optional[type] = None,
        role: str = "active",
    ) -> None:
        from torchft_tpu.observability import init_structured_logging

        init_structured_logging()  # no-op unless TORCHFT_USE_OTEL/LOG_DIR set
        self.quorum_logger = logging.getLogger("torchft_quorums")
        self.commits_logger = logging.getLogger("torchft_commits")
        self.errors_logger = logging.getLogger("torchft_errors")
        # per-replica flight recorder (obs/flight.py): the manager state
        # machine, the communicator's epoch lifecycle, and the heal path
        # all record into this ring; the replica id is stamped once known
        self._flight = FlightRecorder(replica_id=replica_id or "")

        self._load_state_dict_fns: Dict[str, Callable[[object], None]] = {}
        self._user_state_dicts: Dict[str, Callable[[], object]] = {}
        if load_state_dict and state_dict:
            self.register_state_dict_fn("default", load_state_dict, state_dict)

        self._timeout = _env_timeout(TIMEOUT_SEC_ENV, timeout)
        if comm is None:
            # tier-dispatched default: the native (cpp) mesh whenever the
            # library loads and the topology permits, else the Python tier
            # — so the train loop, DiLoCo outer sync, and heal drain all
            # ride the production data plane without every caller wiring
            # tier.make_communicator themselves
            from torchft_tpu import tier as tier_mod

            comm = tier_mod.make_communicator(timeout_s=self._timeout)
        self._comm = comm
        # attach the recorder to the data plane: epoch configure/abort/
        # poison and lane recovery record into the same per-replica ring
        # (a plain attribute — every tier's communicator carries it)
        self._comm.flight = self._flight
        self._min_replica_size = min_replica_size
        self._use_async_quorum = use_async_quorum
        self._init_sync = init_sync
        self._max_retries = max_retries
        self._replica_world_size_mode = world_size_mode

        self._quorum_timeout = _env_timeout(QUORUM_TIMEOUT_SEC_ENV, quorum_timeout)
        self._connect_timeout = _env_timeout(CONNECT_TIMEOUT_SEC_ENV, connect_timeout)
        quorum_retries = knobs.get_int(QUORUM_RETRIES_ENV, quorum_retries)
        # fail fast on a bad TORCHFT_QUANT_KIND: inside the step it would
        # land in the error funnel and silently discard every step
        from torchft_tpu.quantization import quant_kind

        quant_kind()

        self._group_rank: int = rank if rank is not None else int(os.environ.get("RANK", 0))
        self._group_world_size: int = (
            world_size
            if world_size is not None
            else int(os.environ.get("WORLD_SIZE", 1))
        )
        hostname = hostname or socket.gethostname()

        # state dict guard: reads (checkpoint serving) vs writes (train loop)
        self._state_dict_lock = RWLock(timeout=self._timeout)
        # per-quorum profiler epochs (TORCHFT_TRACE_DIR; flight-recorder analog)
        self._tracer = QuorumTracer()

        self._pending_state_dict: Optional[Dict[str, object]] = None
        self._healing = False
        self._errored: Optional[ExceptionWithTraceback] = None
        self._recovery_event: Optional[Event] = None

        # outstanding Works issued this step via allreduce/
        # allreduce_prequantized; fenced at should_commit (the analog of the
        # reference's accelerator-stream synchronize, ``manager.py:888-893``)
        self._pending_works: List[Work] = []
        self._pending_works_lock = threading.Lock()
        # streamed fragment syncs (TORCHFT_STREAM_SYNC): per-fragment Works
        # submitted out-of-band of _pending_works so a round's vote never
        # silently fences them — should_commit instead REFUSES (votes
        # False) while any streamed sync is unresolved, the PR-11
        # begin_relower fence pattern, so a half-streamed sync can never
        # commit.  The scheduler resolves (waits) a fragment's work before
        # its barrier vote, making the fence a no-op on the healthy path.
        # frag -> (work, submit-time step): the step keys the
        # FRAG_SUBMIT/FRAG_COMMIT pair on the flight timeline
        self._stream_pending: Dict[int, Tuple[Work, int]] = {}

        self._step = 0
        self._batches_committed = 0
        self._commit_failures = 0
        self._quorum_id = -1
        # job-lifetime comm-health counters: completed epochs fold in at
        # each quorum change, live-epoch values ride on top — heartbeats
        # carry the (monotonic) sum to the lighthouse for straggler
        # detection
        self._comm_health_base: Dict[str, int] = {
            "stalls": 0,
            "reconnects": 0,
            "failovers": 0,
            "faults": 0,
            "tx_bytes": 0,
            "rx_bytes": 0,
        }
        # True between "outgoing epoch folded into base" and "mesh
        # reconfigured (live counters reset)": heartbeats landing in that
        # window must report base-only, or the outgoing epoch would count
        # twice and spike the lighthouse's stall-rate EWMA
        self._comm_health_folding = False
        self._quorum_future: Optional[concurrent.futures.Future] = None
        # phase wall-times of the most recent quorum round (see _async_quorum)
        self.last_quorum_timings: Dict[str, float] = {}
        # hot spares: this replica's quorum role ("active" | "spare" — a
        # spare drives spare.SpareAgent instead of the train loop and flips
        # to active at promotion), the spare ids the last quorum advertised
        # (gates warm staging / delta publishing on the active side), and
        # the warm snapshot staged for spare chunk fetches
        if role not in ("active", "spare"):
            raise ValueError(f"role must be 'active' or 'spare', got {role!r}")
        if role == "spare":
            from torchft_tpu.wire import (
                WIRE_COMPAT_ENV,
                manager_quorum_wire_version,
            )

            if manager_quorum_wire_version() < 3:
                # refusing beats silently degrading: without the v3 role
                # tail the lighthouse would register this "spare" as a
                # full ACTIVE — counting toward min_replicas/majority and
                # training on a cold shadow at the first quorum
                raise ValueError(
                    "role='spare' requires quorum wire v3; unset (or raise) "
                    f"{WIRE_COMPAT_ENV} on this replica"
                )
        self._role = role
        # degraded mode (wire v5): the surviving-device fraction this
        # replica re-lowered onto (1.0 = full width), advertised on every
        # quorum registration and — while degraded — on heartbeats;
        # _relower_pending fences the commit vote between begin_relower()
        # and complete_relower() so a half-relowered replica never votes
        # commit; _participant_capacities is the whole quorum's capacity
        # vector (aligned with sorted replica ids) driving the data-shard
        # rescale and the weighted outer reduce
        self._capacity = 1.0
        self._relower_pending = False
        self._participant_capacities: List[float] = []
        self._spare_replica_ids: List[str] = []
        self._warm_staged: Optional[tuple] = None
        self._warm_staged_ts = 0.0
        # set by SpareAgent at promotion: the next start_quorum is a no-op
        # because the promotion quorum was already adopted
        self._adopted_quorum = False
        # delta-tap staging: the sharded outer sync taps its assembled
        # delta here; published to the spare feed only on a committed vote
        self._staged_outer_delta: Optional[bytes] = None
        # pipeline timings of the most recent sharded outer sync; ride the
        # next quorum-change event into torchft_quorums (outer_shard_*)
        self._outer_shard_stats: Dict[str, float] = {}
        self._participating_replica_rank: Optional[int] = None
        self._participating_replica_world_size: int = 0

        # one worker: quorum computation overlaps the forward pass
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpuft_async_quorum"
        )

        if checkpoint_transport is None:
            from torchft_tpu.checkpointing.http_transport import HTTPTransport

            checkpoint_transport = HTTPTransport(timeout=self._timeout)
        self._checkpoint_transport: CheckpointTransport = checkpoint_transport

        self._own_store: Optional[StoreServer] = None
        self._manager_server: Optional[ManagerServer] = None
        self._peer_client_factory: Callable[[str], ManagerClient] = (
            _peer_client_factory
            or (lambda addr: ManagerClient(addr, connect_timeout=self._connect_timeout))
        )

        if _manager_client is not None:
            # test hook: fully mocked control plane (``manager_test.py:41-82``)
            self._client = _manager_client
            self._replica_id = replica_id or "testing"
            self._flight.set_replica_id(self._replica_id)
            self._store: Optional[StoreClient] = None
            return

        # -- store bootstrap ------------------------------------------------
        if store_addr is None:
            store_addr = os.environ.get("MASTER_ADDR")
            store_port = store_port or int(os.environ.get("MASTER_PORT", 0) or 0)
        if store_addr is None:
            if self._group_world_size != 1:
                raise ValueError(
                    "store_addr (or MASTER_ADDR) is required for multi-rank "
                    "replica groups"
                )
            # single-process replica group: own the store
            self._own_store = StoreServer("0.0.0.0:0")
            store_addr, store_port = "127.0.0.1", self._own_store.port
        self._store = StoreClient(
            f"{store_addr}:{store_port}", timeout=self._connect_timeout
        )
        # the store address peers will use for communicator rendezvous
        advertised_store = f"{hostname}:{store_port}"

        if self._group_rank == 0:
            if replica_id is None:
                replica_id = ""
            # keep the human prefix, add entropy so restarts are distinct
            # (``manager.py:316-320``)
            new_uuid = str(uuid.uuid4())
            replica_id = (
                new_uuid if replica_id in (None, "") else f"{replica_id}:{new_uuid}"
            )
            if lighthouse_addr is None:
                lighthouse_addr = os.environ[LIGHTHOUSE_ENV]
            bind_port = port or int(os.environ.get(MANAGER_PORT_ENV, 0))
            # server_cls lets deployments swap in the C++ sidecar
            # (torchft_tpu.native.CppManagerServer) — same construction surface
            from torchft_tpu.wire import ROLE_ACTIVE, ROLE_SPARE

            self._manager_server = (server_cls or ManagerServer)(
                replica_id=replica_id,
                lighthouse_addr=lighthouse_addr,
                hostname=hostname,
                bind=f"0.0.0.0:{bind_port}",
                store_addr=advertised_store,
                world_size=self._group_world_size,
                heartbeat_interval=heartbeat_interval,
                connect_timeout=self._connect_timeout,
                quorum_retries=quorum_retries,
                health_fn=self._comm_health,
                role=ROLE_SPARE if role == "spare" else ROLE_ACTIVE,
                warm_fn=self._warm_snapshot,
                # spares ride their warm watermark on every beat (wire v4)
                # so promotion eligibility stays fresh without a quorum-RPC
                # re-registration; actives report nothing
                warm_step_fn=(
                    (lambda: self._step) if role == "spare" else None
                ),
                # degraded capacity rides quorum registrations (every
                # round) and, while < 1, direct heartbeats — read live so
                # complete_relower takes effect on the next beat
                capacity_fn=lambda: self._capacity,
                # /metrics provider: per-replica gauges from the same
                # registry that feeds last_quorum_timings (declared names
                # only — obs/metrics.py is the single source of truth)
                metrics_fn=self._metrics_snapshot,
            )
            # idle-priority warm serving: spare chunk fetches yield to live
            # collectives when the communicator exposes a busy probe
            busy_fn = getattr(self._comm, "busy", None)
            if callable(busy_fn) and hasattr(self._manager_server, "busy_fn"):
                self._manager_server.busy_fn = busy_fn
            self._store.set(MANAGER_ADDR_KEY, self._manager_server.address().encode())
            self._store.set(REPLICA_ID_KEY, replica_id.encode())

        addr = self._store.get(MANAGER_ADDR_KEY, timeout=self._connect_timeout).decode()
        self._replica_id = self._store.get(
            REPLICA_ID_KEY, timeout=self._connect_timeout
        ).decode()
        self._flight.set_replica_id(f"{self._replica_id}/{self._group_rank}")
        self._client = ManagerClient(addr, connect_timeout=self._connect_timeout)
        self._logger = _ManagerLogger(self, self._replica_id, self._group_rank)

    # ------------------------------------------------------------------
    # state dict registry
    # ------------------------------------------------------------------

    def register_state_dict_fn(
        self,
        key: str,
        load_state_dict: Callable[[T], None],
        state_dict: Callable[[], T],
    ) -> None:
        """Register one named (load, save) pair; all registered entries ride
        in the healing checkpoint (``manager.py:380-391``)."""
        self._load_state_dict_fns[key] = cast(Callable[[object], None], load_state_dict)
        self._user_state_dicts[key] = state_dict

    def disallow_state_dict_read(self) -> None:
        """Block checkpoint serving while the train loop mutates state
        (``manager.py:366-378``; used as the DiLoCo inner-step pre-hook)."""
        if getattr(self, "_state_dict_write_guard", None) is None:
            self._state_dict_write_guard = self._state_dict_lock.w_lock()

    def allow_state_dict_read(self) -> None:
        guard = getattr(self, "_state_dict_write_guard", None)
        if guard is not None:
            self._state_dict_write_guard = None
            guard.__exit__(None, None, None)

    def _manager_state_dict(self) -> Dict[str, object]:
        with self._state_dict_lock.r_lock():
            return {
                "user": {key: fn() for key, fn in self._user_state_dicts.items()},
                "torchft": self.state_dict(),
            }

    def state_dict(self) -> Dict[str, int]:
        return {"step": self._step, "batches_committed": self._batches_committed}

    def load_state_dict(self, state_dict: Dict[str, int]) -> None:
        self._step = state_dict["step"]
        self._batches_committed = state_dict["batches_committed"]

    # ------------------------------------------------------------------
    # comm health (straggler-detection input)
    # ------------------------------------------------------------------

    def _comm_health(self):
        """Cumulative comm-health snapshot for the heartbeat: completed
        epochs' fold plus the live epoch's ``lane_stats()``."""
        from torchft_tpu.wire import CommHealth

        base = self._comm_health_base
        stats_fn = getattr(self._comm, "lane_stats", None)
        live = (
            {}
            if self._comm_health_folding
            else stats_fn() if callable(stats_fn) else {}
        )
        return CommHealth(
            stalls=base["stalls"] + sum(live.get("lane_stalls") or []),
            reconnects=base["reconnects"]
            + int(live.get("lane_reconnects", 0) or 0),
            failovers=base["failovers"]
            + int(live.get("lane_failovers", 0) or 0),
            faults=base["faults"] + int(live.get("faults_injected", 0) or 0),
            tx_bytes=base["tx_bytes"] + sum(live.get("lane_tx_bytes") or []),
            rx_bytes=base["rx_bytes"] + sum(live.get("lane_rx_bytes") or []),
        )

    # mapping from last_quorum_timings keys to their declared /metrics
    # names (obs/metrics.py registry; the ftlint metrics-registry checker
    # pins every literal below to a declaration)
    _TIMING_METRICS = (
        ("quorum_rpc_s", "torchft_mgr_quorum_rpc_seconds"),
        ("configure_s", "torchft_mgr_configure_seconds"),
        ("heal_send_s", "torchft_mgr_heal_send_seconds"),
        ("heal_recv_s", "torchft_mgr_heal_recv_seconds"),
        ("heal_bytes_per_sec", "torchft_mgr_heal_bytes_per_sec"),
        ("ring_lanes", "torchft_mgr_ring_lanes"),
        ("outer_shard_overlap_ratio", "torchft_mgr_outer_shard_overlap_ratio"),
    )

    def _metrics_snapshot(self) -> Dict[str, float]:
        """Per-replica /metrics gauges for the ManagerServer endpoint —
        the same registry that feeds ``last_quorum_timings``.  Racy reads
        are fine: a scrape tolerates one stale value."""
        out: Dict[str, float] = {
            "torchft_mgr_step": float(self._step),
            "torchft_mgr_quorum_id": float(self._quorum_id),
            "torchft_mgr_capacity": float(self._capacity),
            "torchft_mgr_batches_committed_total": float(
                self._batches_committed
            ),
            "torchft_mgr_commit_failures": float(self._commit_failures),
            "torchft_mgr_flight_events": float(len(self._flight)),
            "torchft_mgr_flight_dumps_total": float(
                self._flight.dumps_total
            ),
        }
        timings = self.last_quorum_timings
        for key, name in self._TIMING_METRICS:
            value = timings.get(key)
            if value is not None:
                out[name] = float(value)
        return out

    # ------------------------------------------------------------------
    # hot spares (warm channels + promotion handshake)
    # ------------------------------------------------------------------

    @property
    def role(self) -> str:
        """``"active"`` or ``"spare"``; a spare flips at promotion."""
        return self._role

    def _promote_to_active(self) -> None:
        """Promotion handshake, spare side: from here on this replica
        registers with role=ACTIVE (acknowledging the lighthouse's
        promotion) and runs the normal train-loop state machine."""
        from torchft_tpu.wire import ROLE_ACTIVE

        self._flight.record(FlightEvent.SPARE_PROMOTE, step=self._step)
        self._role = "active"
        if self._manager_server is not None:
            self._manager_server.role = ROLE_ACTIVE

    def _warm_snapshot(self) -> Optional[tuple]:
        """Server hook: the currently staged ``(step, PytreePlan)``."""
        return self._warm_staged

    def _maybe_stage_warm(self) -> None:
        """Active side of warm channel (b): after a commit, (re)stage a
        chunk-addressable snapshot of the state dict for spare warm
        fetches — rate-limited, entirely outside the heal path, and only
        while the quorum actually advertises spares.  The host copy runs
        on the quorum executor (behind this round's quorum RPC), NOT the
        train thread — staging a multi-GB state dict inline would tax
        every step by a full-model copy; the ``_state_dict_lock`` rwlock
        gives the executor thread the same consistency the heal path's
        executor-side ``send_checkpoint`` staging already relies on.
        Never raises: a failed staging costs warmth, not the step."""
        if (
            self._manager_server is None
            or not self._spare_replica_ids
            or self._role != "active"
        ):
            return
        interval = _env_timeout(SPARE_WARM_REFRESH_S_ENV, 1.0)
        now = time.monotonic()
        if self._warm_staged is not None and self._warm_staged[0] == self._step:
            return
        # rate-limit on the SUBMIT stamp, independent of whether a staging
        # has landed yet: while the first copy is still queued (or staging
        # keeps failing) the interval must still hold, or every round
        # would queue another full-model copy on the quorum executor
        if self._warm_staged_ts and now - self._warm_staged_ts < interval:
            return
        self._warm_staged_ts = now
        self._executor.submit(self._stage_warm_now)

    def _stage_warm_now(self) -> None:
        """Executor-side body of :meth:`_maybe_stage_warm`."""
        try:
            from torchft_tpu.checkpointing.serialization import plan_pytree

            plan = plan_pytree(self._manager_state_dict(), snapshot=True)
            self._warm_staged = (self._step, plan)
        except Exception as e:  # noqa: BLE001 — warmth is best-effort
            self._logger.warn(f"warm snapshot staging failed: {e}")

    def _stage_outer_delta(self, delta: "np.ndarray") -> None:
        """collectives.outer_sharded_sync tap: hold the assembled delta
        bytes until the commit vote decides their fate."""
        self._staged_outer_delta = np.asarray(delta, dtype=np.float32).tobytes()

    def publish_staged_outer_delta(self, frag: int) -> None:
        """Publish the delta the last sharded sync staged — call ONLY after
        a committed vote (an aborted sync's delta must never reach a
        spare's shadow)."""
        payload, self._staged_outer_delta = self._staged_outer_delta, None
        if payload is not None:
            self.publish_outer_delta(frag, payload)

    def publish_outer_delta(self, frag: int, payload: bytes) -> None:
        """Feed one COMMITTED outer-sync delta (identical bytes on every
        replica by construction) to subscribed spares — warm channel (a).
        No-op without a manager server or registered spares; never raises
        (a dead feed must not fail the committed step it describes)."""
        if self._manager_server is None or not self._spare_replica_ids:
            return
        publish = getattr(self._manager_server, "publish_delta", None)
        if not callable(publish):
            return  # C++ sidecar: no spare feed
        try:
            publish(self._step, frag, bytes(payload))
        except Exception as e:  # noqa: BLE001
            self._logger.warn(f"outer delta publish failed: {e}")

    # ------------------------------------------------------------------
    # degraded mode (survive in-replica device loss)
    # ------------------------------------------------------------------

    @property
    def capacity(self) -> float:
        """Surviving-device fraction this replica runs at (1.0 = full
        width).  Advertised to the lighthouse on every quorum registration
        (and on heartbeats while degraded) as the wire-v5 capacity tail."""
        return self._capacity

    def participant_capacities(self) -> List[float]:
        """Per-participant capacity fractions of the current quorum,
        aligned with the sorted replica-id order (empty on pre-v5 peers or
        before the first quorum).  Callers must hold a completed quorum
        (``wait_quorum``) — the data-shard rescale path does."""
        return list(self._participant_capacities)

    def begin_relower(self) -> None:
        """Mark the start of a degraded re-lower (device loss detected;
        inner mesh about to be rebuilt on the survivors).  Between here and
        :meth:`complete_relower` every commit vote is forced False: a
        half-relowered replica holds inner state that is neither the old
        nor the new layout, and a commit landing in that window would fork
        it from the fleet.  Idempotent; crash-safe by construction (a
        replica that dies mid-relower simply never voted commit)."""
        self._flight.record(FlightEvent.RELOWER_BEGIN, step=self._step)
        self._relower_pending = True

    def complete_relower(self, capacity: float) -> None:
        """Finish a degraded re-lower: the inner mesh is consistent again
        on the surviving devices and this replica now runs at ``capacity``
        (0 < capacity <= 1).  Lifts the commit fence and advertises the new
        fraction on the next quorum registration/heartbeat.  Also the
        restore path: ``complete_relower(1.0)`` after the wounded devices
        heal re-admits a swapped-out replica."""
        if not 0.0 < capacity <= 1.0:
            raise ValueError(
                f"capacity must be in (0, 1], got {capacity!r}"
            )
        if capacity < 1.0 and self._manager_server is not None and not hasattr(
            self._manager_server, "_capacity_fn"
        ):
            # the C++ sidecar has no capacity plumbing: registering
            # full-width while actually degraded would make peers weight
            # this replica's starved contribution at full strength —
            # refuse loudly (docs/operations.md §16 fallback matrix)
            raise RuntimeError(
                "degraded mode requires the Python control plane; this "
                "replica's manager server does not advertise capacity"
            )
        self._capacity = capacity
        self._relower_pending = False
        self._flight.record(
            FlightEvent.RELOWER_COMPLETE, step=self._step, capacity=capacity
        )
        self._logger.info(
            f"re-lower complete: running at capacity {capacity:.3f}"
        )

    def _capacity_weights_engaged(self) -> bool:
        """True when the outer reduce must be capacity-weighted this step.
        A pure function of quorum facts (the capacity vector and the
        participant count), so every rank reaches the same verdict — a
        split decision would fork the divisor across the fleet.  Weighted
        mode requires participation to cover the whole quorum (sync-quorum
        rounds, or async rounds with nobody healing): with healers
        excluded, capacity shares normalized over all members would
        mis-scale the average, so those rounds fall back to the uniform
        1/num_participants divisor."""
        caps = self._participant_capacities
        return bool(
            caps
            and any(c < 1.0 for c in caps)
            and sum(caps) > 0.0
            and self._participating_replica_world_size == len(caps)
        )

    def _own_capacity_weight(self) -> float:
        """This replica's normalized capacity share w_i = cap_i / Σ cap
        under the current quorum (0.0 when not participating).  Only
        meaningful when :meth:`_capacity_weights_engaged` is True."""
        caps = self._participant_capacities
        rank = self._participating_replica_rank
        if rank is None or not 0 <= rank < len(caps):
            return 0.0
        return caps[rank] / sum(caps)

    def _capacity_weight_scale(self) -> Optional[float]:
        """Pre-scale factor turning the standard ``sum / num_participants``
        average into the capacity-weighted average: ``w_i × N`` applied to
        this replica's contribution before the collective, so the shared
        post-division yields ``Σ w_i · g_i``.  None when unweighted."""
        if not self._capacity_weights_engaged():
            return None
        return self._own_capacity_weight() * self.num_participants()

    # ------------------------------------------------------------------
    # error funnel
    # ------------------------------------------------------------------

    def errored(self) -> Optional[ExceptionWithTraceback]:
        return self._errored

    def report_error(self, e: Exception) -> None:
        """Record an error for this step; the step will be voted down at
        commit instead of raising into the train loop
        (``manager.py:495-520``)."""
        wrapped = (
            e
            if isinstance(e, ExceptionWithTraceback)
            else ExceptionWithTraceback(e)
        )
        self._errored = wrapped
        self._flight.record(
            FlightEvent.ERROR, step=self._step, error=str(e)[:200]
        )
        self._flight.maybe_dump("error_funnel")
        self.errors_logger.info(
            "",
            extra={
                "job_id": os.environ.get("JOB_ID", "unknown"),
                "replica_id": self._replica_id,
                "rank": self._group_rank,
                "quorum_id": self._quorum_id,
                "step": self._step,
                "error": str(e),
            },
        )

    def wrap_work(self, work: Work, default: object) -> Work:
        """Swallow errors from async work: on failure, record and substitute
        ``default`` (``manager.py:522-558``)."""

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _chain(f: concurrent.futures.Future) -> None:
            err = f.exception()
            if err is not None:
                if isinstance(err, Exception):
                    self.report_error(err)
                fut.set_result(default)
            else:
                fut.set_result(f.result())

        work.future().add_done_callback(_chain)
        return Work(fut)

    # ------------------------------------------------------------------
    # quorum
    # ------------------------------------------------------------------

    def start_quorum(
        self,
        allow_heal: bool = True,
        shrink_only: bool = False,
        timeout: Optional[float] = None,
    ) -> None:
        """Compute a new quorum and ready the manager for a new step
        (``manager.py:560-615``)."""
        if self._adopted_quorum:
            # promotion handshake: the spare already adopted a quorum (and
            # possibly a heal) for THIS step via spare.SpareAgent — a fresh
            # RPC would park against actives mid-rendezvous.  Consume the
            # flag; the pending future/recovery event fence as usual.
            self._adopted_quorum = False
            return
        if self._quorum_future is not None:
            try:
                self._quorum_future.result()
            except Exception:  # noqa: BLE001
                # already funneled (or about to be superseded): the failed
                # step was voted down at should_commit; the retry starting
                # here must not re-raise the same error into the train loop
                pass

        self._errored = None
        self._healing = False
        self._flight.set_context(step=self._step)
        self._flight.record(FlightEvent.QUORUM_START, step=self._step)
        # drop stale works from a step the caller abandoned without voting;
        # RESOLVED stream entries whose barrier never ran are abandoned the
        # same way (their staged outer state was never adopted), but an
        # entry still in flight stays — the vote fence must keep refusing
        # until the collective actually drains
        with self._pending_works_lock:
            self._pending_works.clear()
            self._stream_pending = {
                f: e for f, e in self._stream_pending.items() if not e[0].done()
            }

        self._quorum_future = self._executor.submit(
            self._async_quorum,
            allow_heal=allow_heal,
            shrink_only=shrink_only,
            quorum_timeout=timeout or self._quorum_timeout,
        )
        # hot spares, warm channel (b): (re)stage a chunk-addressable
        # snapshot of the state dict for spare warm fetches.  HERE — not at
        # the commit vote — because state is quiescent at a step boundary:
        # every committed update is fully applied and ``_step`` labels it
        # exactly (the same consistency model heal staging relies on).
        # Submitted AFTER the quorum so the copy queues behind this
        # round's RPC on the (single-thread) executor, never ahead of it.
        self._maybe_stage_warm()
        if not self._use_async_quorum:
            # sync quorum (DiLoCo/LocalSGD): a failed quorum RPC funnels to
            # a False vote like everywhere else, never into the train loop
            try:
                self.wait_quorum()
            except Exception as e:  # noqa: BLE001
                self.report_error(e)
                return
            if self._healing:
                # heal eagerly so the forward pass runs on good state
                self._apply_pending_state_dict()
                self._healing = False

    @traced("torchft::manager::wait_quorum")
    def wait_quorum(self) -> None:
        """Block until the pending quorum completes; the communicator is in a
        healthy (re)configured state afterwards (``manager.py:617-627``)."""
        assert self._quorum_future is not None, (
            "must call start_quorum before wait_quorum"
        )
        self._quorum_future.result()

    @traced("torchft::manager::_async_quorum")
    def _async_quorum(
        self, allow_heal: bool, shrink_only: bool, quorum_timeout: float
    ) -> None:
        # per-phase wall times of THIS quorum round, for heal attribution
        # (bench/operators read it after wait_quorum; the reference leaves
        # this to profiler spans — a dict is greppable in a kill report)
        timings: Dict[str, float] = {}
        self.last_quorum_timings = timings
        t0 = time.monotonic()
        with obs_span("manager::quorum_rpc", step=self._step):
            quorum = self._client._quorum(
                group_rank=self._group_rank,
                step=self._step,
                checkpoint_metadata=self._checkpoint_transport.metadata(),
                shrink_only=shrink_only,
                timeout=quorum_timeout,
                init_sync=self._init_sync,
                commit_failures=self._commit_failures,
            )
        timings["quorum_rpc_s"] = time.monotonic() - t0
        self._adopt_quorum(quorum, allow_heal, timings)

    def _adopt_quorum(
        self,
        quorum,
        allow_heal: bool,
        timings: Dict[str, float],
    ) -> None:
        """Apply one quorum result: reconfigure the communicator on a
        membership change, serve/fetch heals, and refresh participation
        facts.  Factored out of :meth:`_async_quorum` so a promoted spare
        can adopt the quorum it was handed by the promotion fast-path
        WITHOUT issuing a fresh quorum RPC (the actives are already parked
        in mesh rendezvous waiting for it)."""
        # registered spares this round (v3; empty on legacy peers) gate the
        # active-side warm channels
        self._spare_replica_ids = list(quorum.spare_replica_ids)
        # per-participant capacities (v5; empty on legacy peers): the
        # weighted-outer-reduce and data-shard-rescale inputs — refreshed
        # every round even without a membership change, since a wound
        # never bumps quorum_id by itself
        self._participant_capacities = list(
            getattr(quorum, "participant_capacities", None) or []
        )

        quorum_id = quorum.quorum_id
        replica_rank = quorum.replica_rank
        replica_world_size = quorum.replica_world_size
        heal = quorum.heal
        max_step = quorum.max_step

        # ``ranks_in_quorum``: global ranks across the whole job
        # (``manager.py:668-672``)
        ranks_in_quorum = [
            extract_trailing_digits(rid.split(":")[0]) * self._group_world_size
            + self._group_rank
            for rid in quorum.replica_ids
        ]

        # async quorum → healers are excluded (max-step set); sync quorum →
        # everyone counts because heal completes before the step
        self._participating_replica_rank, self._participating_replica_world_size = (
            (quorum.max_replica_rank, quorum.max_world_size)
            if self._use_async_quorum or not allow_heal
            else (replica_rank, replica_world_size)
        )

        if self._replica_world_size_mode == WorldSizeMode.FIXED_WITH_SPARES:
            self._participating_replica_world_size = min(
                self._participating_replica_world_size, self._min_replica_size
            )
            if (
                self._participating_replica_rank is not None
                and self._participating_replica_rank >= self._min_replica_size
            ):
                self._participating_replica_rank = None

        if quorum_id != self._quorum_id:
            # lane counters of the OUTGOING epoch (bytes/stalls accumulated
            # since its configure) ride the quorum-change event: per-lane
            # imbalance or a stall-heavy lane is visible per epoch without
            # any scraping of the data plane itself
            quorum_extra = {
                "job_id": os.environ.get("JOB_ID", "unknown"),
                "replica_id": self._replica_id,
                "rank": self._group_rank,
                "quorum_id": quorum_id,
                "step": max_step,
            }
            if self._outer_shard_stats:
                # sharded-outer-sync pipeline timings of the outgoing epoch
                # (scatter/update/gather + overlap ratio) ride the same
                # event, then reset so an epoch with no sharded sync never
                # re-reports a stale overlap_ratio
                quorum_extra.update(self._outer_shard_stats)
                self._outer_shard_stats = {}
            coord_stats_fn = getattr(
                self._manager_server, "coord_stats", None
            )
            if callable(coord_stats_fn):
                # coordination-plane beat routing of this replica (via-agg
                # vs direct vs fallbacks) rides the same event
                quorum_extra.update(coord_stats_fn())
            lane_stats_fn = getattr(self._comm, "lane_stats", None)
            prev_lane_stats = lane_stats_fn() if callable(lane_stats_fn) else {}
            if prev_lane_stats:
                quorum_extra.update(
                    comm_lanes=prev_lane_stats.get("lanes"),
                    comm_lane_tx_bytes=prev_lane_stats.get("lane_tx_bytes"),
                    comm_lane_rx_bytes=prev_lane_stats.get("lane_rx_bytes"),
                    comm_lane_stalls=prev_lane_stats.get("lane_stalls"),
                    comm_lane_reconnects=prev_lane_stats.get(
                        "lane_reconnects", 0
                    ),
                    comm_lane_failovers=prev_lane_stats.get(
                        "lane_failovers", 0
                    ),
                    comm_injected_faults=prev_lane_stats.get(
                        "faults_injected", 0
                    ),
                )
                # fold the OUTGOING epoch's counters into the job-lifetime
                # base the heartbeat health summary reports from; from here
                # until the fresh mesh is configured the live counters are
                # already IN the base, so heartbeats report base-only
                self._comm_health_folding = True
                base = self._comm_health_base
                base["stalls"] += sum(prev_lane_stats.get("lane_stalls") or [])
                base["reconnects"] += int(
                    prev_lane_stats.get("lane_reconnects", 0) or 0
                )
                base["failovers"] += int(
                    prev_lane_stats.get("lane_failovers", 0) or 0
                )
                base["faults"] += int(
                    prev_lane_stats.get("faults_injected", 0) or 0
                )
                base["tx_bytes"] += sum(
                    prev_lane_stats.get("lane_tx_bytes") or []
                )
                base["rx_bytes"] += sum(
                    prev_lane_stats.get("lane_rx_bytes") or []
                )
                # gray-failure counters next to the phase wall-times, so a
                # drill can assert in-epoch recovery without scraping logs
                timings["comm_lane_reconnects"] = float(
                    base["reconnects"]
                )
                timings["comm_lane_failovers"] = float(base["failovers"])
                timings["comm_injected_faults"] = float(base["faults"])
                if prev_lane_stats.get("topo_hosts"):
                    # hierarchical-topology counters of the outgoing epoch:
                    # host grouping + shared-memory bytes that never touched
                    # the DCN (the cross-host byte reduction, observable)
                    quorum_extra.update(
                        comm_topo_hosts=prev_lane_stats.get("topo_hosts"),
                        comm_topo_local_world=prev_lane_stats.get(
                            "topo_local_world"
                        ),
                        comm_shm_bytes=(
                            int(prev_lane_stats.get("shm_tx_bytes", 0))
                            + int(prev_lane_stats.get("shm_rx_bytes", 0))
                        ),
                    )
            self.quorum_logger.info("", extra=quorum_extra)
            store_prefixed_addr = (
                f"{quorum.store_address}/torchft/{quorum_id}/{self._group_rank}"
            )
            self._logger.info(
                f"reconfiguring for quorum_id={quorum_id} store={store_prefixed_addr}"
            )
            # fresh profiler epoch per quorum (flight-recorder analog)
            self._tracer.on_quorum_change(quorum_id)
            # the (quorum_id, step) pair stamped here is the correlation
            # anchor flight_merge aligns replicas' clocks on
            self._flight.set_context(step=max_step, quorum_id=quorum_id)
            self._flight.record(
                FlightEvent.QUORUM_ADOPT,
                step=max_step,
                quorum_id=quorum_id,
                world=replica_world_size,
                replica_rank=replica_rank,
            )
            t_cfg = time.monotonic()
            try:
                self._quorum_id = quorum_id
                with obs_span(
                    "manager::comm_configure", quorum_id=quorum_id
                ):
                    self._comm.configure(
                        store_prefixed_addr,
                        self._replica_id if self._replica_id is not None else "0",
                        replica_rank,
                        replica_world_size,
                        quorum_id=quorum_id,
                        group_rank=self._group_rank,
                        group_world_size=self._group_world_size,
                        global_ranks=ranks_in_quorum,
                    )
            except Exception as e:  # noqa: BLE001
                self._logger.exception(f"got exception in comm configure: {e}")
                self.report_error(e)
                return
            finally:
                self._comm_health_folding = False
                timings["configure_s"] = time.monotonic() - t_cfg
            # lane layout of the fresh epoch (benches/operators read it from
            # last_quorum_timings next to the phase wall-times)
            fresh_lane_stats = (
                lane_stats_fn() if callable(lane_stats_fn) else {}
            )
            if fresh_lane_stats.get("lanes"):
                timings["ring_lanes"] = float(fresh_lane_stats["lanes"])
                timings["ring_stripe_floor_bytes"] = float(
                    fresh_lane_stats.get("stripe_floor_bytes", 0)
                )
            if fresh_lane_stats.get("topo_hosts"):
                # topology of the fresh epoch, next to the phase wall-times
                timings["topo_hosts"] = float(fresh_lane_stats["topo_hosts"])
                timings["topo_local_world"] = float(
                    fresh_lane_stats.get("topo_local_world", 1)
                )

        if allow_heal:
            # The reference runs recovery on a dedicated CUDA stream
            # (``manager.py:746-813``); here the quorum thread *is* the
            # recovery lane and the event fences should_commit.
            recovery_event = Event()
            # striped healing engages only when the quorum advertised 2+
            # up-to-date sources (wire v2) and the env gate is on; the
            # single-peer path below is the byte-for-byte legacy behavior
            # and the automatic P=1 fallback
            striped_sources = (
                quorum.recover_src_replica_ranks if _heal_striped_enabled() else []
            )
            i_am_striped_source = (
                len(striped_sources) > 1
                and replica_rank in striped_sources
                and bool(quorum.all_recover_dst_replica_ranks)
            )
            try:
                send_dsts = (
                    list(quorum.all_recover_dst_replica_ranks)
                    if i_am_striped_source
                    else list(quorum.recover_dst_replica_ranks)
                )
                if send_dsts:
                    self._logger.info(f"peers need recovery from us {send_dsts}")
                    t_send = time.monotonic()
                    self._flight.record(
                        FlightEvent.HEAL_SEND_BEGIN,
                        step=max_step,
                        dst_ranks=list(send_dsts),
                        striped=i_am_striped_source,
                    )
                    with obs_span("manager::heal_send", step=max_step):
                        if i_am_striped_source:
                            self._checkpoint_transport.send_checkpoint_striped(
                                dst_ranks=send_dsts,
                                step=max_step,
                                state_dict=self._manager_state_dict(),
                                timeout=self._timeout,
                                source_index=striped_sources.index(replica_rank),
                                num_sources=len(striped_sources),
                            )
                        else:
                            self._checkpoint_transport.send_checkpoint(
                                dst_ranks=send_dsts,
                                step=max_step,
                                state_dict=self._manager_state_dict(),
                                timeout=self._timeout,
                            )
                    timings["heal_send_s"] = time.monotonic() - t_send
                    self._flight.record(
                        FlightEvent.HEAL_SEND_END,
                        step=max_step,
                        duration_s=round(timings["heal_send_s"], 4),
                    )

                if heal:
                    t_recv = time.monotonic()
                    self._healing = True
                    self._flight.record(
                        FlightEvent.HEAL_RECV_BEGIN,
                        step=max_step,
                        sources=len(striped_sources) or 1,
                    )
                    if len(striped_sources) > 1:
                        with obs_span("manager::heal_recv", step=max_step):
                            self._pending_state_dict = self._recv_striped_checkpoint(
                                quorum.heal_sources(), max_step, timings
                            )
                    else:
                        self._logger.info(
                            "healing required, fetching checkpoint metadata from "
                            f"{quorum.recover_src_manager_address} max_step={max_step}"
                        )
                        primary_client = self._peer_client_factory(
                            quorum.recover_src_manager_address
                        )
                        checkpoint_metadata = primary_client._checkpoint_metadata(
                            self._group_rank, timeout=self._timeout
                        )
                        primary_client.close()
                        recover_src_replica_rank = quorum.recover_src_replica_rank
                        assert recover_src_replica_rank is not None, (
                            "must have a recover rank when healing"
                        )
                        self._logger.info(
                            f"fetching checkpoint from {recover_src_replica_rank=} "
                            f"with {checkpoint_metadata=}"
                        )
                        # applied on the main thread at should_commit when safe
                        self._pending_state_dict = (
                            self._checkpoint_transport.recv_checkpoint(
                                src_rank=recover_src_replica_rank,
                                metadata=checkpoint_metadata,
                                step=max_step,
                                timeout=self._timeout,
                            )
                        )
                    self.load_state_dict(
                        cast(Dict[str, int], self._pending_state_dict["torchft"])
                    )
                    self._step = max_step
                    timings["heal_recv_s"] = time.monotonic() - t_recv
                    self._flight.set_context(step=max_step)
                    self._flight.record(
                        FlightEvent.HEAL_RECV_END,
                        step=max_step,
                        duration_s=round(timings["heal_recv_s"], 4),
                    )
            except Exception as e:  # noqa: BLE001
                self._logger.exception(f"got exception in recovery: {e}")
                self.report_error(e)
            recovery_event.record()
            self._recovery_event = recovery_event

    def _recv_striped_checkpoint(
        self,
        sources: List,
        max_step: int,
        timings: Dict[str, float],
    ) -> Dict[str, object]:
        """Striped multi-source heal: collect each source's transport
        metadata (tolerating unreachable managers — a dead source stays in
        the list as a positional placeholder so chunk assignments agree
        across peers) and fetch disjoint chunk ranges from all of them."""
        self._logger.info(
            f"healing required, striped fetch from {len(sources)} sources "
            f"max_step={max_step}"
        )
        src_list: List = []
        for src_rank, addr in sources:
            metadata: Optional[str] = None
            try:
                peer = self._peer_client_factory(addr)
                metadata = peer._checkpoint_metadata(
                    self._group_rank, timeout=self._timeout
                )
                peer.close()
            except Exception as e:  # noqa: BLE001 — source-level failover
                self._logger.warn(
                    f"heal source {src_rank} at {addr} unreachable: {e}"
                )
            src_list.append((src_rank, metadata))
        if all(metadata is None for _, metadata in src_list):
            raise RuntimeError(
                f"no heal source produced checkpoint metadata ({sources})"
            )
        state = self._checkpoint_transport.recv_checkpoint_striped(
            sources=src_list, step=max_step, timeout=self._timeout
        )
        metrics = getattr(self._checkpoint_transport, "last_heal_metrics", None)
        if metrics is not None:
            from torchft_tpu.observability import log_heal

            timings["heal_bytes"] = float(metrics.bytes_total)
            timings["heal_bytes_per_sec"] = metrics.bytes_per_sec
            timings["heal_num_sources"] = float(metrics.num_sources)
            timings["heal_stolen_chunks"] = float(metrics.stolen_chunks)
            log_heal(
                metrics,
                replica_id=self._replica_id,
                rank=self._group_rank,
                quorum_id=self._quorum_id,
            )
        return cast(Dict[str, object], state)

    def _apply_pending_state_dict(self) -> None:
        assert self._healing, "must be in healing state"
        assert self._quorum_future is not None, "must call step before should_commit"
        self._quorum_future.result()

        pending_state_dict = self._pending_state_dict
        if pending_state_dict is None:
            assert self.errored(), "checkpoint was not staged and no error occurred"
            return
        self._logger.info("applying pending state dict")
        assert self._load_state_dict_fns, "user load_state_dict is not initialized"
        pending_user = cast(Dict[str, object], pending_state_dict["user"])
        with self._state_dict_lock.w_lock():
            for key, load_fn in self._load_state_dict_fns.items():
                load_fn(pending_user[key])
            self._pending_state_dict = None
        self._flight.record(FlightEvent.HEAL_APPLY, step=self._step)
        self._logger.info("Loaded state dict.")

    # ------------------------------------------------------------------
    # gradient averaging
    # ------------------------------------------------------------------

    def allreduce_is_identity(self) -> bool:
        """True when the replica-dim average is mathematically the identity
        (single-member communicator, this replica fully participating) —
        callers may then skip device↔host gradient movement entirely, the
        analog of a world-size-1 NCCL allreduce being free."""
        try:
            self.wait_quorum()
        except Exception as e:  # noqa: BLE001 — funnel, never raise
            self.report_error(e)
            return False
        return (
            self._comm.size() <= 1
            and self.num_participants() == 1
            and self.is_participating()
            and self._errored is None
        )

    def allreduce(
        self,
        data: Union[np.ndarray, List[np.ndarray]],
        should_quantize: bool = False,
        in_place: bool = False,
        stream: Optional[int] = None,
        register_pending: bool = True,
    ) -> Work:
        """Fault-tolerant AVG allreduce of gradients across the participating
        replicas (``manager.py:410-493``).

        Returns a Work whose value is the averaged array(s).  If an error was
        already recorded this step the input is returned unchanged; if this
        replica is not participating (healing/spare) its contribution is
        zeroed and the result is still divided by ``num_participants()``.

        ``in_place=True`` skips the communicator's full-payload defensive
        copy by reducing directly in ``data``'s buffers — pass it ONLY for
        buffers you built for this call and will not read afterwards (the
        ddp bucket path does); buffers that alias live state (LocalSGD's
        host params) must keep the default.

        ``stream``, when given, marks this as an ASYNC streamed fragment
        submit (the TORCHFT_STREAM_SYNC scheduler riding the legacy
        replicated outer wire): the work registers in the stream-fence
        registry instead of ``_pending_works`` — same contract as
        :meth:`outer_shard_allreduce`'s ``stream``.

        ``register_pending=False`` registers the work NOWHERE: for
        constituent works whose owner fences a composite covering them
        (``ddp.allreduce_pytree``'s streamed bucket rings — the composite
        is what rides the stream-fence registry).
        """

        def _failed_fast(w: Work) -> Work:
            # a fail-fast streamed submit still registers (and stamps
            # FRAG_SUBMIT): the caller's barrier will stream_resolved the
            # fragment, and a FRAG_ABORT must always have a paired submit
            # on the flight timeline
            return w if stream is None else self.stream_submitted(stream, w)

        if self.errored():
            return _failed_fast(DummyWork(data))

        # a failed quorum funnels like any collective error: the input rides
        # through unchanged and the vote discards the step — errors must
        # never propagate into the train loop (``manager.py:487-493``)
        try:
            self.wait_quorum()
        except Exception as e:  # noqa: BLE001
            self.report_error(e)
            return _failed_fast(DummyWork(data))
        num_participants = self.num_participants()

        if not self.is_participating():
            # contribute zeros (the reference zeroes the grad tensors in
            # place, ``manager.py:441-442``; inputs here may be read-only
            # jax views, so swap in zero buffers instead)
            if isinstance(data, np.ndarray):
                data = np.zeros_like(data)
            else:
                data = [np.zeros_like(a) for a in data]
        elif (scale := self._capacity_weight_scale()) is not None:
            # degraded fleet: pre-scale this replica's contribution by
            # w_i × N so the shared 1/N post-division yields the
            # capacity-weighted average Σ w_i·g_i — matching the
            # capacity-proportional data shards each replica processed.
            # The collective's summed bytes stay identical on every rank,
            # so replicas never fork.  Integer grads are left unweighted
            # (fractional scaling would truncate them to garbage).
            data = _scale_contribution(data, scale)

        try:
            if should_quantize:
                from torchft_tpu.collectives import allreduce_quantized
                from torchft_tpu.quantization import quant_kind

                # wire format for the quantized ring: int8 (default) or
                # fp8 e4m3 (the reference's format) via TORCHFT_QUANT_KIND
                work = allreduce_quantized(self._comm, data, kind=quant_kind())
            else:
                work = self._comm.allreduce(data, ReduceOp.SUM, in_place=in_place)

            # AVG = SUM / runtime participant count — replica count is never
            # baked into compiled programs (SURVEY.md §7 hard part 1)
            def _normalize(value: object) -> object:
                if isinstance(value, np.ndarray):
                    return _div(value, num_participants)
                return [_div(a, num_participants) for a in cast(list, value)]

            wrapped = self.wrap_work(work.then(_normalize), data)
            if stream is not None:
                self.stream_submitted(stream, wrapped)
            elif register_pending:
                self._register_pending(wrapped)
            return wrapped
        except Exception as e:  # noqa: BLE001
            self._logger.exception(f"got exception in all reduce -- skipping remaining: {e}")
            self.report_error(e)
            return _failed_fast(DummyWork(data))

    def allreduce_prequantized(
        self, q: np.ndarray, scales: np.ndarray, n: int
    ) -> Work:
        """Fault-tolerant SUM-allreduce of an already-quantized stream (int8
        rows + rowwise f32 scales, e.g. quantized on device by
        ``ops.pallas_quant``), normalized by ``num_participants()``.

        Same orchestration contract as :meth:`allreduce`: waits the quorum,
        zeroes the contribution of non-participants, swallows errors into a
        failed vote, and returns a pending Work (the wire pipeline runs
        off-thread) whose value is the averaged float32 array of length
        ``n``.  On error the value is this replica's own dequantized
        contribution, mirroring the unquantized input-passthrough."""
        from torchft_tpu.collectives import allreduce_prequantized
        from torchft_tpu.quantization import dequantize_int8_rowwise

        def _own_value() -> np.ndarray:
            return dequantize_int8_rowwise(
                q, np.asarray(scales).reshape(-1), n, np.float32
            )

        if self.errored():
            return DummyWork(_own_value())

        try:
            self.wait_quorum()
        except Exception as e:  # noqa: BLE001 — funnel, never raise
            self.report_error(e)
            return DummyWork(_own_value())
        num_participants = self.num_participants()
        q_in, s_in = q, scales
        if not self.is_participating():
            q_in = np.zeros_like(q)
            s_in = np.zeros_like(scales)
        elif (scale := self._capacity_weight_scale()) is not None:
            # weighted average on an already-quantized stream: the int8
            # payload is untouchable, but dequant = q × scale — so the
            # capacity weight rides the rowwise scales
            s_in = (np.asarray(scales, np.float32) * np.float32(scale))

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _run() -> None:
            try:
                summed = allreduce_prequantized(self._comm, q_in, s_in, n)
                fut.set_result(summed / num_participants)
            except Exception as e:  # noqa: BLE001 — funnel, never raise
                self.report_error(e)
                fut.set_result(_own_value())

        threading.Thread(
            target=_run, name="tpuft_prequantized_allreduce", daemon=True
        ).start()
        out = Work(fut)
        self._register_pending(out)
        return out

    def outer_shard_group(self) -> tuple:
        """``(group_size, group_index, owns_shard)`` for the sharded outer
        optimizer under the CURRENT quorum: flat topologies shard across the
        communicator world (one shard per replica); hierarchical topologies
        shard across HOSTS (owners are the host leaders — members ride the
        shared-memory hops and own no outer state).  Callers must hold a
        completed quorum (``wait_quorum``) — the fragment sync path does."""
        comm = self._comm
        topo_fn = getattr(comm, "hier_topology", None)
        topo = topo_fn() if callable(topo_fn) else None
        if topo:
            ring = list(topo["leader_ring"])
            if topo["is_leader"]:
                return len(ring), ring.index(comm.rank()), True
            return len(ring), -1, False
        ws = max(1, comm.size())
        return ws, comm.rank() if ws > 1 else 0, True

    def outer_shard_allreduce(
        self,
        flat: np.ndarray,
        update_cb: Callable[[int, int, np.ndarray], np.ndarray],
        should_quantize: bool = False,
        stream: Optional[int] = None,
    ) -> Work:
        """Fault-tolerant sharded outer sync (ZeRO-1 over the replica dim):
        chunk-pipelined ``reduce_scatter → update_cb → allgather`` of the
        flat f32 pseudo-gradient, normalized by ``num_participants()``.

        Same orchestration contract as :meth:`allreduce`: waits the quorum,
        zeroes the contribution of non-participants (they still run the
        collective schedule and apply the same deltas, so params never
        fork), funnels errors into a failed vote, and returns a pending
        Work.  The value is the f32 delta (``params = backup + delta``) —
        or ``None`` after any error, which the caller must treat as a
        discarded step (the vote will be False).  Pipeline phase timings
        land in ``last_quorum_timings`` as ``outer_shard_*``.

        ``stream``, when given, is the fragment index of an ASYNC streamed
        submit (the TORCHFT_STREAM_SYNC scheduler in ``local_sgd.py``): the
        collectives frame in that fragment's rotating STREAM_OUTER tag
        window, the work registers in the stream-fence registry instead of
        ``_pending_works`` (so ``start_quorum``'s stale-work drop and the
        vote's fence never touch it), and a FRAG_SUBMIT flight event marks
        the submit.  :meth:`should_commit` votes False while any streamed
        work is unresolved — a half-streamed sync NEVER commits; the caller
        must ``wait()`` the work at its bounded-staleness barrier before
        voting."""

        def _failed_fast(w: Work) -> Work:
            # fail-fast streamed submits still register + stamp FRAG_SUBMIT
            # so the barrier's FRAG_ABORT always has its pair (see allreduce)
            return w if stream is None else self.stream_submitted(stream, w)

        if self.errored():
            return _failed_fast(DummyWork(None))
        try:
            self.wait_quorum()
        except Exception as e:  # noqa: BLE001 — funnel, never raise
            self.report_error(e)
            return _failed_fast(DummyWork(None))
        num_participants = self.num_participants()
        if not self.is_participating():
            flat = np.zeros_like(flat)

        # degraded fleet: the sharded outer sync runs as a WEIGHTED sum —
        # every rank pre-scales its pseudo-gradient by its normalized
        # capacity share and the division drops out (weights sum to 1).
        # The engage decision is a pure function of quorum facts, so the
        # whole fleet flips together; the allgathered wire-format delta
        # stays bit-identical across replicas either way.
        weight: Optional[float] = None
        if self._capacity_weights_engaged():
            weight = self._own_capacity_weight() if self.is_participating() else 0.0

        from torchft_tpu import wire as wire_mod
        from torchft_tpu.collectives import outer_sharded_sync
        from torchft_tpu.quantization import quant_kind

        kind = quant_kind() if should_quantize else None
        timings = self.last_quorum_timings
        fut: concurrent.futures.Future = concurrent.futures.Future()
        if stream is None:
            tag_base, tag_span = (
                wire_mod.OUTER_SHARD_TAG_BASE,
                wire_mod.OUTER_SHARD_TAG_SPAN,
            )
        else:
            # window keyed on (outer step + fragment): consecutive streamed
            # syncs land in distinct windows even at num_fragments=1 (the
            # step advances every committed round, and a failed round
            # poisons the comm epoch, whose reconfigure flushes the old
            # connections), and the key is quorum-shared state, so a healed
            # replica picks the same window as the survivors — a local
            # submit counter would drift permanently after a restart
            tag_base, tag_span = wire_mod.stream_frag_tag_window(
                self._step + stream
            )

        def _run() -> None:
            tm: Dict[str, float] = {}
            try:
                delta = outer_sharded_sync(
                    self._comm,
                    flat,
                    update_cb,
                    num_participants,
                    should_quantize=should_quantize,
                    kind=kind or "int8",
                    timings=tm,
                    weight=weight,
                    # delta-tap: stage the (replica-identical) delta bytes
                    # for the spare feed; published only on a committed vote
                    tap=(
                        self._stage_outer_delta
                        if self._spare_replica_ids
                        else None
                    ),
                    tag_base=tag_base,
                    tag_span=tag_span,
                )
                fut.set_result(delta)
            except Exception as e:  # noqa: BLE001 — funnel, never raise
                self.report_error(e)
                fut.set_result(None)
            finally:
                if tm:
                    stats = {f"outer_shard_{k}": v for k, v in tm.items()}
                    timings.update(stats)
                    self._outer_shard_stats = stats

        threading.Thread(
            target=_run, name="tpuft_outer_shard_sync", daemon=True
        ).start()
        out = Work(fut)
        if stream is None:
            self._register_pending(out)
        else:
            self.stream_submitted(stream, out)
        return out

    def stream_submitted(self, frag: int, work: Work) -> Work:
        """Register an async streamed fragment sync in the stream-fence
        registry (NOT ``_pending_works`` — see :meth:`outer_shard_allreduce`)
        and stamp the FRAG_SUBMIT flight event.  Returns ``work``."""
        self._flight.record(
            FlightEvent.FRAG_SUBMIT, step=self._step, frag=frag
        )
        with self._pending_works_lock:
            self._stream_pending[frag] = (work, self._step)
        return work

    def stream_unresolved(self) -> List[int]:
        """Fragment indices of streamed outer syncs whose collectives are
        still in flight.  Non-empty at vote time forces the vote False
        (:meth:`should_commit`) — the commit fence that guarantees a
        half-streamed sync never commits."""
        with self._pending_works_lock:
            return sorted(
                f
                for f, (w, _s) in self._stream_pending.items()
                if not w.done()
            )

    def stream_resolved(self, frag: int, committed: Optional[bool]) -> None:
        """Mark a streamed fragment sync fully resolved (waited + voted +
        applied or discarded) and record its lifecycle flight event —
        stamped with the SUBMIT-time step, so the FRAG_SUBMIT/FRAG_COMMIT
        pair shares a ``(step, frag)`` key on the merged timeline (a
        committed vote bumps ``_step`` before the caller gets here)."""
        with self._pending_works_lock:
            entry = self._stream_pending.pop(frag, None)
        self._flight.record(
            FlightEvent.FRAG_COMMIT if committed else FlightEvent.FRAG_ABORT,
            step=entry[1] if entry is not None else self._step,
            frag=frag,
        )

    def _register_pending(self, work: Work) -> None:
        with self._pending_works_lock:
            self._pending_works.append(work)

    def _fence_pending_works(self) -> None:
        """Wait every collective issued this step before voting: a failure
        landing after the vote would otherwise let this replica commit with
        its own unaveraged gradients (error-funnel substitution) while peers
        commit averaged ones — silent cross-replica divergence.  Analog of
        the reference's stream synchronize (``manager.py:888-893``)."""
        import time as _time

        with self._pending_works_lock:
            pending, self._pending_works = self._pending_works, []
        deadline = _time.monotonic() + self._timeout  # one shared budget
        for work in pending:
            try:
                # errors are already swallowed by wrap_work / the funnel;
                # only a genuine stall can raise (TimeoutError) here
                work.wait(timeout=max(0.0, deadline - _time.monotonic()))
            except Exception as e:  # noqa: BLE001
                self.report_error(e)

    # ------------------------------------------------------------------
    # commit
    # ------------------------------------------------------------------

    @traced("torchft::manager::should_commit")
    def should_commit(self, timeout: Optional[float] = None) -> bool:
        """Vote on committing this step (``manager.py:855-943``)."""
        # the vote depends on this step's quorum results (participation
        # facts, healing state) — wait it even if no allreduce ran this step
        # (e.g. a protocol-only or fully-quantized step); otherwise the vote
        # can read a stale participant count and spuriously fail.  A quorum
        # failure becomes a False vote (absorbed by the commit_failures /
        # max_retries path), not an exception out of the train loop —
        # calling without start_quorum at all is still a loud error (a real
        # raise, not ``assert`` — that would vanish under ``python -O``)
        if self._quorum_future is None:
            raise RuntimeError(
                "must call start_quorum before should_commit"
            )
        try:
            self.wait_quorum()
        except Exception as e:  # noqa: BLE001 — funnel, never raise
            self.report_error(e)
        # fence all in-flight collectives, then recovery, before voting
        with obs_span("manager::fence", step=self._step):
            self._fence_pending_works()
            if self._recovery_event is not None:
                self._recovery_event.synchronize(timeout=self._timeout)
                self._recovery_event = None
        self._flight.record(FlightEvent.COMMIT_FENCE, step=self._step)

        if (err := self._comm.errored()) is not None:
            self.report_error(err)

        if self._healing:
            self._apply_pending_state_dict()

        if self._relower_pending:
            # degraded re-lower in flight: inner state is mid-transition
            # between device layouts — committing now would fork this
            # replica from the fleet (and a crash here must read as "never
            # voted commit", which funneling to a False vote guarantees)
            self.report_error(
                RuntimeError(
                    "degraded re-lower in progress; refusing to commit a "
                    "half-relowered step"
                )
            )

        if stale_frags := self.stream_unresolved():
            # stream fence (the begin_relower pattern): a streamed fragment
            # sync whose collectives are still in flight at a vote means
            # the protocol was violated (the scheduler waits the work at
            # its staleness barrier before voting) — committing would let
            # this replica adopt a half-streamed delta later while peers
            # may have discarded it.  Force the vote False.
            self.report_error(
                RuntimeError(
                    f"streamed fragment sync(s) {stale_frags} still in "
                    "flight at the commit vote; refusing to commit a "
                    "half-streamed sync"
                )
            )

        enough_replicas = self.num_participants() >= self._min_replica_size
        local_should_commit = enough_replicas and self._errored is None
        self._flight.record(
            FlightEvent.COMMIT_VOTE, step=self._step, local=local_should_commit
        )
        with obs_span("manager::should_commit", step=self._step):
            should_commit = self._client.should_commit(
                self._group_rank,
                self._step,
                local_should_commit,
                timeout=timeout or self._timeout,
            )
        self._flight.record(
            FlightEvent.COMMIT_RESULT,
            step=self._step,
            committed=should_commit,
        )
        self._logger.info(
            f"should_commit={should_commit} enough_replicas={enough_replicas}, "
            f"errored={self._errored}"
        )

        self.commits_logger.info(
            "",
            extra={
                "job_id": os.environ.get("JOB_ID", "unknown"),
                "replica_id": self._replica_id,
                "rank": self._group_rank,
                "quorum_id": self._quorum_id,
                "step": self._step,
                "commit_result": should_commit,
            },
        )

        self._checkpoint_transport.disallow_checkpoint()

        if should_commit:
            # single-writer by protocol: wait_quorum() above joined the
            # quorum future, so the quorum thread's `_step = max_step` has
            # a happens-before edge to this train-thread increment, and no
            # new quorum starts until the train loop calls start_quorum
            # ftlint: ignore[thread-safety] — ordered by wait_quorum join
            self._step += 1
            # ftlint: ignore[thread-safety] — ordered by wait_quorum join
            self._batches_committed += self.num_participants()
            self._commit_failures = 0
        else:
            self._commit_failures += 1
            if (
                self._max_retries is not None
                and self._commit_failures > self._max_retries
            ):
                msg = (
                    f"should_commit failed {self._commit_failures} times "
                    f"consecutively, exceeding max_retries={self._max_retries}"
                )
                self._logger.exception(msg)
                raise RuntimeError(msg)
        return should_commit

    # ------------------------------------------------------------------
    # participation facts
    # ------------------------------------------------------------------

    def is_participating(self) -> bool:
        """False while healing (async quorum) or parked as a spare
        (``manager.py:1003-1020``)."""
        if self._participating_replica_rank is None:
            return False
        if self._healing:
            assert self._use_async_quorum
            return False
        return True

    def num_participants(self) -> int:
        assert self._participating_replica_world_size >= 0, "internal error"
        return self._participating_replica_world_size

    def participating_rank(self) -> Optional[int]:
        assert self._quorum_future is not None, "must call start_quorum before"
        self._quorum_future.result()
        return self._participating_replica_rank

    def current_step(self) -> int:
        """Current step count; incremented only on committed steps
        (``manager.py:1030-1040``)."""
        return self._step

    def batches_committed(self) -> int:
        return self._batches_committed

    @property
    def replica_id(self) -> str:
        return self._replica_id

    # ------------------------------------------------------------------

    def shutdown(self) -> None:
        self._tracer.stop()  # flush the final quorum epoch's trace
        if flight_dir():
            # the final complete ring (atexit's analog for in-process
            # replicas — a thread-plane victim's dump survives its death)
            try:
                self._flight.dump("shutdown")
            except OSError:
                pass
        self._checkpoint_transport.shutdown(wait=False)
        if self._quorum_future is not None:
            try:
                self._quorum_future.result(timeout=1.0)
            except Exception:  # noqa: BLE001
                pass
        self._executor.shutdown(wait=False)
        if self._manager_server is not None:
            self._manager_server.shutdown()
        if self._store is not None:
            self._store.close()
        if self._own_store is not None:
            self._own_store.shutdown()
        self._comm.shutdown()

    # test-friendly logger attribute (mocked-client path sets it lazily)
    @property
    def _logger(self) -> "_ManagerLogger":
        if not hasattr(self, "_logger_obj"):
            self._logger_obj = _ManagerLogger(
                self, getattr(self, "_replica_id", "?"), self._group_rank
            )
        return self._logger_obj

    @_logger.setter
    def _logger(self, value: "_ManagerLogger") -> None:
        self._logger_obj = value


def _scale_contribution(
    data: Union[np.ndarray, List[np.ndarray]], scale: float
) -> Union[np.ndarray, List[np.ndarray]]:
    """Out-of-place capacity-weight pre-scale of a gradient contribution
    (same dtype-preservation contract as :func:`_div`; integer arrays pass
    through unscaled — fractional weights would floor them to noise)."""

    def _one(a: np.ndarray) -> np.ndarray:
        if np.issubdtype(a.dtype, np.integer):
            return a
        return (a * scale).astype(a.dtype)

    if isinstance(data, np.ndarray):
        return _one(data)
    return [_one(a) for a in data]


def _div(a: np.ndarray, n: int) -> np.ndarray:
    # Always out-of-place: the communicator may return the caller's own
    # buffer aliased (DummyCommunicator passthrough), and mutating it would
    # silently corrupt a retained gradient. Integer grads floor-divide;
    # everything else (incl. extension float dtypes like bfloat16, which are
    # NOT np.inexact subdtypes) true-divides.
    if np.issubdtype(a.dtype, np.integer):
        return a // n
    return (a / n).astype(a.dtype)


class _ManagerLogger:
    """Prefixes ``[replica/rank - step N]`` (``manager.py:1056-1073``)."""

    def __init__(self, manager: Manager, replica_id: str, group_rank: int) -> None:
        self._logger = logging.getLogger(__name__)
        self._replica_id = replica_id
        self._group_rank = group_rank
        self._manager = manager

    def _prefix(self) -> str:
        return (
            f"[{self._replica_id}/{self._group_rank} - "
            f"step {self._manager.current_step()}]"
        )

    def info(self, msg: str) -> None:
        self._logger.info(f"{self._prefix()} {msg}")

    def warn(self, msg: str) -> None:
        self._logger.warning(f"{self._prefix()} {msg}")

    def exception(self, msg: str) -> None:
        self._logger.exception(f"{self._prefix()} {msg}")
