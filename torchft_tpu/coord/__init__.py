"""Hierarchical coordination plane (wire v4).

At the scale argued by the 100k-GPU HSDP report and SPARe (PAPERS.md), the
flat control plane — every replica (and spare) heartbeating one lighthouse,
every quorum broadcast carrying full membership, every status poll
recomputing fleet state — becomes the bottleneck long before the data plane
does.  This package is the aggregation tier that fixes all three:

- :class:`ZoneAggregator` — a per-host/per-zone process that batches member
  heartbeats (with their ``CommHealth`` summaries and spare warm-progress)
  into ONE upstream ``LH_AGG_BEAT`` RPC per flush tick.  The control-plane
  analog of the PR-3 host-leader abstraction: members talk to a local
  leader, only leaders talk upstream.
- :class:`AggMemberClient` — the member side: managers route their beats
  through a discovered aggregator (``TORCHFT_AGG_ADDR``) and fall back to
  direct lighthouse beats on aggregator death.
- :mod:`torchft_tpu.coord.scale` — the thread-plane scale harness: 500–1000
  simulated replicas plus a spare pool driven through quorum/kill/rejoin/
  promote churn, reporting p99 quorum latency, lighthouse CPU, and the
  lighthouse-inbound RPC reduction vs direct heartbeats.

The lighthouse side (accepting aggregated beats, the aggregator-death
reporting-gap grace, delta-coded quorum broadcasts, the TTL-cached /status
snapshot) lives in ``lighthouse.py``/``wire.py``; see docs/operations.md
§15 for the runbook.
"""

from torchft_tpu.coord.aggregator import AggMemberClient, ZoneAggregator

__all__ = ["AggMemberClient", "ZoneAggregator"]
