"""Thread-plane coordination scale harness: 500–1000 simulated replicas.

Drives a real lighthouse (by default in a SUBPROCESS, so its CPU burn is
measurable in isolation via /proc) with hundreds of simulated replicas:
each is one thread running the manager-shaped control loop — park on the
quorum RPC, re-register on every broadcast with an advancing step — while
per-zone beat pumps carry the fleet's heartbeats, either through real
:class:`ZoneAggregator` processes-worth of batching or directly, per
member.  A spare pool parks with ``ROLE_SPARE`` and follows the promotion
fast-path when the lighthouse moves one into the participant set.

What it measures (the ISSUE-12 acceptance surface):

- ``p99_quorum_latency_s`` — per-replica quorum RPC round-trip (request →
  broadcast received) through quorum/kill/rejoin/promote churn;
- ``lighthouse_cpu_frac`` — lighthouse-subprocess CPU seconds per wall
  second over the measured window (None when run in-process);
- ``rpc_reduction_vs_direct`` — lighthouse-inbound beat-RPC rate of an
  all-direct calibration window divided by the aggregated steady state
  (the >=10x gate at 500 replicas);
- ``spurious_membership_edits`` — observed ``quorum_id`` bumps minus the
  churn plan's expected edits (kills + rejoins; an aggregator bounce must
  contribute ZERO — aggregator death is a reporting gap, not member
  death).

Run it directly::

    python -m torchft_tpu.coord.scale --replicas 500 --aggregators 2

The CI smoke runs ~200 replicas under a hard time budget
(tests/test_coord.py); the 500–1000 sweep is the ``slow``-marked variant
and the bench phase (bench.py ``coord``).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from torchft_tpu import knobs
from torchft_tpu.coord.aggregator import AggMemberClient, ZoneAggregator
from torchft_tpu.lighthouse import LighthouseClient, LighthouseServer
from torchft_tpu.wire import ROLE_ACTIVE, ROLE_SPARE, WireError

logger = logging.getLogger(__name__)

_LH_SCRIPT = """\
import sys, time
from torchft_tpu.lighthouse import LighthouseServer
s = LighthouseServer(
    bind="127.0.0.1:0",
    min_replicas=int(sys.argv[1]),
    join_timeout_ms=int(sys.argv[2]),
    quorum_tick_ms=int(sys.argv[3]),
    heartbeat_timeout_ms=int(sys.argv[4]),
)
print("PORT", s.port, flush=True)
while True:
    time.sleep(3600)
"""


def _proc_cpu_seconds(pid: int) -> Optional[float]:
    """utime+stime of one pid in seconds (Linux /proc; None elsewhere)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            raw = f.read()
        # comm may contain spaces/parens: fields restart after the last ')'
        rest = raw[raw.rindex(")") + 2 :].split()
        utime, stime = int(rest[11]), int(rest[12])
        return (utime + stime) / os.sysconf("SC_CLK_TCK")
    except (OSError, ValueError, IndexError):
        return None


class _Lighthouse:
    """A lighthouse either as a subprocess (CPU-measurable) or in-proc."""

    def __init__(
        self,
        min_replicas: int,
        join_timeout_ms: int,
        tick_ms: int,
        hb_timeout_ms: int,
        subprocess_mode: bool,
    ) -> None:
        self.proc: Optional[subprocess.Popen] = None
        self.server: Optional[LighthouseServer] = None
        if subprocess_mode:
            repo_root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            env = dict(os.environ)
            env["PYTHONPATH"] = (
                repo_root + os.pathsep + env.get("PYTHONPATH", "")
            )
            self.proc = subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    _LH_SCRIPT,
                    str(min_replicas),
                    str(join_timeout_ms),
                    str(tick_ms),
                    str(hb_timeout_ms),
                ],
                stdout=subprocess.PIPE,
                env=env,
                text=True,
            )
            assert self.proc.stdout is not None
            line = self.proc.stdout.readline()
            if not line.startswith("PORT "):
                raise RuntimeError(
                    f"lighthouse subprocess failed to start: {line!r}"
                )
            self.port = int(line.split()[1])
        else:
            self.server = LighthouseServer(
                bind="127.0.0.1:0",
                min_replicas=min_replicas,
                join_timeout_ms=join_timeout_ms,
                quorum_tick_ms=tick_ms,
                heartbeat_timeout_ms=hb_timeout_ms,
            )
            self.port = self.server.port
        self.addr = f"127.0.0.1:{self.port}"

    def cpu_seconds(self) -> Optional[float]:
        if self.proc is not None:
            return _proc_cpu_seconds(self.proc.pid)
        return None

    def shutdown(self) -> None:
        if self.proc is not None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if self.server is not None:
            self.server.shutdown()


@dataclass
class _SimReplica:
    """One simulated replica: the manager-shaped quorum loop in a thread.
    Heartbeats are carried by the zone's beat pump, not this thread."""

    rid: str
    role: int = ROLE_ACTIVE
    alive: bool = True
    step: int = 0
    warm_step: int = 0
    promoted: bool = False
    latencies: List[float] = field(default_factory=list)
    thread: Optional[threading.Thread] = None
    client: Optional[LighthouseClient] = None

    def kill(self) -> None:
        self.alive = False
        client = self.client
        if client is not None:
            client.interrupt()


class _BeatPump(threading.Thread):
    """Carries heartbeats for a zone's members at a fixed cadence.  One
    pump thread stands in for its members' heartbeat threads — the WIRE
    traffic (one AGG_BEAT or LH_HEARTBEAT frame per member per interval)
    is exactly what per-member threads would produce, which is what the
    lighthouse-inbound measurement cares about.  Implements the same
    fall-back-to-direct-on-aggregator-death policy as
    ``manager_server._run_heartbeat``."""

    def __init__(
        self,
        name: str,
        members: List[_SimReplica],
        lighthouse_addr: str,
        agg_addr: Optional[str],
        interval_s: float,
        stop: threading.Event,
    ) -> None:
        super().__init__(name=f"tpuft_beat_pump_{name}", daemon=True)
        self.members = members
        self._lh_addr = lighthouse_addr
        self.agg_addr = agg_addr
        self._interval_s = interval_s
        self._halt = stop
        self.fallback_beats = 0
        self._agg_down_until = 0.0

    def run(self) -> None:
        agg_client: Optional[AggMemberClient] = None
        direct: Optional[LighthouseClient] = None
        while not self._halt.is_set():
            t0 = time.monotonic()
            for m in list(self.members):
                if not m.alive or self._halt.is_set():
                    continue
                warm = m.warm_step if m.role == ROLE_SPARE else -1
                agg_addr = self.agg_addr
                if (
                    agg_addr is not None
                    and time.monotonic() >= self._agg_down_until
                ):
                    try:
                        if agg_client is None or agg_client.addr != agg_addr:
                            if agg_client is not None:
                                agg_client.close()
                            agg_client = AggMemberClient(
                                agg_addr, connect_timeout=5.0
                            )
                        resp = agg_client.beat(
                            m.rid, role=m.role, warm_step=warm
                        )
                        if resp["upstream_ok"]:
                            continue
                        # aggregator up but its upstream flushes failing:
                        # same policy as the manager — beat direct instead
                    except (OSError, TimeoutError, WireError):
                        # dead aggregator: one failed dial per cooloff, not
                        # one per member per sweep — the rest of this sweep
                        # (and sweeps until the cooloff expires) go direct
                        if agg_client is not None:
                            agg_client.close()
                        agg_client = None
                        self.fallback_beats += 1
                        self._agg_down_until = (
                            time.monotonic()
                            + knobs.get_float("TORCHFT_AGG_RETRY_S", 2.0)
                        )
                try:
                    if direct is None:
                        direct = LighthouseClient(
                            self._lh_addr, connect_timeout=5.0
                        )
                    direct.heartbeat(
                        m.rid, warm_step=warm if warm >= 0 else None
                    )
                except (OSError, TimeoutError, WireError):
                    if direct is not None:
                        direct.close()
                    direct = None
            self._halt.wait(
                max(0.0, self._interval_s - (time.monotonic() - t0))
            )
        for c in (agg_client, direct):
            if c is not None:
                c.close()


def _quorum_loop(
    replica: _SimReplica,
    lighthouse_addr: str,
    stop: threading.Event,
    rpc_timeout_s: float,
    round_pause_s: float,
) -> None:
    client = LighthouseClient(lighthouse_addr, connect_timeout=10.0)
    replica.client = client
    try:
        while not stop.is_set() and replica.alive:
            t0 = time.monotonic()
            try:
                quorum = client.quorum(
                    replica_id=replica.rid,
                    timeout=rpc_timeout_s,
                    address=f"sim://{replica.rid}",
                    store_address=f"sim-store://{replica.rid}",
                    step=replica.step,
                    role=replica.role,
                )
            except TimeoutError:
                continue
            except (ConnectionError, OSError, WireError):
                if stop.is_set() or not replica.alive:
                    return
                time.sleep(0.05)
                continue
            dt = time.monotonic() - t0
            in_quorum = any(
                p.replica_id == replica.rid for p in quorum.participants
            )
            max_step = max(
                (p.step for p in quorum.participants), default=0
            )
            if in_quorum:
                replica.latencies.append(dt)
                if replica.role == ROLE_SPARE:
                    # promotion fast-path landed: from here on this
                    # replica registers as an ordinary active
                    replica.role = ROLE_ACTIVE
                    replica.promoted = True
                # advance the commit front like a training step would
                replica.step = max(replica.step, max_step) + 1
            else:
                # parked spare: track the commit front as its warm
                # watermark (rides the beat pump to the lighthouse)
                replica.warm_step = max_step
            if round_pause_s > 0:
                stop.wait(round_pause_s)
    finally:
        client.close()


def _percentile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))
    return ordered[idx]


def _beat_rpc_sample(status: dict) -> tuple:
    """(inbound beat RPC total, snapshot clock).  Rates difference against
    the snapshot's OWN rebuild time — status is TTL-cached, so the poll
    time would over/under-state the window by up to one TTL."""
    counts = status.get("rpc_counts", {})
    total = int(counts.get("LH_HEARTBEAT_REQ", 0)) + int(
        counts.get("LH_AGG_BEAT_REQ", 0)
    )
    return total, float(status.get("now_monotonic", 0.0))


def run_scale_harness(
    num_replicas: int = 500,
    num_aggregators: int = 2,
    num_spares: int = 4,
    direct_fraction: float = 0.05,
    kills: int = 2,
    rejoins: int = 1,
    agg_bounce: bool = True,
    beat_interval_s: float = 0.25,
    round_pause_s: Optional[float] = None,
    calibrate_direct_s: float = 1.5,
    steady_s: float = 2.5,
    hb_timeout_ms: int = 2000,
    tick_ms: int = 50,
    join_timeout_ms: int = 1000,
    rpc_timeout_s: float = 15.0,
    lighthouse_subprocess: bool = True,
    deadline_s: float = 180.0,
) -> Dict[str, object]:
    """Run the full churn scenario; returns the metrics dict (see module
    docstring).  Raises AssertionError when an invariant breaks (spurious
    membership edits, promotions that never landed, fleet that never
    converged)."""
    t_start = time.monotonic()
    deadline = t_start + deadline_s
    if round_pause_s is None:
        # self-pace the quorum storm with fleet size: the harness hosts
        # every simulated replica in ONE process, so per-round client-side
        # work is O(replicas^2) and an unpaced storm would starve the
        # measurement at the top of the range
        round_pause_s = max(0.05, num_replicas / 4000.0)
    # same single-process reality for liveness: hundreds of sim threads
    # share one GIL with the beat pumps, so scheduler starvation can
    # stretch a pump sweep well past a bound sized for real fleets —
    # scale the heartbeat verdict with the thread count
    hb_timeout_ms = max(hb_timeout_ms, num_replicas * 10)
    stop = threading.Event()
    lighthouse = _Lighthouse(
        min_replicas=max(1, num_replicas // 2),
        join_timeout_ms=join_timeout_ms,
        tick_ms=tick_ms,
        hb_timeout_ms=hb_timeout_ms,
        subprocess_mode=lighthouse_subprocess,
    )
    status_client = LighthouseClient(lighthouse.addr, connect_timeout=10.0)
    aggregators: List[ZoneAggregator] = []
    pumps: List[_BeatPump] = []
    report: Dict[str, object] = {
        "replicas": num_replicas,
        "aggregators": num_aggregators,
        "spares": num_spares,
        "direct_fraction": direct_fraction,
    }

    def remaining() -> float:
        return deadline - time.monotonic()

    def wait_status(pred, what: str, budget_s: float = 30.0) -> dict:
        end = time.monotonic() + min(budget_s, max(1.0, remaining()))
        status = {}
        while time.monotonic() < end:
            try:
                status = status_client.status(timeout=5.0)
            except (OSError, TimeoutError, WireError):
                time.sleep(0.2)
                continue
            if pred(status):
                return status
            time.sleep(0.1)
        raise AssertionError(f"scale harness: {what} (last status {status})")

    actives = [
        _SimReplica(rid=f"sim_{i:04d}") for i in range(num_replicas)
    ]
    spares = [
        _SimReplica(rid=f"sim_spare_{i:02d}", role=ROLE_SPARE)
        for i in range(num_spares)
    ]
    n_direct = max(0, int(num_replicas * direct_fraction))

    try:
        # -- phase 1: all-direct calibration window -----------------------
        # every member beats the lighthouse directly; the measured beat-RPC
        # rate is the flat-control-plane baseline the aggregation win is
        # quoted against
        calib_pump = _BeatPump(
            "calib",
            actives + spares,
            lighthouse.addr,
            agg_addr=None,
            interval_s=beat_interval_s,
            stop=stop,
        )
        before_n, before_t = _beat_rpc_sample(
            status_client.status(timeout=5.0)
        )
        calib_pump.start()
        time.sleep(max(0.5, calibrate_direct_s))
        after_n, after_t = _beat_rpc_sample(status_client.status(timeout=5.0))
        if after_t <= before_t:  # same cached snapshot: outwait the TTL
            time.sleep(knobs.get_float("TORCHFT_STATUS_TTL_S", 0.5) + 0.1)
            after_n, after_t = _beat_rpc_sample(
                status_client.status(timeout=5.0)
            )
        direct_rate = (after_n - before_n) / max(1e-3, after_t - before_t)
        report["direct_beat_rpcs_per_s"] = round(direct_rate, 1)
        # retire the calibration pump (its Event is shared; use a fresh
        # stop for the real run)
        stop.set()
        calib_pump.join(timeout=10.0)
        stop = threading.Event()

        # -- phase 2: aggregated topology ---------------------------------
        for i in range(num_aggregators):
            aggregators.append(
                ZoneAggregator(
                    lighthouse.addr,
                    bind="127.0.0.1:0",
                    agg_id=f"zone_{i}",
                )
            )
        # mixed fleet: the first n_direct actives beat direct forever; the
        # rest (and every spare) ride their zone's aggregator
        zones: List[List[_SimReplica]] = [[] for _ in aggregators]
        for j, m in enumerate(actives[n_direct:] + spares):
            zones[j % len(zones)].append(m)
        for i, zone in enumerate(zones):
            pumps.append(
                _BeatPump(
                    f"zone{i}",
                    zone,
                    lighthouse.addr,
                    agg_addr=aggregators[i].local_address(),
                    interval_s=beat_interval_s,
                    stop=stop,
                )
            )
        if n_direct:
            pumps.append(
                _BeatPump(
                    "direct",
                    actives[:n_direct],
                    lighthouse.addr,
                    agg_addr=None,
                    interval_s=beat_interval_s,
                    stop=stop,
                )
            )
        for p in pumps:
            p.start()

        # -- phase 3: fleet convergence -----------------------------------
        for m in actives + spares:
            m.thread = threading.Thread(
                target=_quorum_loop,
                args=(m, lighthouse.addr, stop, rpc_timeout_s, round_pause_s),
                name=f"tpuft_sim_{m.rid}",
                daemon=True,
            )
            m.thread.start()
        status = wait_status(
            lambda s: s.get("num_participants") == num_replicas,
            f"fleet never converged to {num_replicas} participants",
            budget_s=60.0,
        )
        qid_converged = int(status["quorum_id"])
        report["converge_s"] = round(time.monotonic() - t_start, 2)

        # -- phase 4: steady-state measurement ----------------------------
        cpu0 = lighthouse.cpu_seconds()
        before_n, before_t = _beat_rpc_sample(
            status_client.status(timeout=5.0)
        )
        t_steady = time.monotonic()
        time.sleep(max(0.5, steady_s))
        after_n, after_t = _beat_rpc_sample(status_client.status(timeout=5.0))
        if after_t <= before_t:
            time.sleep(knobs.get_float("TORCHFT_STATUS_TTL_S", 0.5) + 0.1)
            after_n, after_t = _beat_rpc_sample(
                status_client.status(timeout=5.0)
            )
        agg_rate = (after_n - before_n) / max(1e-3, after_t - before_t)
        report["agg_beat_rpcs_per_s"] = round(agg_rate, 1)
        report["rpc_reduction_vs_direct"] = (
            round(direct_rate / agg_rate, 1) if agg_rate > 0 else None
        )

        # -- phase 5: churn -----------------------------------------------
        expected_edits = 0
        promoted_expected = 0
        killed: List[_SimReplica] = []
        live_spares = num_spares
        for k in range(kills):
            victim = actives[-(1 + k)]
            victim.kill()
            killed.append(victim)
            expected_edits += 1
            if live_spares > 0:
                live_spares -= 1
                promoted_expected += 1
            wait_status(
                lambda s: s.get("num_participants")
                == num_replicas - len(killed) + promoted_expected
                and int(s.get("promotions_total", 0)) >= promoted_expected,
                f"membership never settled after kill #{k + 1}",
                budget_s=45.0,
            )
        if rejoins:
            for j in range(min(rejoins, len(killed))):
                reborn = _SimReplica(rid=f"sim_rejoin_{j:02d}")
                actives.append(reborn)
                zones[j % len(zones)].append(reborn)
                expected_edits += 1
                reborn.thread = threading.Thread(
                    target=_quorum_loop,
                    args=(
                        reborn,
                        lighthouse.addr,
                        stop,
                        rpc_timeout_s,
                        round_pause_s,
                    ),
                    name=f"tpuft_sim_{reborn.rid}",
                    daemon=True,
                )
                reborn.thread.start()
            expected_participants = (
                num_replicas - len(killed) + promoted_expected + rejoins
            )
            wait_status(
                lambda s: s.get("num_participants") == expected_participants,
                "rejoin never landed",
                budget_s=45.0,
            )

        # -- phase 6: aggregator bounce (the reporting-gap proof) ---------
        if agg_bounce and aggregators:
            pre = status_client.status(timeout=5.0)
            qid_pre_bounce = int(pre["quorum_id"])
            bounced = aggregators[0]
            bounced.shutdown()
            # longer than the aggregator-death bound, shorter than the
            # member grace: pumps fall back to direct beats meanwhile
            agg_timeout_s = knobs.get_float("TORCHFT_AGG_TIMEOUT_S", 1.0)
            time.sleep(agg_timeout_s + 1.0)
            replacement = ZoneAggregator(
                lighthouse.addr, bind="127.0.0.1:0", agg_id="zone_0_reborn"
            )
            aggregators.append(replacement)
            for p in pumps:
                if p.agg_addr == bounced.local_address():
                    p.agg_addr = replacement.local_address()
            time.sleep(1.0)
            post = status_client.status(timeout=5.0)
            qid_post_bounce = int(post["quorum_id"])
            report["agg_bounce_edits"] = qid_post_bounce - qid_pre_bounce
            assert qid_post_bounce == qid_pre_bounce, (
                f"aggregator bounce cost {qid_post_bounce - qid_pre_bounce} "
                "membership edit(s) — aggregator death must be a reporting "
                "gap, not a member death"
            )
            report["pump_fallback_beats"] = sum(
                p.fallback_beats for p in pumps
            )

        # -- phase 7: final accounting ------------------------------------
        cpu1 = lighthouse.cpu_seconds()
        final = status_client.status(timeout=5.0)
        qid_final = int(final["quorum_id"])
        observed_edits = qid_final - qid_converged
        report["quorum_id_final"] = qid_final
        report["expected_membership_edits"] = expected_edits
        report["observed_membership_edits"] = observed_edits
        report["spurious_membership_edits"] = observed_edits - expected_edits
        report["promotions_total"] = int(final.get("promotions_total", 0))
        report["promoted_spares"] = sum(1 for s in spares if s.promoted)
        all_latencies = [
            lat for m in actives + spares for lat in m.latencies
        ]
        report["quorum_rounds_observed"] = len(all_latencies)
        report["p50_quorum_latency_s"] = _percentile(all_latencies, 0.50)
        report["p99_quorum_latency_s"] = _percentile(all_latencies, 0.99)
        if cpu0 is not None and cpu1 is not None:
            wall = time.monotonic() - t_steady
            report["lighthouse_cpu_frac"] = round(
                max(0.0, cpu1 - cpu0) / wall, 3
            )
        else:
            report["lighthouse_cpu_frac"] = None
        report["status_rebuilds"] = int(final.get("status_rebuilds", 0))
        report["wall_s"] = round(time.monotonic() - t_start, 2)
        assert report["promotions_total"] >= promoted_expected, report
        assert observed_edits == expected_edits, (
            f"spurious membership edits: expected {expected_edits} "
            f"(kills+rejoins), observed {observed_edits} — {report}"
        )
        return report
    finally:
        stop.set()
        for m in actives + spares:
            m.alive = False
            if m.client is not None:
                m.client.interrupt()
        for m in actives + spares:
            if m.thread is not None:
                m.thread.join(timeout=5.0)
        for p in pumps:
            p.join(timeout=5.0)
        for agg in aggregators:
            agg.shutdown()
        status_client.close()
        lighthouse.shutdown()


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser("torchft_tpu coordination scale harness")
    parser.add_argument("--replicas", type=int, default=500)
    parser.add_argument("--aggregators", type=int, default=2)
    parser.add_argument("--spares", type=int, default=4)
    parser.add_argument("--kills", type=int, default=2)
    parser.add_argument("--rejoins", type=int, default=1)
    parser.add_argument("--no-agg-bounce", action="store_true")
    parser.add_argument("--deadline-s", type=float, default=180.0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    report = run_scale_harness(
        num_replicas=args.replicas,
        num_aggregators=args.aggregators,
        num_spares=args.spares,
        kills=args.kills,
        rejoins=args.rejoins,
        agg_bounce=not args.no_agg_bounce,
        deadline_s=args.deadline_s,
    )
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
