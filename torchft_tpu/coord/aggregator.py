"""Zone aggregator: batch member heartbeats into one upstream RPC per tick.

One :class:`ZoneAggregator` runs per host or failure zone.  Members send
their ordinary heartbeats (replica id, role, spare warm-step, cumulative
``CommHealth``) to the aggregator over ``AGG_BEAT`` frames at their normal
cadence; the aggregator keeps only the LATEST beat per member and flushes
the whole batch upstream as a single ``LH_AGG_BEAT`` RPC every
``TORCHFT_AGG_FLUSH_MS`` — so the lighthouse-inbound RPC rate is
``aggregators / flush_interval`` instead of ``members / beat_interval``
(~50x lower at 500 members, 2 zones, defaults).

Failure semantics (the load-bearing part):

- **Aggregator death is a reporting gap, not a member death.**  The
  lighthouse tracks which aggregator last reported each member; when that
  aggregator's own flushes stop, affected members get a bounded extra
  grace window (``TORCHFT_AGG_GRACE_S``) before the heartbeat verdict
  applies — enough for their managers to notice the dead aggregator and
  fall back to direct beats (``manager_server._run_heartbeat``).  A member
  that stays silent past the grace is genuinely dead.
- **Upstream state rides the member response.**  Each ``AGG_BEAT_RESP``
  carries whether the aggregator's last upstream flush succeeded plus a
  lighthouse-restart counter (success-after-failure transitions), so a
  member beating via the aggregator still learns about lighthouse bounces
  and can interrupt its parked quorum RPC exactly like the direct path.
- **The aggregator holds no quorum state.**  Crash/restart loses nothing
  but a flush tick; members re-route or fall back within a beat interval.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import uuid
from typing import Dict, Optional

from torchft_tpu import knobs
from torchft_tpu.wire import (
    AggBeat,
    CommHealth,
    ErrCode,
    MemberBeat,
    MsgType,
    ROLE_ACTIVE,
    RpcClient,
    WireError,
    Writer,
    configure_server_socket,
    create_listener,
    raise_if_error,
    recv_frame,
    send_error,
    send_frame,
)

logger = logging.getLogger(__name__)

AGG_ADDR_ENV = "TORCHFT_AGG_ADDR"
AGG_FLUSH_MS_ENV = "TORCHFT_AGG_FLUSH_MS"  # default 100
AGG_RETRY_S_ENV = "TORCHFT_AGG_RETRY_S"  # default 2.0


class ZoneAggregator:
    """Threaded per-zone heartbeat aggregator (see module docstring)."""

    def __init__(
        self,
        lighthouse_addr: str,
        bind: str = "0.0.0.0:0",
        agg_id: Optional[str] = None,
        flush_interval_s: Optional[float] = None,
        connect_timeout: float = 10.0,
    ) -> None:
        self._lighthouse_addr = lighthouse_addr
        self._agg_id = agg_id or (
            f"agg_{socket.gethostname()}_{uuid.uuid4().hex[:8]}"
        )
        self._flush_interval_s = flush_interval_s
        self._connect_timeout = connect_timeout

        self._lock = threading.Lock()
        # latest beat per member since the last flush
        self._pending: Dict[str, MemberBeat] = {}
        # upstream link state, mirrored into every member response
        self._upstream_failures = 0
        self._lh_restarts = 0
        self._upstream_ok = False
        # cumulative observability
        self.beats_in = 0
        self.flushes = 0
        self.flush_errors = 0
        self.members_seen: set = set()

        self._shutdown = False
        self._upstream: Optional[RpcClient] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()

        self._sock = create_listener(bind, backlog=512)
        self._port: int = self._sock.getsockname()[1]
        threading.Thread(
            target=self._serve, name="tpuft_agg_accept", daemon=True
        ).start()
        threading.Thread(
            target=self._run_flush, name="tpuft_agg_flush", daemon=True
        ).start()
        logger.info(
            "ZoneAggregator %s listening on %s (upstream %s)",
            self._agg_id,
            self.local_address(),
            lighthouse_addr,
        )

    # -- public -------------------------------------------------------------

    @property
    def port(self) -> int:
        return self._port

    @property
    def agg_id(self) -> str:
        return self._agg_id

    def address(self) -> str:
        return f"{socket.gethostname()}:{self._port}"

    def local_address(self) -> str:
        return f"127.0.0.1:{self._port}"

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        upstream = self._upstream
        if upstream is not None:
            upstream.close()

    # -- member side --------------------------------------------------------

    def _serve(self) -> None:
        while not self._shutdown:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            configure_server_socket(conn)
            with self._conns_lock:
                if self._shutdown:
                    conn.close()
                    continue
                self._conns.add(conn)
            threading.Thread(
                target=self._handle_conn,
                args=(conn,),
                name="tpuft_agg_conn",
                daemon=True,
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                msg_type, r = recv_frame(conn)
                if msg_type != MsgType.AGG_BEAT_REQ:
                    send_error(
                        conn, ErrCode.INVALID, f"bad aggregator op {msg_type}"
                    )
                    continue
                beat = MemberBeat.decode(r)
                with self._lock:
                    self._pending[beat.replica_id] = beat
                    self.beats_in += 1
                    self.members_seen.add(beat.replica_id)
                    ok, restarts = self._upstream_ok, self._lh_restarts
                w = Writer().boolean(ok).u64(restarts)
                send_frame(conn, MsgType.AGG_BEAT_RESP, w.payload())
        except (ConnectionError, OSError, WireError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    # -- upstream side ------------------------------------------------------

    def _flush_interval(self) -> float:
        if self._flush_interval_s is not None:
            return self._flush_interval_s
        return max(0.005, knobs.get_float(AGG_FLUSH_MS_ENV, 100.0) / 1000.0)

    def _run_flush(self) -> None:
        while not self._shutdown:
            time.sleep(self._flush_interval())
            self._flush_once()

    def _flush_once(self) -> None:
        """One upstream flush: ship every pending beat as a single RPC.
        An EMPTY flush still goes out — the flush itself is the
        aggregator's own liveness signal (``agg_last`` on the lighthouse),
        and a silent idle aggregator would look dead."""
        with self._lock:
            batch, self._pending = self._pending, {}
        agg = AggBeat(agg_id=self._agg_id, beats=list(batch.values()))
        w = Writer()
        agg.encode(w)
        try:
            if self._upstream is None:
                self._upstream = RpcClient(
                    self._lighthouse_addr,
                    connect_timeout=self._connect_timeout,
                )
            msg_type, r = self._upstream.call(
                MsgType.LH_AGG_BEAT_REQ, w.payload(), timeout=5.0
            )
            raise_if_error(msg_type, r)
            with self._lock:
                self.flushes += 1
                if self._upstream_failures:
                    # success after failure: the lighthouse (likely)
                    # restarted — members learn via the response counter
                    self._upstream_failures = 0
                    self._lh_restarts += 1
                self._upstream_ok = True
        except (OSError, TimeoutError, WireError) as e:
            logger.info(
                "aggregator %s upstream flush failed: %s", self._agg_id, e
            )
            with self._lock:
                self.flush_errors += 1
                self._upstream_failures += 1
                self._upstream_ok = False
                # re-queue the batch so the beats land on the next
                # successful flush instead of vanishing (newer beats win)
                merged = dict(batch)
                merged.update(self._pending)
                self._pending = merged
            upstream = self._upstream
            self._upstream = None
            if upstream is not None:
                upstream.close()


class AggMemberClient(RpcClient):
    """Member-side client for one :class:`ZoneAggregator`.  ``beat``
    returns the aggregator's upstream view so callers can mirror the
    direct path's lighthouse-restart detection."""

    def __init__(self, addr: str, connect_timeout: float = 10.0) -> None:
        super().__init__(addr, connect_timeout=connect_timeout)

    def beat(
        self,
        replica_id: str,
        role: int = ROLE_ACTIVE,
        warm_step: int = -1,
        health: Optional[CommHealth] = None,
        timeout: float = 5.0,
    ) -> Dict[str, object]:
        w = Writer()
        MemberBeat(
            replica_id=replica_id,
            role=role,
            warm_step=warm_step,
            health=health,
        ).encode(w)
        msg_type, r = self.call(
            MsgType.AGG_BEAT_REQ, w.payload(), timeout, idempotent=True
        )
        raise_if_error(msg_type, r)
        return {"upstream_ok": r.boolean(), "lh_restarts": r.u64()}
