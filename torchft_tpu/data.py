"""Fault-tolerant data sharding.

Twin of the reference sampler (``torchft/data.py:24-77``): the dataset is
sharded across ``num_replica_groups × num_workers_per_group`` shards and this
worker reads shard ``global_rank = group_rank + num_workers * replica_rank``.
Same documented-lossy semantics: when the replica count changes, workers keep
their shard assignment from construction time; exactly-once delivery across
failures is explicitly out of scope (steps, not samples, are the unit of
fault tolerance).

Index-based (grain-style) rather than iterator-based: ``__getitem__`` of any
random-access dataset composes with it.

Degraded-mode rescale (wire v5): when the fleet carries wounded replicas,
``capacities`` (or :meth:`DistributedSampler.set_capacities`) switches the
sampler to capacity-PROPORTIONAL shards — a replica running at 0.75 of its
devices reads ~0.75 of an even share, apportioned deterministically by
largest remainder (:func:`capacity_shard_counts`) so every replica derives
the identical partition from the identical quorum facts.  The capacity path
uses contiguous block partitioning (counts differ per replica, so the
legacy stride is inapplicable); ``capacities=None`` keeps the legacy
strided layout bit-for-bit.  Capacity restored mid-run is just
``set_capacities`` again: the next ``indices()`` call rebalances.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np


def capacity_shard_counts(total: int, capacities: Sequence[float]) -> List[int]:
    """Apportion ``total`` samples across replicas proportionally to their
    capacity fractions, deterministically (largest-remainder method, ties
    to the lowest replica index).  Pure function of its inputs — every
    replica computes the identical split from the identical quorum
    capacities, including when the fractions don't divide the total.

    Zero/negative capacities get zero samples; an all-zero (or empty)
    capacity vector falls back to an even split so a pathological quorum
    can never starve the whole fleet."""
    n = len(capacities)
    if n == 0:
        return []
    weights = np.asarray(
        [max(0.0, float(c)) for c in capacities], dtype=np.float64
    )
    if weights.sum() <= 0.0:
        weights = np.ones(n, dtype=np.float64)
    shares = weights / weights.sum() * total
    counts = np.floor(shares).astype(np.int64)
    remainder = int(total - counts.sum())
    if remainder > 0:
        # largest fractional parts win the leftover samples; ties resolve
        # to the lowest replica index (argsort is stable on the negated
        # fractions)
        order = np.argsort(-(shares - counts), kind="stable")
        for idx in order[:remainder]:
            counts[idx] += 1
    return [int(c) for c in counts]


class DistributedSampler:
    """Shards a dataset across replica groups and their workers; this
    worker reads shard ``group_rank + num_workers * replica_rank``
    (``torchft/data.py:24-77`` semantics, documented-lossy on membership
    change).

    ``capacities`` (optional, one fraction per replica group in replica-
    rank order — i.e. aligned with the quorum's sorted replica ids, see
    ``Manager.participant_capacities``) engages the degraded-mode rescale
    described in the module docstring."""

    def __init__(
        self,
        dataset_len: int,
        replica_rank: int,
        num_replica_groups: int,
        group_rank: int = 0,
        num_workers_per_group: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        capacities: Optional[Sequence[float]] = None,
    ) -> None:
        self._dataset_len = dataset_len
        self._num_replica_groups = num_replica_groups
        self._replica_rank = replica_rank
        self._group_rank = group_rank
        self._num_workers = num_workers_per_group
        self._num_shards = num_replica_groups * num_workers_per_group
        self._global_rank = group_rank + num_workers_per_group * replica_rank
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        self._epoch = 0
        self._capacities: Optional[List[float]] = None
        self.set_capacities(capacities)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def set_capacities(self, capacities: Optional[Sequence[float]]) -> None:
        """Switch the shard layout to capacity-proportional apportionment
        (or back to the legacy even/strided layout with ``None``).  Takes
        effect on the next ``indices()`` call — capacity restored mid-run
        rebalances without reconstructing the sampler.  A full-capacity
        vector is normalized to ``None`` so an unwounded fleet stays on
        the legacy layout bit-for-bit."""
        if capacities is not None:
            if len(capacities) != self._num_replica_groups:
                raise ValueError(
                    f"capacities has {len(capacities)} entries for "
                    f"{self._num_replica_groups} replica groups"
                )
            if all(float(c) >= 1.0 for c in capacities):
                capacities = None
        self._capacities = (
            [float(c) for c in capacities] if capacities is not None else None
        )

    def _usable(self, order_len: int) -> int:
        return (order_len // self._num_shards) * self._num_shards

    @property
    def num_samples(self) -> int:
        if self._capacities is not None:
            order_len = self._dataset_len
            if not self._drop_last:
                order_len += (-order_len) % self._num_shards
            counts = capacity_shard_counts(
                self._usable(order_len), self._capacities
            )
            mine = counts[self._replica_rank]
            # workers split their replica's block evenly, remainder to the
            # low group ranks — same partition every replica derives
            per, extra = divmod(mine, self._num_workers)
            return per + (1 if self._group_rank < extra else 0)
        if self._drop_last:
            return self._dataset_len // self._num_shards
        return -(-self._dataset_len // self._num_shards)

    def __len__(self) -> int:
        return self.num_samples

    def indices(self) -> List[int]:
        order = np.arange(self._dataset_len)
        if self._shuffle:
            rng = np.random.default_rng(self._seed + self._epoch)
            rng.shuffle(order)
        if not self._drop_last:
            pad = (-len(order)) % self._num_shards
            if pad:
                order = np.concatenate([order, order[:pad]])
        usable = self._usable(len(order))
        if self._capacities is None:
            return list(order[self._global_rank : usable : self._num_shards])
        # capacity-proportional contiguous blocks: replica r owns
        # order[starts[r] : starts[r] + counts[r]], then its workers slice
        # that block evenly (remainder to the low ranks).  A partition —
        # never an overlap, never a dropped sample inside ``usable``.
        counts = capacity_shard_counts(usable, self._capacities)
        start = int(sum(counts[: self._replica_rank]))
        block = order[start : start + counts[self._replica_rank]]
        per, extra = divmod(len(block), self._num_workers)
        w_start = self._group_rank * per + min(self._group_rank, extra)
        w_len = per + (1 if self._group_rank < extra else 0)
        return list(block[w_start : w_start + w_len])

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices())


def batch_indices(
    sampler: DistributedSampler, batch_size: int, drop_last: bool = True
) -> Iterator[List[int]]:
    batch: List[int] = []
    for idx in sampler:
        batch.append(idx)
        if len(batch) == batch_size:
            yield batch
            batch = []
    if batch and not drop_last:
        yield batch
