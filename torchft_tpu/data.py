"""Fault-tolerant data sharding.

Twin of the reference sampler (``torchft/data.py:24-77``): the dataset is
sharded across ``num_replica_groups × num_workers_per_group`` shards and this
worker reads shard ``global_rank = group_rank + num_workers * replica_rank``.
Same documented-lossy semantics: when the replica count changes, workers keep
their shard assignment from construction time; exactly-once delivery across
failures is explicitly out of scope (steps, not samples, are the unit of
fault tolerance).

Index-based (grain-style) rather than iterator-based: ``__getitem__`` of any
random-access dataset composes with it.
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np


class DistributedSampler:
    """Shards a dataset across replica groups and their workers; this
    worker reads shard ``group_rank + num_workers * replica_rank``
    (``torchft/data.py:24-77`` semantics, documented-lossy on membership
    change)."""

    def __init__(
        self,
        dataset_len: int,
        replica_rank: int,
        num_replica_groups: int,
        group_rank: int = 0,
        num_workers_per_group: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ) -> None:
        self._dataset_len = dataset_len
        self._num_shards = num_replica_groups * num_workers_per_group
        self._global_rank = group_rank + num_workers_per_group * replica_rank
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    @property
    def num_samples(self) -> int:
        if self._drop_last:
            return self._dataset_len // self._num_shards
        return -(-self._dataset_len // self._num_shards)

    def __len__(self) -> int:
        return self.num_samples

    def indices(self) -> List[int]:
        order = np.arange(self._dataset_len)
        if self._shuffle:
            rng = np.random.default_rng(self._seed + self._epoch)
            rng.shuffle(order)
        if not self._drop_last:
            pad = (-len(order)) % self._num_shards
            if pad:
                order = np.concatenate([order, order[:pad]])
        usable = (len(order) // self._num_shards) * self._num_shards
        return list(order[self._global_rank : usable : self._num_shards])

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices())


def batch_indices(
    sampler: DistributedSampler, batch_size: int, drop_last: bool = True
) -> Iterator[List[int]]:
    batch: List[int] = []
    for idx in sampler:
        batch.append(idx)
        if len(batch) == batch_size:
            yield batch
            batch = []
    if batch and not drop_last:
        yield batch
