"""Canonical registry of every ``TORCHFT_*`` / ``TPUFT_*`` environment knob.

The stack's knob surface grew to ~100 distinct environment variables across
six PRs, each read ad-hoc at its point of use.  This module is the single
source of truth the ``ftlint`` knob checker (``torchft_tpu/analysis``)
enforces: every knob-shaped name appearing anywhere in package source must
be declared here, and the knob reference table in ``docs/operations.md``
must agree with this registry in both directions (run
``python -m torchft_tpu.knobs`` to re-emit the table).

Declaring a knob here does NOT change how it is read — modules with
bespoke parse semantics (fault-program specs, ``auto`` tri-states, custom
error text) keep their own readers.  Modules with plain scalar reads go
through the live accessors below (``get_str`` / ``get_int`` / ``get_float``
/ ``get_bool``), which read ``os.environ`` at call time (never cached, so
tests that monkeypatch the environment keep working) and name the knob in
their parse errors.

To add a knob: declare it below (name, type, default, one-line doc), use
an accessor (or a bespoke reader) at the point of use, and refresh the
``docs/operations.md`` knob table.  ``ftlint`` fails the build on any
undeclared knob and on registry/docs drift.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

__all__ = [
    "Knob",
    "REGISTRY",
    "get_raw",
    "get_str",
    "get_int",
    "get_float",
    "get_bool",
    "operations_md_table",
]


@dataclass(frozen=True)
class Knob:
    name: str
    type: str  # "str" | "int" | "float" | "bool"
    default: str  # human-rendered default (may be "auto", "unset", ...)
    doc: str
    scope: str = "runtime"  # "runtime" | "bench" | "launcher"


REGISTRY: Dict[str, Knob] = {}


def _k(name: str, type: str, default: str, doc: str, scope: str = "runtime") -> None:
    if name in REGISTRY:
        raise ValueError(f"duplicate knob declaration: {name}")
    REGISTRY[name] = Knob(name=name, type=type, default=default, doc=doc, scope=scope)


# --- control plane ----------------------------------------------------------
_k("TORCHFT_LIGHTHOUSE", "str", "unset",
   "Lighthouse address (host:port) a manager registers with; required for multi-replica runs")
_k("TORCHFT_MANAGER_PORT", "int", "0",
   "Bind port for the manager server (0 = ephemeral)")
_k("TORCHFT_TIMEOUT_SEC", "float", "per-ctor (60)",
   "Per-op data-plane timeout; peers abort a ring after this long")
_k("TORCHFT_QUORUM_TIMEOUT_SEC", "float", "per-ctor (900)",
   "Quorum RPC deadline (covers rendezvous of the whole fleet)")
_k("TORCHFT_CONNECT_TIMEOUT_SEC", "float", "per-ctor (60)",
   "Control-plane dial deadline (lighthouse/manager/store)")
_k("TORCHFT_QUORUM_RETRIES", "int", "0",
   "Consecutive failed-quorum retries before the manager raises")
_k("TORCHFT_CONNECT_RETRIES", "int", "3",
   "Dial attempts with jittered exponential backoff inside the connect deadline")
_k("TORCHFT_WIRE_COMPAT", "int", "5 (current)",
   "Pin the control-plane wire version during rolling upgrades (1..5; 4 pins pre-v5 bytes, 3 disables the v4 coordination plane)")
_k("TORCHFT_WATCHDOG_TIMEOUT_SEC", "float", "0 (off)",
   "Futures watchdog: log+dump stacks when an op exceeds this bound")
_k("TORCHFT_TIER", "str", "auto",
   "Control-plane tier: cpp | python | auto (cpp when the native build loads)")
_k("TORCHFT_NATIVE_DIR", "str", "<repo>/native",
   "Directory holding the native tier build (libtpuft.so)")
# --- hierarchical coordination plane (wire v4) ------------------------------
_k("TORCHFT_AGG_ADDR", "str", "unset",
   "Zone aggregator address (host:port) this manager routes heartbeats through; unset = beat the lighthouse directly")
_k("TORCHFT_AGG_FLUSH_MS", "float", "100",
   "Aggregator upstream flush cadence: one batched LH_AGG_BEAT RPC per tick")
_k("TORCHFT_AGG_TIMEOUT_S", "float", "1.0",
   "Lighthouse-side flush age after which an aggregator counts dead (reporting gap, not member death)")
_k("TORCHFT_AGG_GRACE_S", "float", "heartbeat timeout",
   "Extra member-liveness grace while the member's aggregator is dead (covers the fall-back-to-direct window); explicit 0 disables")
_k("TORCHFT_AGG_RETRY_S", "float", "2.0",
   "Member-side cooloff before retrying a failed aggregator (beats go direct meanwhile)")
_k("TORCHFT_STATUS_TTL_S", "float", "0.5",
   "Lighthouse /status(.json) snapshot TTL: status polls rebuild (and take the state lock) at most once per TTL")
# --- observability ----------------------------------------------------------
_k("TORCHFT_USE_OTEL", "bool", "0",
   "Opt into the OpenTelemetry metrics exporter when the SDK is installed")
_k("TORCHFT_LOG_DIR", "str", "unset",
   "Directory for JSONL metrics logs (torchft_quorums / torchft_heals); enables logging when set")
_k("TORCHFT_TRACE_DIR", "str", "unset",
   "Directory for per-epoch chrome-trace dumps (off when unset)")
_k("TORCHFT_FLIGHT_EVENTS", "int", "4096",
   "Flight-recorder ring capacity (typed events per replica); 0 disables recording entirely")
_k("TORCHFT_FLIGHT_DIR", "str", "unset",
   "Directory flight dumps land in as flight_{replica_id}.jsonl (poison / error-funnel / SIGUSR2 / atexit / shutdown triggers); unset disables file dumps")
_k("TORCHFT_FLIGHT_SPANS", "bool", "0",
   "Collect per-step trace spans (quorum rpc, collectives, lane windows, heal) for Chrome-trace export")
_k("TORCHFT_FLIGHT_DUMP_MIN_S", "float", "1.0",
   "Rate limit between automatic flight dumps (a poison storm must not turn into an fsync storm)")
_k("TORCHFT_METRICS", "bool", "1",
   "Serve the Prometheus-text /metrics endpoint on the lighthouse and every ManagerServer")
_k("TORCHFT_METRICS_TTL_S", "float", "0.5",
   "ManagerServer /metrics snapshot TTL: scrape storms rebuild the sample set at most once per TTL")
# --- data plane: lanes / framing / topology ---------------------------------
_k("TORCHFT_RING_LANES", "str", "auto",
   "TCP lanes per peer for striped collectives (auto = profile-derived; must be uniform)")
_k("TORCHFT_RING_FRAME_KB", "str", "auto",
   "Stripe floor per lane frame in KiB (auto = RTT*BW-derived)")
_k("TORCHFT_HIERARCHICAL", "str", "auto",
   "Topology-aware dispatch: auto | 0 | 1 (auto engages at >=2 hosts with a multi-member host)")
_k("TORCHFT_HOST_ID", "str", "advertised host",
   "Override host identity for same-IP host grouping")
_k("TORCHFT_SHM_SLOT_MB", "float", "8",
   "Per-slot size of the intra-host shared-memory segment (MiB, 64-byte aligned)")
_k("TORCHFT_LANE_RETRIES", "int", "2",
   "In-epoch re-dial attempts for a reset lane before failover to surviving lanes")
_k("TORCHFT_LANE_BACKOFF_MS", "float", "50",
   "Base backoff between in-epoch lane re-dials (jittered exponential)")
_k("TORCHFT_BUCKET_CAP_MB", "float", "32",
   "Gradient bucket split size for DDP allreduce (must be uniform across replicas)")
_k("TORCHFT_BABY_SHM_MIN", "int", "262144",
   "Minimum payload bytes routed via the baby-process shared-memory ring")
# --- data plane: quantization ----------------------------------------------
_k("TORCHFT_QUANT_KIND", "str", "int8",
   "Wire quantization kind for quantized collectives")
_k("TORCHFT_QUANT_WINDOW_MB", "float", "4",
   "Pipelined quantized-collective window size (MiB)")
_k("TORCHFT_QUANT_DEVICE_REDUCE", "str", "auto",
   "Force on/off the on-device dequant+reduce kernel path")
# --- net emulation / fault injection ----------------------------------------
_k("TORCHFT_NET_EMU", "str", "off",
   "Named link-emulation profile for the data plane: wan_1g | dcn_10g")
_k("TORCHFT_NET_GBPS", "float", "profile",
   "Override the emulated link rate (Gbit/s)")
_k("TORCHFT_NET_RTT_MS", "float", "profile",
   "Override the emulated round-trip time (ms)")
_k("TORCHFT_NET_CWND_KB", "float", "256",
   "Per-stream congestion-window cap under emulation (KiB)")
_k("TORCHFT_NET_FAULTS", "str", "unset",
   "Fault program: loss:P,reset:P,reset_once:N,stall:P:MS,partition:A+B|self (see operations.md #10)")
_k("TORCHFT_NET_FAULT_SEED", "int", "unset",
   "Seed for reproducible fault-program draws")
# --- healing ----------------------------------------------------------------
_k("TORCHFT_HEAL_STRIPED", "bool", "1",
   "Striped multi-source heal (0 pins the legacy single-peer heal)")
_k("TORCHFT_HEAL_CHUNK_MB", "float", "4",
   "Target chunk size for striped heal transfers (MiB)")
_k("TORCHFT_HEAL_MAX_SOURCES", "int", "0 (all)",
   "Cap on concurrent heal sources (0 = every up-to-date peer)")
_k("TORCHFT_HEAL_SOURCE_TIMEOUT_S", "float", "30",
   "Per-request stall bound before a heal source is declared dead and its chunks stolen")
# --- eviction policy --------------------------------------------------------
_k("TORCHFT_EVICT_SLOW", "bool", "0",
   "Exclude flagged comm-health stragglers from the next quorum")
_k("TORCHFT_EVICT_RATIO", "float", "4.0",
   "Stall-rate multiple over the fleet median that flags a replica")
_k("TORCHFT_EVICT_MIN_STALL_RATE", "float", "20.0",
   "Absolute stall-rate floor below which nobody is flagged")
_k("TORCHFT_EVICT_PERSIST", "int", "3",
   "Consecutive flagged quorum rounds before eviction")
# --- sharded outer optimizer ------------------------------------------------
_k("TORCHFT_OUTER_SHARD", "str", "auto",
   "ZeRO-1-style sharded outer sync: auto | 0 | 1 (0 = legacy replicated path)")
_k("TORCHFT_OUTER_CHUNK_MB", "float", "16",
   "Pipelined outer-sync chunk size (MiB, capped at 64 chunks)")
# --- streamed outer sync (zero-overhead DiLoCo fragments) -------------------
_k("TORCHFT_STREAM_SYNC", "str", "auto",
   "Stream DiLoCo fragment outer syncs under inner compute: auto / 0 / 1 (0 = legacy blocking sync, byte-identical; auto engages only when TORCHFT_STREAM_MAX_STALENESS >= 1 and the cadence has room; 1 forces with a derived staleness bar)")
_k("TORCHFT_STREAM_MAX_STALENESS", "int", "0 (off)",
   "Bounded-staleness bar in inner steps: a streamed fragment delta applies exactly this many steps after its sync point (clamped to per-fragment sync_every - delay - 1; identical on every replica)")
# --- degraded mode (in-replica device loss, wire v5) ------------------------
_k("TORCHFT_DEGRADED_MIN_FRAC", "float", "0 (never)",
   "Capacity floor: evict a replica wounded below this fraction (never below min_replicas/majority)")
_k("TORCHFT_DEGRADED_SWAP", "bool", "1",
   "Swap a wounded replica for a warm full-width spare in one membership edit (promotion preferred over degradation)")
_k("TORCHFT_CHAOS_DEVICE_LOSS", "int", "unset",
   "Chaos (process plane): hide N devices at startup so the replica comes up wounded and re-lowers")
# --- hot spares -------------------------------------------------------------
_k("TORCHFT_SPARE_PROMOTE", "bool", "1",
   "Allow the lighthouse to promote a warmed spare when an active dies")
_k("TORCHFT_SPARE_MAX_LAG", "int", "unset (any)",
   "Max warm-step staleness for a spare to be promotion-eligible")
_k("TORCHFT_SPARE_WARM_REFRESH_S", "float", "1.0",
   "Min seconds between warm-snapshot restagings on an active with spares registered")
_k("TORCHFT_SPARE_WARM_PACE_MS", "float", "5",
   "Spare-side pause between warm chunk fetches (idle priority)")
_k("TORCHFT_SPARE_WARM_BUDGET_S", "float", "2.0",
   "Per-round time budget a spare spends fetching warm chunks")
_k("TORCHFT_SPARE_DELTA_BUF_MB", "float", "128",
   "Bounded outer-delta feed ring an active publishes for spares (MiB)")
# --- attention / model kernels ----------------------------------------------
_k("TORCHFT_FLASH", "str", "auto",
   "Force (1) / kill (0) the Pallas flash-attention path")
_k("TORCHFT_FLASH_PLATFORM", "str", "jax backend",
   "Override the platform the flash kernel lowers for (tpu | cpu interpret)")
_k("TORCHFT_FLASH_BLOCK_Q", "int", "512",
   "Flash-attention query block size")
_k("TORCHFT_FLASH_BLOCK_K", "int", "512",
   "Flash-attention key/value block size")
# --- launcher / scheduler ---------------------------------------------------
_k("TPUFT_GROUP_RANK", "int", "0",
   "This replica group's global rank (set by the launcher/scheduler)", "launcher")
_k("TPUFT_GROUP_WORLD_SIZE", "int", "1",
   "Total replica groups in the job (set by the launcher/scheduler)", "launcher")
_k("TPUFT_STANDBY_GATE", "str", "unset",
   "Gate file a standby blocks on before starting (hot-standby launch path)", "launcher")
# --- bench harness (bench.py / scripts) -------------------------------------
_k("TPUFT_BENCH_PLATFORM", "str", "auto",
   "Force the bench backend (cpu | tpu)", "bench")
_k("TPUFT_BENCH_WORKER_PLATFORM", "str", "inherit",
   "Backend for bench fleet worker processes", "bench")
_k("TPUFT_BENCH_MODE", "str", "ddp",
   "Bench training mode (ddp | localsgd | diloco)", "bench")
_k("TPUFT_BENCH_OUT", "str", "<repo>/bench_out.json",
   "Bench artifact output path", "bench")
_k("TPUFT_BENCH_EVENTS_DIR", "str", "unset",
   "Directory fleet workers write lifecycle events to", "bench")
_k("TPUFT_BENCH_STEPS", "int", "8 cpu / 30 tpu",
   "Phase-A measured steps", "bench")
_k("TPUFT_BENCH_TARGET_STEPS", "int", "derived",
   "Fleet worker step target (set for workers by the parent)", "bench")
_k("TPUFT_BENCH_DIM", "int", "256 cpu / 2048 tpu",
   "Bench model hidden dim", "bench")
_k("TPUFT_BENCH_LAYERS", "int", "4 cpu / 16 tpu",
   "Bench model layer count", "bench")
_k("TPUFT_BENCH_SEQ", "int", "256 cpu / 2048 tpu",
   "Bench sequence length", "bench")
_k("TPUFT_BENCH_BATCH", "int", "4 cpu / 8 tpu",
   "Bench per-step batch size", "bench")
_k("TPUFT_BENCH_HEAD_DIM", "int", "64 cpu / 128 tpu",
   "Bench attention head dim", "bench")
_k("TPUFT_BENCH_REMAT", "bool", "0 cpu / 1 tpu",
   "Enable remat in the bench model", "bench")
_k("TPUFT_BENCH_REMAT_MODE", "str", "unset",
   "Remat policy override for the bench model", "bench")
_k("TPUFT_BENCH_REPLICAS", "int", "3",
   "Fleet phase replica-group count", "bench")
_k("TPUFT_BENCH_STANDBY", "int", "1",
   "Hot standbys kept during the fleet phase", "bench")
_k("TPUFT_BENCH_ALL_STANDBY", "bool", "0",
   "Relaunch every killed replica as a standby", "bench")
_k("TPUFT_BENCH_FLEET_STEPS", "int", "48 cpu / 100 tpu",
   "Fleet phase step count", "bench")
_k("TPUFT_BENCH_FLEET_DIM", "int", "256",
   "Fleet phase model hidden dim", "bench")
_k("TPUFT_BENCH_FLEET_LAYERS", "int", "4",
   "Fleet phase model layer count", "bench")
_k("TPUFT_BENCH_FLEET_SEQ", "int", "256 cpu / 512 tpu",
   "Fleet phase sequence length", "bench")
_k("TPUFT_BENCH_FLEET_BATCH", "int", "4 cpu / 8 tpu",
   "Fleet phase batch size", "bench")
_k("TPUFT_BENCH_KILL_EVERY", "int", "14 cpu / 25 tpu",
   "Fleet phase: kill one replica every N steps", "bench")
_k("TPUFT_BENCH_JOIN_MS", "float", "1000",
   "Fleet phase relaunch join pause (ms)", "bench")
_k("TPUFT_BENCH_HEAL_TRANSPORT", "str", "comm",
   "Heal transport for the fleet phase (comm | http)", "bench")
_k("TPUFT_BENCH_DILOCO_STEPS", "int", "48 cpu / 96 tpu",
   "DiLoCo phase step count", "bench")
_k("TPUFT_BENCH_DILOCO_SYNC", "int", "8",
   "DiLoCo outer-sync cadence (steps)", "bench")
_k("TPUFT_BENCH_DILOCO_DELAY", "int", "2",
   "DiLoCo delayed-apply depth", "bench")
_k("TPUFT_BENCH_DILOCO_FRAGMENTS", "int", "2",
   "DiLoCo streaming fragment count", "bench")
_k("TPUFT_BENCH_DILOCO_KILLS", "int", "3",
   "DiLoCo chaos-leg kill count", "bench")
_k("TPUFT_BENCH_DILOCO_QUANT", "str", "auto",
   "DiLoCo quantized-wire legs: auto | 0 | 1", "bench")
_k("TPUFT_BENCH_DILOCO_QUANT_WIRE", "bool", "0",
   "Worker-side flag: quantize the outer-sync wire", "bench")
_k("TPUFT_BENCH_SKIP_FLEET", "bool", "0",
   "Skip the fleet (kill/heal) bench phase", "bench")
_k("TPUFT_BENCH_SKIP_DILOCO", "bool", "0",
   "Skip the DiLoCo bench phase", "bench")
_k("TPUFT_BENCH_SKIP_SPARE", "bool", "0",
   "Skip the hot-spare promotion bench phase", "bench")
_k("TPUFT_BENCH_SKIP_COORD", "bool", "0",
   "Skip the coordination-plane scale phase", "bench")
_k("TPUFT_BENCH_SKIP_DEGRADED", "bool", "0",
   "Skip the degraded-mode (device-loss) bench phase", "bench")
_k("TPUFT_BENCH_SKIP_STREAM", "bool", "0",
   "Skip the streamed-outer-sync DiLoCo bench leg (diloco_faultfree_streaming)", "bench")
_k("TPUFT_BENCH_SKIP_OBS", "bool", "0",
   "Skip the observability-overhead bench phase", "bench")
_k("TPUFT_BENCH_OBS_STEPS", "int", "40",
   "Measured steps per leg of the observability-overhead phase", "bench")
_k("TPUFT_BENCH_COORD_REPLICAS", "int", "120 cpu / 500 tpu",
   "Simulated replicas driven by the coordination scale phase", "bench")
_k("TPUFT_BENCH_PROBE_TIMEOUT_S", "float", "180",
   "Backend-executes probe deadline", "bench")
_k("TPUFT_BENCH_PROBE_WINDOW_S", "float", "900",
   "Total window spent re-probing a wedged backend at startup", "bench")
_k("TPUFT_BENCH_REPROBE_WINDOW_S", "float", "60",
   "Mid-run recovery: window spent re-probing after a wedge", "bench")
_k("TPUFT_BENCH_REPROBE_BUDGET_S", "float", "1500",
   "Mid-run recovery: budget for the phase-A recapture subprocess", "bench")
_k("TPUFT_BENCH_PHASE_FLOOR_S", "float", "1500",
   "Minimum per-phase share of the remaining budget", "bench")
_k("TPUFT_BENCH_TOTAL_BUDGET_S", "float", "2100",
   "Soft wall-clock budget for the whole bench run", "bench")
_k("TPUFT_BENCH_HARD_DEADLINE_S", "float", "budget+1200",
   "Hard watchdog: emit a partial artifact and exit 0 at this age", "bench")
_k("TPUFT_PEAK_TFLOPS", "float", "auto",
   "Override the per-chip peak TFLOP/s used for MFU math", "bench")
_k("TPUFT_SWEEP_OUT", "str", "unset",
   "mfu_sweep artifact output path", "bench")


def _parse_error(name: str, raw: str, expected: str) -> ValueError:
    return ValueError(f"unparseable {name}={raw!r} (expected {expected})")


def _lookup(name: str) -> Knob:
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not a registered knob — declare it in torchft_tpu/knobs.py"
        ) from None


def get_raw(name: str) -> Optional[str]:
    """The raw environment value of a registered knob (None when unset).

    Reads ``os.environ`` at call time — values are never cached, so tests
    that monkeypatch the environment see their overrides immediately."""
    _lookup(name)
    return os.environ.get(name)


def get_str(name: str, default: str = "") -> str:
    raw = get_raw(name)
    return raw if raw else default


def get_int(name: str, default: int = 0) -> int:
    raw = get_raw(name)
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise _parse_error(name, raw, "int") from None


def get_float(name: str, default: float = 0.0) -> float:
    raw = get_raw(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise _parse_error(name, raw, "float") from None


def get_bool(name: str, default: bool = False) -> bool:
    """Truthiness parse shared by every boolean knob: explicit off values
    ("0", "false", "off") are false, any other non-empty value is true."""
    raw = get_raw(name)
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "off")


def operations_md_table() -> str:
    """The ``docs/operations.md`` knob-reference table, generated from this
    registry so the two can never drift (ftlint cross-checks both ways)."""
    lines = [
        "| Knob | Type | Default | What it does |",
        "|---|---|---|---|",
    ]
    for knob in sorted(REGISTRY.values(), key=lambda k: (k.scope, k.name)):
        default = knob.default.replace("|", "\\|")
        doc = knob.doc.replace("|", "\\|")
        lines.append(f"| `{knob.name}` | {knob.type} | {default} | {doc} |")
    return "\n".join(lines)


if __name__ == "__main__":  # pragma: no cover - doc regeneration helper
    print(operations_md_table())
