"""Lighthouse: global membership / quorum service.

One lighthouse runs per job.  Replica groups register participation via the
blocking ``quorum`` RPC and send periodic heartbeats; the lighthouse computes
a quorum each tick and broadcasts it to every parked requester.  This is the
behavioral twin of the reference's Rust lighthouse (``src/lighthouse.rs``):

- ``quorum_compute`` (``src/lighthouse.rs:141-269``): filter participants by
  heartbeat freshness; take the *fast quorum* when every previous-quorum
  member is back; otherwise require ``min_replicas``, a majority of all
  heartbeating replicas (anti split-brain), and wait ``join_timeout_ms`` for
  healthy stragglers before issuing a smaller quorum. ``shrink_only``
  restricts candidates to previous members.
- Tick loop every ``quorum_tick_ms`` (``src/lighthouse.rs:345-352``);
  ``quorum_id`` bumps on membership change or on any member reporting commit
  failures (``src/lighthouse.rs:307-325``); participants are cleared after a
  quorum is issued so each round re-registers.
- The ``quorum`` RPC registers the requester (implicit heartbeat), runs a
  proactive tick, then parks until a quorum *containing the requester*
  arrives, re-registering if a quorum excludes it
  (``src/lighthouse.rs:484-551``); the server honors the client's deadline
  like the reference honors ``grpc-timeout`` (``src/timeout.rs``).
- The same listener also answers plain HTTP: ``/`` and ``/status`` render a
  dashboard and ``/replica/{id}/kill`` forwards a Kill RPC to that replica's
  manager (``src/lighthouse.rs:370-388,454-479``).  We sniff the first bytes
  of each connection to route HTTP vs framed RPC on one port.
"""

from __future__ import annotations

import argparse
import html
import json
import logging
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from torchft_tpu import knobs
from torchft_tpu.obs import metrics as obs_metrics
from torchft_tpu.obs.flight import FlightEvent, FlightRecorder
from torchft_tpu.wire import (
    ROLE_ACTIVE,
    ROLE_SPARE,
    WIRE_COMPAT_ENV,
    AggBeat,
    CommHealth,
    ErrCode,
    MsgType,
    Quorum,
    QuorumDelta,
    QuorumMember,
    Reader,
    RpcClient,
    WireError,
    Writer,
    apply_quorum_delta,
    configure_server_socket,
    create_listener,
    connect,
    make_quorum_delta,
    manager_quorum_wire_version,
    quorum_digest,
    raise_if_error,
    read_http_path,
    recv_frame,
    send_error,
    send_frame,
    send_http_response,
)

logger = logging.getLogger(__name__)

# Straggler detection / eviction knobs.  Heartbeats carry a cumulative
# comm-health summary (wire.CommHealth); the lighthouse differences
# consecutive beats into EWMA rates and flags a replica whose stall rate is
# a persistent outlier vs its peers.  With TORCHFT_EVICT_SLOW=1 a flagged
# replica is excluded from the next quorum (never below min_replicas or
# the anti-split-brain majority), so the fleet sheds a gray node
# proactively instead of timing out on it every step.
EVICT_SLOW_ENV = "TORCHFT_EVICT_SLOW"
# flag when stall_rate > ratio x median(peer stall rates) ...
EVICT_RATIO_ENV = "TORCHFT_EVICT_RATIO"  # default 4.0
# ... AND above this absolute floor (events/s) — so an idle fleet where
# everyone is near zero never flags anybody
EVICT_MIN_STALL_RATE_ENV = "TORCHFT_EVICT_MIN_STALL_RATE"  # default 20.0
# consecutive outlier evaluations (one per heartbeat) before flagging
EVICT_PERSIST_ENV = "TORCHFT_EVICT_PERSIST"  # default 3


def _evict_slow_enabled() -> bool:
    return os.environ.get(EVICT_SLOW_ENV, "0").lower() in ("1", "true", "on")


# Hot-spare promotion (wire v3 SPARE role).  A spare registers via the
# quorum RPC with role=SPARE: it heartbeats and receives every quorum
# broadcast (riding the version-gated ``spares`` tail) but never counts
# toward min_replicas or the anti-split-brain majority and never enters the
# participant list — so a spare joining, warming, or DYING never bumps
# quorum_id or reconfigures the active fleet.  When an active member of the
# previous quorum stops heartbeating, the lighthouse promotes the freshest
# healthy spare (max reported warm step, ties to the lowest replica_id) in
# the SAME quorum computation that would have shrunk the fleet: the spare
# moves into the candidate set and the resulting membership edit is the one
# quorum_id bump the failure was always going to cost.
SPARE_PROMOTE_ENV = "TORCHFT_SPARE_PROMOTE"
# a spare lagging the fleet by more than this many steps is too cold to
# promote (it would stall the quorum on a bulk heal anyway; let the fleet
# shrink and the spare keep warming)
SPARE_MAX_LAG_ENV = "TORCHFT_SPARE_MAX_LAG"  # default: unlimited
# Spare liveness is judged on a LAXER bound than active death detection:
# a sub-second heartbeat_timeout sized for fast failure detection also
# means one scheduler-starved beat from the spare (whose process spends
# its time warming, not spinning on the control plane) would make it
# ineligible at exactly the promotion instant — and a missed promotion is
# PERMANENT once the shrunk quorum becomes prev (dead members of the old
# prev are no longer anyone's to replace).  A spare this stale may be
# dead; the cost of wrongly promoting one is a single wedged round (the
# fleet sheds it at the next heartbeat verdict), while the cost of
# wrongly skipping one is the full cold heal-in the spare existed to
# avoid.  Registration pruning stays at 4x.
_SPARE_FRESH_FACTOR = 3.0


def _spare_promote_enabled() -> bool:
    return knobs.get_bool(SPARE_PROMOTE_ENV, True)


# Degraded-mode policy (wire v5).  A replica that lost in-replica devices
# re-lowers onto the survivors and advertises a capacity fraction instead
# of dying; the lighthouse treats that fraction as a first-class policy
# input with a three-rung ladder:
#
#   wound  — the fleet keeps the wounded replica (reduced data shard,
#            weighted outer reduce); zero membership edits.
#   swap   — promotion preferred over degradation: when a full-width warm
#            spare is registered, the wounded replica trades places with
#            it in ONE membership edit (same quorum computation, like
#            hold-the-shrink).  The swapped-out replica stays excluded
#            while it remains degraded and is re-admitted the moment it
#            re-registers at full capacity.
#   evict  — a replica wounded below TORCHFT_DEGRADED_MIN_FRAC is shed
#            from the quorum (never below min_replicas or the
#            anti-split-brain majority: a limping replica still beats no
#            quorum).  0 (the default) disables floor eviction.
DEGRADED_MIN_FRAC_ENV = "TORCHFT_DEGRADED_MIN_FRAC"
DEGRADED_SWAP_ENV = "TORCHFT_DEGRADED_SWAP"


def _degraded_min_frac() -> float:
    return knobs.get_float(DEGRADED_MIN_FRAC_ENV, 0.0)


def _degraded_swap_enabled() -> bool:
    return knobs.get_bool(DEGRADED_SWAP_ENV, True)


# Hierarchical coordination plane (wire v4).  Zone aggregators batch member
# heartbeats into one upstream RPC per flush tick (LH_AGG_BEAT_REQ); the
# lighthouse remembers which aggregator last reported each member.  When an
# aggregator goes quiet, its members' beat staleness is a REPORTING gap,
# not evidence of member death: each affected member gets a bounded extra
# grace window (during which its manager's heartbeat loop falls back to
# direct beats) before the normal heartbeat verdict applies.  An aggregator
# is judged dead on a much tighter bound than members (it flushes every
# ~100 ms), so the gap is known well before any member heartbeat expires.
AGG_TIMEOUT_S_ENV = "TORCHFT_AGG_TIMEOUT_S"  # default 1.0
AGG_GRACE_S_ENV = "TORCHFT_AGG_GRACE_S"  # default: heartbeat timeout
# /status(.json) snapshot TTL: status polls are served from a cached
# snapshot rebuilt at most once per TTL, so a dashboard fleet polling at
# high QPS never contends on the quorum state lock.
STATUS_TTL_S_ENV = "TORCHFT_STATUS_TTL_S"  # default 0.5
# recently-issued quorums kept for delta-coded broadcasts (by digest)
_RECENT_QUORUMS_MAX = 8
_PAYLOAD_CACHE_MAX = 64


def _agg_freshness_knobs(hb_timeout_s: float) -> Tuple[float, float]:
    """(aggregator dead-after age, member grace while its agg is dead).

    Unset grace defaults to one heartbeat timeout; an EXPLICIT 0 disables
    the reporting-gap excuse entirely (agg-routed members judged as
    strictly as direct ones) — unset and 0 must stay distinguishable."""
    agg_timeout = knobs.get_float(AGG_TIMEOUT_S_ENV, 1.0)
    raw_grace = knobs.get_raw(AGG_GRACE_S_ENV)
    if raw_grace is None or raw_grace == "":
        grace = hb_timeout_s
    else:
        grace = knobs.get_float(AGG_GRACE_S_ENV, hb_timeout_s)
    return agg_timeout, grace


def _beat_fresh(
    state: "_State",
    rid: str,
    now: float,
    bound_s: float,
    agg_timeout_s: float,
    grace_s: float,
) -> bool:
    """Member liveness with the aggregator reporting-gap excuse: fresh
    within ``bound_s`` as before; a member whose last beat arrived via an
    aggregator that is itself dead gets ``grace_s`` extra (its beats
    stopped because the REPORTER died — the member's manager falls back to
    direct beats within a heartbeat interval or two).  A stale member
    whose aggregator is alive is genuinely quiet and gets no excuse."""
    ts = state.heartbeats.get(rid)
    if ts is None:
        return False
    age = now - ts
    if age < bound_s:
        return True
    agg = state.via_agg.get(rid)
    if agg is None or grace_s <= 0:
        return False
    agg_ts = state.agg_last.get(agg)
    if agg_ts is not None and now - agg_ts <= agg_timeout_s:
        return False  # reporting path alive: the member itself went quiet
    return age < bound_s + grace_s


def _spare_max_lag() -> Optional[int]:
    raw = os.environ.get(SPARE_MAX_LAG_ENV)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError as e:
        raise ValueError(
            f"unparseable {SPARE_MAX_LAG_ENV}={raw!r} (expected int)"
        ) from e


def _evict_knobs() -> Tuple[float, float, int]:
    try:
        ratio = float(os.environ.get(EVICT_RATIO_ENV, "") or 4.0)
        min_rate = float(os.environ.get(EVICT_MIN_STALL_RATE_ENV, "") or 20.0)
        persist = int(os.environ.get(EVICT_PERSIST_ENV, "") or 3)
    except ValueError as e:
        raise ValueError(
            f"unparseable eviction knob: {EVICT_RATIO_ENV}="
            f"{os.environ.get(EVICT_RATIO_ENV)!r} "
            f"{EVICT_MIN_STALL_RATE_ENV}="
            f"{os.environ.get(EVICT_MIN_STALL_RATE_ENV)!r} "
            f"{EVICT_PERSIST_ENV}={os.environ.get(EVICT_PERSIST_ENV)!r}"
        ) from e
    return ratio, min_rate, max(1, persist)


@dataclass
class LighthouseConfig:
    """CLI-visible knobs (``src/lighthouse.rs:94-131``)."""

    min_replicas: int
    bind: str = "0.0.0.0:0"
    join_timeout_ms: int = 60_000
    quorum_tick_ms: int = 100
    heartbeat_timeout_ms: int = 5_000


@dataclass
class _MemberDetails:
    joined: float
    member: QuorumMember


@dataclass
class _ReplicaHealth:
    """Per-replica comm-health aggregate differenced from heartbeats."""

    last: Optional[CommHealth] = None
    last_ts: float = 0.0
    stall_rate: float = 0.0  # EWMA, events/s
    reconnect_rate: float = 0.0  # EWMA, events/s
    tx_rate: float = 0.0  # EWMA, bytes/s
    reconnects: int = 0  # cumulative, straight from the last beat
    failovers: int = 0
    flag_streak: int = 0
    flagged: bool = False


@dataclass
class _State:
    participants: Dict[str, _MemberDetails] = field(default_factory=dict)
    heartbeats: Dict[str, float] = field(default_factory=dict)
    prev_quorum: Optional[Quorum] = None
    quorum_id: int = 0
    health: Dict[str, _ReplicaHealth] = field(default_factory=dict)
    evicted_now: List[str] = field(default_factory=list)
    evicted_prev: set = field(default_factory=set)
    evictions_total: int = 0
    # hot spares: registered SPARE-role members, kept OUT of participants
    # (and out of every membership count) until promoted.  ``spare_ids``
    # remembers which heartbeating replica ids are spares so majority math
    # never counts them; ``promoted`` pins ids the lighthouse flipped to
    # active until the replica itself re-registers with role=ACTIVE.
    spares: Dict[str, _MemberDetails] = field(default_factory=dict)
    spare_ids: set = field(default_factory=set)
    promoted: set = field(default_factory=set)
    promoted_now: List[str] = field(default_factory=list)
    promotions_total: int = 0
    # hold-the-shrink anchors: when each prev member was FIRST observed
    # absent-but-heartbeat-fresh (the window must run from the member's
    # own disappearance — anchoring on the survivors' park time can expire
    # BEFORE the missing member's heartbeat does, issuing the shrink while
    # the member still counts healthy and permanently missing the
    # promotion once the shrunk quorum becomes prev)
    hold_since: Dict[str, float] = field(default_factory=dict)
    # hierarchical coordination plane (wire v4): which aggregator last
    # reported each member (cleared when the member beats direct), and
    # each aggregator's last flush time — the inputs to the aggregator
    # reporting-gap grace in ``_beat_fresh``
    via_agg: Dict[str, str] = field(default_factory=dict)
    agg_last: Dict[str, float] = field(default_factory=dict)
    # rate limit for the note_health stale-entry prune (an O(members)
    # sweep per beat would be O(N^2)/s at fleet scale)
    health_pruned_ts: float = 0.0
    # degraded-mode (wire v5): wounded replicas a full-width spare swapped
    # out — excluded from quorums while they remain degraded, re-admitted
    # when they re-register at full capacity; plus the floor-eviction
    # accounting twins of evicted_now/evicted_prev/evictions_total
    degraded_swapped: set = field(default_factory=set)
    degraded_evicted_now: List[str] = field(default_factory=list)
    degraded_evicted_prev: set = field(default_factory=set)
    degraded_evictions_total: int = 0
    swaps_total: int = 0
    # wounded replicas swapped out THIS computation (reset alongside
    # promoted_now) — the flight recorder's DEGRADED_SWAP feed
    swapped_now: List[str] = field(default_factory=list)


# health entries stop counting as straggler-median "reporters" after this
# many seconds without a beat, and are dropped entirely at 4x — a departed
# replica's frozen rate must not skew the peer median (or satisfy the
# >= 3-reporters guard) forever, and replica-id churn must not grow the map
# unboundedly
_HEALTH_STALE_S = 15.0


def note_health(state: _State, replica_id: str, health: CommHealth, now: float) -> None:
    """Fold one heartbeat's cumulative comm-health counters into the
    replica's EWMA rates, then re-evaluate the outlier flags.  Pure on
    ``state`` (caller holds the server lock); driven directly by tests."""
    if now - state.health_pruned_ts > _HEALTH_STALE_S or now < state.health_pruned_ts:
        state.health_pruned_ts = now
        for rid in [
            r
            for r, rh in state.health.items()
            if now - rh.last_ts > 4 * _HEALTH_STALE_S
        ]:
            del state.health[rid]
    h = state.health.setdefault(replica_id, _ReplicaHealth())
    if h.last is not None and now > h.last_ts:
        dt = now - h.last_ts
        alpha = min(1.0, dt / 5.0)  # ~5 s horizon
        stall_rate = max(0, health.stalls - h.last.stalls) / dt
        reconnect_rate = max(0, health.reconnects - h.last.reconnects) / dt
        tx_rate = max(0, health.tx_bytes - h.last.tx_bytes) / dt
        h.stall_rate += alpha * (stall_rate - h.stall_rate)
        h.reconnect_rate += alpha * (reconnect_rate - h.reconnect_rate)
        h.tx_rate += alpha * (tx_rate - h.tx_rate)
    h.last = health
    h.last_ts = now
    h.reconnects = health.reconnects
    h.failovers = health.failovers
    _evaluate_stragglers(state, replica_id, now)


def _evaluate_stragglers(state: _State, updated_id: str, now: float) -> None:
    """Flag ``updated_id`` when its stall rate is a persistent outlier vs
    its peers.  Needs >= 3 FRESH reporting replicas (with 2 there is no
    majority to say which side is 'normal'; a departed replica's frozen
    rate must not stand in as a reporter)."""
    ratio, min_rate, persist = _evict_knobs()
    rates = {
        rid: rh.stall_rate
        for rid, rh in state.health.items()
        if rh.last and now - rh.last_ts <= _HEALTH_STALE_S
    }
    h = state.health[updated_id]
    if len(rates) < 3:
        h.flag_streak, h.flagged = 0, False
        return
    others = sorted(r for rid, r in rates.items() if rid != updated_id)
    median = others[len(others) // 2]
    if h.stall_rate > max(ratio * median, min_rate):
        h.flag_streak += 1
    else:
        h.flag_streak = 0
        h.flagged = False
    if h.flag_streak >= persist and not h.flagged:
        h.flagged = True
        logger.warning(
            "straggler flagged: %s stall_rate=%.1f/s vs peer median %.1f/s",
            updated_id,
            h.stall_rate,
            median,
        )


def _note_warm_step(state: "_State", replica_id: str, warm_step: int) -> None:
    """Fold a beat-carried spare warm watermark into the registration
    record (wire v4): promotion eligibility and the /status spare table
    stay fresh at heartbeat cadence instead of quorum-RPC re-registration
    cadence.  Monotonic — a scheduler-starved stale beat never regresses
    the watermark.  Caller holds the server lock.

    COPY-on-write, never in place: the registered member object is shared
    by reference with every issued quorum that carried it (prev_quorum and
    the delta-base ring), whose digests were stamped at issue time — an
    in-place step bump would silently drift their content out from under
    those digests and break every delta computed against them."""
    details = state.spares.get(replica_id)
    if details is not None and warm_step > details.member.step:
        import dataclasses

        details.member = dataclasses.replace(details.member, step=warm_step)


def _note_capacity(state: "_State", replica_id: str, capacity: float) -> None:
    """Fold a beat-carried degraded-capacity fraction (wire v5) into the
    registration record, so the wound→swap→evict ladder reacts at beat
    cadence instead of waiting for the next quorum-RPC registration.
    Copy-on-write for the same reason as :func:`_note_warm_step` — the
    registered member object is shared by reference with issued quorums
    whose digests were stamped at issue time.  The function is total (a
    full-capacity report lifts the swapped-out exclusion too), but note
    the live beat encoder only ever carries DEGRADED fractions — healed
    re-admission in practice rides the full-capacity quorum registration
    (:meth:`LighthouseServer._register`), which happens every round.
    Caller holds the server lock."""
    capacity = min(1.0, max(0.0, capacity))
    details = state.participants.get(replica_id)
    if details is not None and details.member.capacity != capacity:
        import dataclasses

        details.member = dataclasses.replace(details.member, capacity=capacity)
    if capacity >= 1.0:
        state.degraded_swapped.discard(replica_id)


def _promote_spares(
    now: float, state: _State, cfg: LighthouseConfig, healthy_replicas: set
) -> None:
    """Hot-spare promotion: when a previous-quorum member stopped
    heartbeating, move the freshest healthy spare(s) into the participant
    set — the same membership edit the death was always going to cost,
    minus the shrink.  Mutates ``state`` (tick path only; ``_status`` calls
    ``quorum_compute`` with ``allow_promote=False``)."""
    state.promoted_now = []
    state.swapped_now = []
    if not _spare_promote_enabled() or state.prev_quorum is None:
        return
    if any(d.member.shrink_only for d in state.participants.values()):
        # a shrink_only round restricts membership to prev members — a
        # promotion would smuggle a new member into exactly the quorum the
        # caller asked to only ever shrink
        return
    hb_timeout_s = cfg.heartbeat_timeout_ms / 1000.0
    agg_timeout_s, grace_s = _agg_freshness_knobs(hb_timeout_s)
    prev = state.prev_quorum.participants
    prev_ids = {m.replica_id for m in prev}
    dead_prev = {
        m.replica_id for m in prev if m.replica_id not in healthy_replicas
    }
    # promotions from EARLIER ticks that are already standing in for the
    # same deaths: a promoted spare stays in ``participants`` (and in
    # ``promoted``) until the quorum issues, but ``dead_prev`` is
    # recomputed from the unchanged prev_quorum every tick — without this
    # offset each tick would burn another spare on the same dead member
    # and the replacement quorum would GROW past the old world size.
    already_replacing = sum(
        1
        for rid in state.participants
        if rid in state.promoted and rid not in prev_ids
    )
    slots = len(dead_prev) - already_replacing
    if not state.spares:
        return
    eligible = [
        d
        for rid, d in state.spares.items()
        if _beat_fresh(
            state,
            rid,
            now,
            _SPARE_FRESH_FACTOR * hb_timeout_s,
            agg_timeout_s,
            grace_s,
        )
    ]
    max_lag = _spare_max_lag()
    if max_lag is not None:
        prev_max_step = max((m.step for m in prev), default=0)
        eligible = [
            d for d in eligible if d.member.step >= prev_max_step - max_lag
        ]
    # freshest first (max warm step), ties to the lowest replica_id
    eligible.sort(key=lambda d: (-d.member.step, d.member.replica_id))
    for details in eligible[: max(0, slots)]:
        rid = details.member.replica_id
        state.spares.pop(rid)
        state.spare_ids.discard(rid)
        state.promoted.add(rid)
        state.participants[rid] = _MemberDetails(
            joined=now, member=details.member
        )
        healthy_replicas.add(rid)
        state.promoted_now.append(rid)
        state.promotions_total += 1
        logger.warning(
            "promoting spare %s (warm step %d) to replace dead %s",
            rid,
            details.member.step,
            ", ".join(sorted(dead_prev)),
        )
    # Swap rung of the degraded ladder: promotion preferred over
    # degradation.  With warm spares left over after death replacement, a
    # WOUNDED participant (capacity < 1, alive and registered) trades
    # places with a full-width spare in this same computation — wounded
    # out + spare in is ONE membership edit, exactly like hold-the-shrink
    # turns a death into one edit.  The swapped-out replica stays
    # excluded from future quorums (quorum_compute's degraded filter)
    # while it remains degraded, and is re-admitted the moment it
    # re-registers at full capacity.
    if not _degraded_swap_enabled():
        return
    remaining = [d for d in eligible if d.member.replica_id in state.spares]
    wounded = sorted(
        (
            d
            for rid, d in state.participants.items()
            if d.member.capacity < 1.0
            and rid in healthy_replicas
            and rid not in state.promoted
            # already swapped out: the excluded replica keeps re-registering
            # while degraded — swapping it AGAIN would burn a second spare
            # on the same wound and grow the quorum by one per round
            and rid not in state.degraded_swapped
        ),
        # most-wounded first, ties to the lowest replica_id
        key=lambda d: (d.member.capacity, d.member.replica_id),
    )
    for details, victim in zip(remaining, wounded):
        rid = details.member.replica_id
        wid = victim.member.replica_id
        state.spares.pop(rid)
        state.spare_ids.discard(rid)
        state.promoted.add(rid)
        state.participants.pop(wid, None)
        state.degraded_swapped.add(wid)
        state.participants[rid] = _MemberDetails(
            joined=now, member=details.member
        )
        healthy_replicas.add(rid)
        state.promoted_now.append(rid)
        state.swapped_now.append(wid)
        state.promotions_total += 1
        state.swaps_total += 1
        logger.warning(
            "swapping wounded %s (capacity %.2f) for full-width spare %s "
            "(warm step %d) — one membership edit",
            wid,
            victim.member.capacity,
            rid,
            details.member.step,
        )


def quorum_compute(
    now: float,
    state: _State,
    cfg: LighthouseConfig,
    allow_promote: bool = True,
) -> Tuple[Optional[List[QuorumMember]], str]:
    """Decide whether a quorum can be issued right now.

    Pure function mirroring ``quorum_compute`` (``src/lighthouse.rs:141-269``)
    so the full Rust unit-test matrix applies directly.  Registered spares
    never count toward ``min_replicas`` or the anti-split-brain majority;
    ``allow_promote`` gates the one mutation (spare → participant) so a
    status read stays side-effect free.
    """
    hb_timeout_s = cfg.heartbeat_timeout_ms / 1000.0
    agg_timeout_s, grace_s = _agg_freshness_knobs(hb_timeout_s)
    healthy_replicas = {
        rid
        for rid in state.heartbeats
        if rid not in state.spare_ids
        and _beat_fresh(state, rid, now, hb_timeout_s, agg_timeout_s, grace_s)
    }
    if allow_promote:
        _promote_spares(now, state, cfg, healthy_replicas)
    healthy_participants = {
        rid: d for rid, d in state.participants.items() if rid in healthy_replicas
    }

    candidates = sorted(
        (d.member for d in healthy_participants.values()), key=lambda m: m.replica_id
    )
    shrink_only = any(d.member.shrink_only for d in healthy_participants.values())

    # straggler eviction (TORCHFT_EVICT_SLOW): exclude persistently-flagged
    # gray replicas from the candidate set — BEFORE the fast-quorum path,
    # so even a fully-healthy-looking round sheds the straggler — but never
    # below min_replicas or the anti-split-brain majority (a gray node is
    # still better than no quorum)
    state.evicted_now = []
    if _evict_slow_enabled():
        flagged = {rid for rid, rh in state.health.items() if rh.flagged}
        keep = [m for m in candidates if m.replica_id not in flagged]
        if (
            len(keep) < len(candidates)
            and len(keep) >= cfg.min_replicas
            and len(keep) > len(healthy_replicas) // 2
        ):
            state.evicted_now = sorted(
                m.replica_id for m in candidates if m.replica_id in flagged
            )
            candidates = keep

    # degraded-mode ladder, rungs 2 and 3 (see DEGRADED_MIN_FRAC_ENV):
    # swapped-out wounded replicas stay excluded while degraded, and a
    # replica wounded below the capacity floor is evicted — both behind
    # the same never-below-min_replicas/majority guard as straggler
    # eviction.  Runs BEFORE the fast-quorum path so a wounded-but-
    # healthy-looking round still sheds/swaps.
    state.degraded_evicted_now = []
    min_frac = _degraded_min_frac()
    swapped_out = {
        m.replica_id
        for m in candidates
        if m.capacity < 1.0 and m.replica_id in state.degraded_swapped
    }
    floor_evict = {
        m.replica_id
        for m in candidates
        if min_frac > 0.0
        and m.capacity < min_frac
        and m.replica_id not in swapped_out
    }
    if swapped_out or floor_evict:
        drop = swapped_out | floor_evict
        keep = [m for m in candidates if m.replica_id not in drop]
        if (
            len(keep) >= cfg.min_replicas
            and len(keep) > len(healthy_replicas) // 2
        ):
            state.degraded_evicted_now = sorted(floor_evict)
            candidates = keep

    metadata = (
        f"[{len(healthy_participants)}/{len(state.participants)} participants healthy]"
        f"[{len(healthy_replicas)} heartbeating][shrink_only={shrink_only}]"
        + (
            f"[evicting slow: {', '.join(state.evicted_now)}]"
            if state.evicted_now
            else ""
        )
        + (
            f"[promoting spare: {', '.join(state.promoted_now)}]"
            if state.promoted_now
            else ""
        )
        + (f"[{len(state.spares)} spares]" if state.spares else "")
        + (
            f"[evicting degraded below {min_frac}: "
            f"{', '.join(state.degraded_evicted_now)}]"
            if state.degraded_evicted_now
            else ""
        )
        + (
            f"[swapped-out degraded excluded: {', '.join(sorted(swapped_out))}]"
            if swapped_out
            else ""
        )
    )

    if state.prev_quorum is not None:
        prev_ids = {m.replica_id for m in state.prev_quorum.participants}
        if shrink_only:
            candidates = [m for m in candidates if m.replica_id in prev_ids]
        # Fast quorum: every member of the previous quorum is healthy and has
        # re-registered — no need to wait for stragglers.
        if all(rid in healthy_participants for rid in prev_ids):
            return candidates, f"Fast quorum found! {metadata}"

    if len(healthy_participants) < cfg.min_replicas:
        return (
            None,
            f"New quorum not ready, only have {len(healthy_participants)} "
            f"participants, need min_replicas {cfg.min_replicas} {metadata}",
        )

    # Anti split-brain: a quorum must represent a strict majority of every
    # replica the lighthouse believes is alive.
    if len(healthy_participants) <= len(healthy_replicas) // 2:
        return (
            None,
            f"New quorum not ready, only have {len(healthy_participants)} "
            f"participants, need at least half of {len(healthy_replicas)} "
            f"healthy workers {metadata}",
        )

    all_healthy_joined = len(healthy_participants) == len(healthy_replicas)
    first_joined = min(
        (d.joined for d in healthy_participants.values()), default=now
    )
    if (
        not all_healthy_joined
        and now - first_joined < cfg.join_timeout_ms / 1000.0
    ):
        return (
            None,
            f"Valid quorum with {len(healthy_participants)} participants, "
            f"waiting for {len(healthy_replicas) - len(healthy_participants)} "
            f"healthy but not participating stragglers due to join timeout "
            f"{metadata}",
        )

    # Hold-the-shrink: a freshly-dead prev member still has a fresh
    # heartbeat for up to heartbeat_timeout, so the join-timeout path above
    # would issue a SHRUNK quorum first — and promotion (which replaces
    # dead members of prev_quorum) could then never fire.  While a warm
    # spare is registered and a prev member is absent-but-heartbeat-fresh,
    # defer the shrink until the heartbeat verdict lands: either the member
    # re-registers (fast quorum) or its heartbeat expires and the promotion
    # above replaces it in the same computation.  Bounded by join+heartbeat
    # timeouts so a wedged replica that keeps heartbeating but never
    # re-registers is still shed, just one heartbeat window later.
    if (
        allow_promote
        and _spare_promote_enabled()
        and not shrink_only
        and state.spares
        and state.prev_quorum
    ):
        missing_fresh = sorted(
            rid
            for rid in (m.replica_id for m in state.prev_quorum.participants)
            if rid not in healthy_participants and rid in healthy_replicas
        )
        # the hold window runs per missing member from ITS first observed
        # absence (a re-registered or heartbeat-expired member drops out
        # of missing_fresh and its anchor is pruned); a wedged member that
        # keeps beating but never re-registers escapes the hold after the
        # bounded window, so the shrink is delayed, never denied
        for rid in list(state.hold_since):
            if rid not in missing_fresh:
                del state.hold_since[rid]
        # same laxer liveness bound promotion eligibility uses: the hold
        # must never wait for a verdict the promotion would then refuse
        spare_fresh = any(
            _beat_fresh(
                state,
                rid,
                now,
                _SPARE_FRESH_FACTOR * hb_timeout_s,
                agg_timeout_s,
                grace_s,
            )
            for rid in state.spares
        )
        hold_window_s = (
            cfg.join_timeout_ms + cfg.heartbeat_timeout_ms
        ) / 1000.0
        held = [
            rid
            for rid in missing_fresh
            if spare_fresh
            and now - state.hold_since.setdefault(rid, now) < hold_window_s
        ]
        if held:
            return None, (
                f"Holding shrink: prev member(s) {', '.join(held)} "
                f"absent but heartbeat-fresh with a warm spare registered — "
                f"waiting for the heartbeat verdict (rejoin or promotion) "
                f"{metadata}"
            )

    return candidates, f"Valid quorum found {metadata}"


def _quorum_changed(a: List[QuorumMember], b: List[QuorumMember]) -> bool:
    return [m.replica_id for m in a] != [m.replica_id for m in b]


class LighthouseServer:
    """Threaded lighthouse server.

    The reference runs this as a tokio service inside either the standalone
    ``torchft_lighthouse`` binary or the training process via pyo3
    (``src/lib.rs:609-671``); here it is a daemon-threaded object you
    construct and ``shutdown()``.
    """

    def __init__(
        self,
        bind: str = "0.0.0.0:0",
        min_replicas: int = 1,
        join_timeout_ms: int = 100,
        quorum_tick_ms: int = 100,
        heartbeat_timeout_ms: int = 5_000,
    ) -> None:
        # NB: the pyo3 binding defaults join_timeout_ms to 100 for tests
        # (src/lib.rs:609-671); the CLI default is 60s.
        self._cfg = LighthouseConfig(
            min_replicas=min_replicas,
            bind=bind,
            join_timeout_ms=join_timeout_ms,
            quorum_tick_ms=quorum_tick_ms,
            heartbeat_timeout_ms=heartbeat_timeout_ms,
        )
        self._state = _State()
        self._lock = threading.Condition()
        self._generation = 0  # bumped on every broadcast quorum
        self._change_reason: Optional[str] = None
        self._shutdown = False
        # rate limit for the proactive tick quorum requests run: at fleet
        # scale a registration storm would otherwise run one O(members)
        # quorum_compute PER request (O(N^2) per round); the background
        # tick loop bounds the added latency to one tick interval
        self._last_tick_ts = 0.0
        # delta-coded broadcasts (wire v4): recently issued quorums by
        # content digest (the delta bases requesters may advertise) and a
        # small cache of encoded response payloads — one delta/full build
        # per (base, new) pair per round instead of one per parked waiter
        self._recent_quorums: Dict[int, Quorum] = {}
        self._payload_cache: Dict[tuple, tuple] = {}
        self._payload_lock = threading.Lock()
        # cached /status snapshot: (built_ts, snapshot dict, json bytes);
        # rebuilt at most once per TORCHFT_STATUS_TTL_S so status polls
        # never contend on the quorum state lock.  status_lock_acquires
        # counts actual rebuilds (the regression gate for status storms).
        self._status_cache: Tuple[float, Optional[dict], bytes] = (
            float("-inf"),
            None,
            b"",
        )
        self._status_cache_lock = threading.Lock()
        self.status_lock_acquires = 0
        # /metrics rides the SAME TTL-cached snapshot; the rendered text is
        # cached per snapshot build, so a scrape storm costs neither a
        # state-lock acquire nor a re-render
        self._metrics_cache: Tuple[float, bytes] = (float("-inf"), b"")
        self._metrics_cache_lock = threading.Lock()
        # coordination-plane flight recorder: quorum issues, promotions,
        # swaps and evictions land here (replica_id "lighthouse" in merged
        # fleet timelines)
        self._flight = FlightRecorder(replica_id="lighthouse")
        # inbound RPC counters by MsgType (the aggregation win is measured
        # here: agg flushes replace per-member heartbeat RPCs)
        self._inbound_counts: Dict[int, int] = {}
        self._inbound_counts_lock = threading.Lock()
        # parked quorum waiters (token → member), re-registered atomically
        # when a quorum excludes them — see _tick_locked
        self._parked: Dict[object, QuorumMember] = {}
        # live client connections, severed at shutdown — a "dead" lighthouse
        # must look dead to connected managers (kill/restart chaos relies on
        # it; the reference's process exit severs everything for free)
        self._conns: set = set()
        self._conns_lock = threading.Lock()

        self._sock = create_listener(bind, backlog=512)
        self._port: int = self._sock.getsockname()[1]

        self._accept_thread = threading.Thread(
            target=self._serve, name="tpuft_lighthouse_accept", daemon=True
        )
        self._accept_thread.start()
        self._tick_thread = threading.Thread(
            target=self._run_ticks, name="tpuft_lighthouse_tick", daemon=True
        )
        self._tick_thread.start()
        logger.info("Lighthouse listening on %s", self.address())

    # -- public surface ----------------------------------------------------

    @property
    def port(self) -> int:
        return self._port

    def address(self) -> str:
        return f"{socket.gethostname()}:{self._port}"

    def local_address(self) -> str:
        return f"127.0.0.1:{self._port}"

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        with self._lock:
            self._lock.notify_all()

    # -- tick loop ---------------------------------------------------------

    def _run_ticks(self) -> None:
        while not self._shutdown:
            time.sleep(self._cfg.quorum_tick_ms / 1000.0)
            with self._lock:
                self._tick_locked()

    def _log_if_changed(self, reason: str) -> None:
        if reason != self._change_reason:
            logger.info("Quorum status: %s", reason)
            self._change_reason = reason

    def _tick_locked(self) -> None:
        """One quorum decision round (``src/lighthouse.rs:292-343``)."""
        self._last_tick_ts = time.monotonic()
        participants, reason = quorum_compute(time.monotonic(), self._state, self._cfg)
        self._log_if_changed(reason)
        if participants is None:
            return

        commit_failure_ids = [
            m.replica_id for m in participants if m.commit_failures > 0
        ]
        state = self._state
        # eviction accounting is transition-based: a replica entering the
        # evicted set of an ISSUED quorum counts once per continuous
        # eviction episode, independent of membership-change ordering
        newly_shed = [
            r for r in state.evicted_now if r not in state.evicted_prev
        ]
        state.evicted_prev = set(state.evicted_now)
        if newly_shed:
            state.evictions_total += len(newly_shed)
            logger.warning(
                "quorum sheds slow replica(s): %s", ", ".join(newly_shed)
            )
        # degraded floor evictions: same transition-based accounting
        newly_floor_shed = [
            r
            for r in state.degraded_evicted_now
            if r not in state.degraded_evicted_prev
        ]
        state.degraded_evicted_prev = set(state.degraded_evicted_now)
        if newly_floor_shed:
            state.degraded_evictions_total += len(newly_floor_shed)
            logger.warning(
                "quorum evicts replica(s) wounded below the capacity "
                "floor: %s",
                ", ".join(newly_floor_shed),
            )
        if state.prev_quorum is None or _quorum_changed(
            participants, state.prev_quorum.participants
        ):
            state.quorum_id += 1
            logger.info("Detected quorum change, bumping quorum_id to %d", state.quorum_id)
        elif commit_failure_ids:
            state.quorum_id += 1
            logger.info(
                "Detected commit failures in [%s], bumping quorum_id to %d",
                ", ".join(commit_failure_ids),
                state.quorum_id,
            )

        hb_timeout_s = self._cfg.heartbeat_timeout_ms / 1000.0
        agg_timeout_s, grace_s = _agg_freshness_knobs(hb_timeout_s)
        now = time.monotonic()
        quorum = Quorum(
            quorum_id=state.quorum_id,
            participants=list(participants),
            created=time.time(),
            # registered healthy spares ride the version-gated tail: every
            # member (and each spare itself) learns the spare set without
            # the spares ever counting as membership
            spares=sorted(
                (
                    d.member
                    for rid, d in state.spares.items()
                    if _beat_fresh(
                        state,
                        rid,
                        now,
                        _SPARE_FRESH_FACTOR * hb_timeout_s,
                        agg_timeout_s,
                        grace_s,
                    )
                ),
                key=lambda m: m.replica_id,
            ),
        )
        state.prev_quorum = quorum
        state.participants.clear()
        state.hold_since.clear()  # fresh prev quorum, fresh hold anchors
        # flight feed: the coordination plane's side of the fleet timeline
        # (record() is a lock-free deque append — safe under the big lock)
        issue_step = max((m.step for m in quorum.participants), default=-1)
        self._flight.set_context(step=issue_step, quorum_id=state.quorum_id)
        self._flight.record(
            FlightEvent.QUORUM_ISSUE,
            world=len(quorum.participants),
            spares=len(quorum.spares),
        )
        for rid in state.promoted_now:
            self._flight.record(FlightEvent.SPARE_PROMOTE, replica=rid)
        for rid in state.swapped_now:
            self._flight.record(FlightEvent.DEGRADED_SWAP, replica=rid)
        for rid in newly_shed:
            self._flight.record(FlightEvent.EVICT_SLOW, replica=rid)
        for rid in newly_floor_shed:
            self._flight.record(FlightEvent.DEGRADED_EVICT, replica=rid)
        # delta-base ring: waiters advertising this quorum's digest on
        # later rounds receive membership deltas instead of full snapshots
        digest = quorum_digest(quorum)
        quorum._digest = digest
        self._recent_quorums[digest] = quorum
        while len(self._recent_quorums) > _RECENT_QUORUMS_MAX:
            self._recent_quorums.pop(next(iter(self._recent_quorums)))
        # spare registrations are STICKY (unlike participants): a spare
        # spends most of its time warming, not parked on a quorum RPC, and
        # promotion must find it registered the instant an active dies.
        # Dead spares are pruned on heartbeat age instead.
        for rid in [
            rid
            for rid in state.spares
            if now - state.heartbeats.get(rid, float("-inf"))
            > 4 * hb_timeout_s
        ]:
            del state.spares[rid]
            state.spare_ids.discard(rid)
        # Atomically re-register parked waiters the new quorum excluded.
        # The reference re-registers from the waiter's own loop
        # (src/lighthouse.rs:534-543), which can livelock when fast-stepping
        # members re-request (and proactively tick) before an excluded
        # waiter's thread wakes; doing it here closes that race.
        included = {m.replica_id for m in quorum.participants}
        for member in self._parked.values():
            if member.replica_id not in included:
                # NOT an implicit heartbeat: a replica that died while its
                # request was parked must age out on the normal heartbeat
                # timeout, not stay "alive" until its request deadline
                self._register(member, refresh_heartbeat=False)
        self._generation += 1
        self._lock.notify_all()

    # -- connection handling ----------------------------------------------

    def _serve(self) -> None:
        while not self._shutdown:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            configure_server_socket(conn)
            with self._conns_lock:
                if self._shutdown:
                    conn.close()
                    continue
                self._conns.add(conn)
            threading.Thread(
                target=self._handle_conn,
                args=(conn,),
                name="tpuft_lighthouse_conn",
                daemon=True,
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            # Peek enough bytes to distinguish HTTP from framed RPC; a slow
            # sender may deliver the first bytes across several segments.
            conn.settimeout(10.0)
            head = b""
            sniff_deadline = time.monotonic() + 10.0
            while len(head) < 4:
                head = conn.recv(4, socket.MSG_PEEK)
                if not head or time.monotonic() > sniff_deadline:
                    if len(head) < 4:
                        return
                if len(head) < 4:
                    time.sleep(0.01)
            conn.settimeout(None)
            if head[:3] in (b"GET", b"POS", b"HEA"):
                self._handle_http(conn)
                return
            while True:
                msg_type, r = recv_frame(conn)
                with self._inbound_counts_lock:
                    self._inbound_counts[msg_type] = (
                        self._inbound_counts.get(msg_type, 0) + 1
                    )
                if msg_type == MsgType.LH_QUORUM_REQ:
                    self._handle_quorum(conn, r)
                elif msg_type == MsgType.LH_HEARTBEAT_REQ:
                    replica_id = r.string()
                    # optional comm-health tail (flag byte + CommHealth);
                    # absent on legacy clients
                    health = None
                    if not r.done() and r.u8():
                        health = CommHealth.decode(r)
                    # optional v4 spare warm-step tail (flag byte + i64)
                    warm_step = None
                    if not r.done() and r.u8():
                        warm_step = r.i64()
                    # optional v5 degraded-capacity tail (flag byte + f64)
                    capacity = None
                    if not r.done() and r.u8():
                        capacity = r.f64()
                    with self._lock:
                        now = time.monotonic()
                        state = self._state
                        state.heartbeats[replica_id] = now
                        # a direct beat resets the reporting path: this
                        # member's liveness is judged without agg grace
                        state.via_agg.pop(replica_id, None)
                        if health is not None:
                            note_health(state, replica_id, health, now)
                        if warm_step is not None:
                            _note_warm_step(state, replica_id, warm_step)
                        if capacity is not None:
                            _note_capacity(state, replica_id, capacity)
                    send_frame(conn, MsgType.LH_HEARTBEAT_RESP)
                elif msg_type == MsgType.LH_AGG_BEAT_REQ:
                    # one aggregator flush: every member beat it batched
                    # since the last flush lands under ONE lock acquisition
                    agg = AggBeat.decode(r)
                    with self._lock:
                        now = time.monotonic()
                        state = self._state
                        state.agg_last[agg.agg_id] = now
                        for beat in agg.beats:
                            state.heartbeats[beat.replica_id] = now
                            state.via_agg[beat.replica_id] = agg.agg_id
                            if beat.health is not None:
                                note_health(
                                    state, beat.replica_id, beat.health, now
                                )
                            if beat.role == ROLE_SPARE and beat.warm_step >= 0:
                                _note_warm_step(
                                    state, beat.replica_id, beat.warm_step
                                )
                    send_frame(conn, MsgType.LH_AGG_BEAT_RESP)
                elif msg_type == MsgType.LH_STATUS_REQ:
                    # serve the CACHED pre-serialized snapshot: blob() is
                    # wire-identical to string() (u32 length + utf-8
                    # bytes), so the client's r.string() reads it while
                    # this path pays zero per-poll json.dumps — the same
                    # O(members) cost the TTL cache amortizes for HTTP
                    send_frame(
                        conn,
                        MsgType.LH_STATUS_RESP,
                        Writer().blob(self._status_json()).payload(),
                    )
                else:
                    send_error(conn, ErrCode.INVALID, f"bad lighthouse op {msg_type}")
        except (ConnectionError, OSError, WireError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _register(
        self, requester: QuorumMember, refresh_heartbeat: bool = True
    ) -> None:
        now = time.monotonic()
        state = self._state
        rid = requester.replica_id
        if refresh_heartbeat:
            state.heartbeats[rid] = now  # implicit heartbeat
        if requester.role == ROLE_SPARE and rid not in state.promoted:
            state.spares[rid] = _MemberDetails(joined=now, member=requester)
            state.spare_ids.add(rid)
            state.participants.pop(rid, None)
            return
        if requester.role != ROLE_SPARE:
            # an explicit active registration acknowledges a promotion (or
            # was never a spare); either way this id now counts as active
            state.promoted.discard(rid)
            state.spare_ids.discard(rid)
        if requester.capacity >= 1.0:
            # a full-capacity registration lifts the swapped-out exclusion:
            # the wounded replica healed (or restarted full-width) and is
            # an ordinary candidate again
            state.degraded_swapped.discard(rid)
        state.spares.pop(rid, None)
        state.participants[rid] = _MemberDetails(joined=now, member=requester)

    def _handle_quorum(self, conn: socket.socket, r: Reader) -> None:
        requester = QuorumMember.decode(r)
        timeout_ms = r.u64()
        # v3 role tail (absent on legacy clients); v4 adds the delta base:
        # the digest of the last quorum this requester decoded, so the
        # response can be a membership delta instead of the full list
        base_digest: Optional[int] = None
        if not r.done():
            tail_version = r.u32()
            if tail_version >= 3:
                requester.role = r.u8()
            if tail_version >= 4 and r.boolean():
                r.i64()  # base quorum_id (diagnostic only)
                base_digest = r.u64()
            if tail_version >= 5:
                requester.capacity = min(1.0, max(0.0, r.f64()))
        deadline = time.monotonic() + timeout_ms / 1000.0
        logger.info("Received quorum request for replica %s", requester.replica_id)

        token = object()
        failure: Optional[Tuple[ErrCode, str]] = None
        promoted_fast = False
        with self._lock:
            self._register(requester)
            # Promotion fast-path: a spare the tick loop promoted INTO the
            # standing quorum was (by design) probably warming, not parked,
            # when that quorum was issued — parking it for the NEXT quorum
            # would deadlock against actives already blocked in mesh
            # rendezvous waiting for it.  Hand it the standing quorum now.
            # The ``promoted`` pin is REQUIRED alongside prev membership:
            # a crashed active relaunched by its supervisor as role=spare
            # under the same replica_id also matches prev.participants, and
            # handing THAT cold process the standing quorum would let it
            # join collectives on fresh state (heal=False when the prev
            # member's step equals max_step) — it must park and re-enter
            # as an ordinary warming spare instead.
            if requester.role == ROLE_SPARE:
                prev = self._state.prev_quorum
                if (
                    prev is not None
                    and requester.replica_id in self._state.promoted
                    and any(
                        p.replica_id == requester.replica_id
                        for p in prev.participants
                    )
                ):
                    quorum = prev
                    promoted_fast = True
        if promoted_fast:
            conn.settimeout(30.0)
            try:
                self._send_quorum_resp(conn, quorum, base_digest)
            finally:
                conn.settimeout(None)
            return
        with self._lock:
            self._parked[token] = requester
            gen = self._generation
            try:
                # proactive tick, rate-limited: a fleet-scale registration
                # storm must not run one O(members) quorum_compute per
                # request — the background tick loop (and the requests that
                # do win the rate gate) bound added latency to ~one tick
                if (
                    time.monotonic() - self._last_tick_ts
                    >= 0.5 * self._cfg.quorum_tick_ms / 1000.0
                ):
                    self._tick_locked()
                while True:
                    if self._generation > gen:
                        gen = self._generation
                        quorum = self._state.prev_quorum
                        assert quorum is not None
                        # spares receive EVERY issued quorum (their live view
                        # of membership + max_step); a promoted spare shows
                        # up in participants and learns it from the result
                        if requester.role == ROLE_SPARE or any(
                            p.replica_id == requester.replica_id
                            for p in quorum.participants
                        ):
                            break
                        # Quorum formed without us; _tick_locked already
                        # re-registered us atomically — just keep waiting.
                        logger.info(
                            "Replica %s not in quorum, retrying",
                            requester.replica_id,
                        )
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._shutdown:
                        failure = (
                            ErrCode.SHUTDOWN if self._shutdown else ErrCode.TIMEOUT,
                            f"quorum request for {requester.replica_id!r} "
                            f"{'aborted by shutdown' if self._shutdown else 'timed out'}",
                        )
                        break
                    self._lock.wait(min(remaining, 0.1))
            finally:
                del self._parked[token]

        # socket IO strictly outside the server lock: one dead/slow client's
        # full TCP buffer must never wedge the lighthouse
        conn.settimeout(30.0)
        try:
            if failure is not None:
                send_error(conn, failure[0], failure[1])
                return
            self._send_quorum_resp(conn, quorum, base_digest)
        finally:
            conn.settimeout(None)

    def _quorum_payload(
        self, quorum: Quorum, base_digest: Optional[int]
    ) -> Tuple[int, bytes]:
        """(msg_type, payload) answering one quorum request: a membership
        delta when the requester advertised a base this server still holds
        (and the pin allows v4), else the full snapshot.  Encoded payloads
        are cached per (base, new, version) so a thousand parked waiters
        cost one encode, not a thousand."""
        wire_version = manager_quorum_wire_version()
        new_digest = getattr(quorum, "_digest", None)
        if new_digest is None:
            new_digest = quorum_digest(quorum)
        # quorum_id/created ride the payload but not the digest (a
        # commit-failure round bumps quorum_id with identical membership),
        # so they must be part of the cache key
        issue = (quorum.quorum_id, quorum.created)
        if base_digest is not None and wire_version >= 4:
            base = self._recent_quorums.get(base_digest)
            if base is not None:
                key = ("delta", wire_version, base_digest, new_digest, issue)
                with self._payload_lock:
                    hit = self._payload_cache.get(key)
                if hit is not None:
                    return hit
                w = Writer()
                make_quorum_delta(base, quorum).encode(w)
                resp = (int(MsgType.LH_QUORUM_DELTA_RESP), w.payload())
                with self._payload_lock:
                    if len(self._payload_cache) > _PAYLOAD_CACHE_MAX:
                        self._payload_cache.clear()
                    self._payload_cache[key] = resp
                return resp
        key = ("full", wire_version, new_digest, issue)
        with self._payload_lock:
            hit = self._payload_cache.get(key)
        if hit is not None:
            return hit
        w = Writer()
        quorum.encode(w)
        resp = (int(MsgType.LH_QUORUM_RESP), w.payload())
        with self._payload_lock:
            if len(self._payload_cache) > _PAYLOAD_CACHE_MAX:
                self._payload_cache.clear()
            self._payload_cache[key] = resp
        return resp

    def _send_quorum_resp(
        self, conn: socket.socket, quorum: Quorum, base_digest: Optional[int]
    ) -> None:
        msg_type, payload = self._quorum_payload(quorum, base_digest)
        send_frame(conn, msg_type, payload)

    # -- status / dashboard -------------------------------------------------

    def _status(self) -> dict:
        return self._status_snapshot()[0]

    def _status_json(self) -> bytes:
        return self._status_snapshot()[1]

    def _status_snapshot(self) -> Tuple[dict, bytes]:
        """Serve status from the TTL-cached snapshot: a status storm (the
        dashboard fleet) acquires the quorum state lock at most once per
        ``TORCHFT_STATUS_TTL_S``, and concurrent polls serialize on the
        cache lock, not the quorum loop."""
        ttl = knobs.get_float(STATUS_TTL_S_ENV, 0.5)
        now = time.monotonic()
        with self._status_cache_lock:
            built_ts, snap, raw = self._status_cache
            if snap is not None and now - built_ts < ttl:
                return snap, raw
            snap = self._status_rebuild()
            raw = json.dumps(snap, indent=2).encode()
            self._status_cache = (now, snap, raw)
            return snap, raw

    def _status_rebuild(self) -> dict:
        with self._lock:
            self.status_lock_acquires += 1
            now = time.monotonic()
            # quorum_compute writes state.evicted_now (the tick loop's
            # eviction-accounting channel); a status read must stay
            # side-effect free, so snapshot/restore it and disable the
            # spare-promotion mutation
            saved_evicted = list(self._state.evicted_now)
            _, reason = quorum_compute(
                now, self._state, self._cfg, allow_promote=False
            )
            self._state.evicted_now = saved_evicted
            prev = self._state.prev_quorum
            max_step = (
                max((p.step for p in prev.participants), default=-1) if prev else -1
            )
            # heal-path facts: who is behind (will recover on its next
            # quorum) and how many up-to-date peers can serve a striped heal
            lagging = [
                p.replica_id
                for p in (prev.participants if prev else [])
                if p.step < max_step
            ]
            return {
                # the rebuild's own clock: rate math over cached snapshots
                # must difference counters against THIS, not the caller's
                # poll time (a cached snapshot is up to one TTL stale)
                "now_monotonic": round(now, 3),
                "quorum_id": self._state.quorum_id,
                "quorum_status": reason,
                "max_step": max_step,
                "lagging_replicas": lagging,
                "num_heal_sources": (
                    len(prev.participants) - len(lagging) if prev else 0
                ),
                "num_participants": len(prev.participants) if prev else -1,
                "participants": [
                    {
                        "replica_id": p.replica_id,
                        "address": p.address,
                        "store_address": p.store_address,
                        "step": p.step,
                        "world_size": p.world_size,
                        # degraded-mode capacity column: 1.0 = full width;
                        # a dashboard spots wounded replicas at a glance
                        "capacity": p.capacity,
                    }
                    for p in (prev.participants if prev else [])
                ],
                "heartbeats": {
                    rid: now - ts for rid, ts in self._state.heartbeats.items()
                },
                # gray-failure health column: per-replica comm-health rates
                # (from heartbeat CommHealth summaries) + straggler flags
                "health": {
                    rid: {
                        "stall_rate": round(h.stall_rate, 1),
                        "reconnect_rate": round(h.reconnect_rate, 3),
                        "tx_rate": round(h.tx_rate, 1),
                        "lane_reconnects": h.reconnects,
                        "lane_failovers": h.failovers,
                        "flagged": h.flagged,
                    }
                    for rid, h in self._state.health.items()
                    if h.last is not None
                },
                "evict_slow_enabled": _evict_slow_enabled(),
                "evicted_replicas": list(self._state.evicted_now),
                "evictions_total": self._state.evictions_total,
                # hot-spare table: who is parked warm, how far each shadow
                # lags the commit front, and how many promotions have fired.
                # A spare's "step" is the warm watermark it reported with
                # its last registration — warm_lag_steps is the promotion
                # cost in fragment deltas.
                "spare_promote_enabled": _spare_promote_enabled(),
                "spares": [
                    {
                        "replica_id": d.member.replica_id,
                        "address": d.member.address,
                        "warm_step": d.member.step,
                        "warm_lag_steps": max(0, max_step - d.member.step)
                        if max_step >= 0
                        else None,
                        "heartbeat_age_s": round(
                            now
                            - self._state.heartbeats.get(
                                d.member.replica_id, now
                            ),
                            2,
                        ),
                    }
                    for _rid, d in sorted(self._state.spares.items())
                ],
                "promotions_total": self._state.promotions_total,
                # degraded-mode ladder facts: who is wounded (and how
                # deep), who a spare swapped out, and the floor/eviction
                # policy counters — served from this same TTL-cached
                # snapshot, so the dashboard fleet adds no lock traffic
                "degraded_replicas": [
                    {"replica_id": p.replica_id, "capacity": p.capacity}
                    for p in (prev.participants if prev else [])
                    if p.capacity < 1.0
                ],
                "degraded_swapped_out": sorted(self._state.degraded_swapped),
                "degraded_min_frac": _degraded_min_frac(),
                "degraded_swap_enabled": _degraded_swap_enabled(),
                "degraded_evictions_total": (
                    self._state.degraded_evictions_total
                ),
                "swaps_total": self._state.swaps_total,
                # hierarchical coordination plane: aggregator flush ages +
                # which members currently report via an aggregator, and the
                # inbound RPC counters the aggregation win is measured by
                "aggregators": {
                    agg_id: round(now - ts, 2)
                    for agg_id, ts in sorted(self._state.agg_last.items())
                },
                "aggregated_members": len(self._state.via_agg),
                "rpc_counts": self._inbound_counts_by_name(),
                "status_rebuilds": self.status_lock_acquires,
            }

    def _inbound_counts_by_name(self) -> Dict[str, int]:
        with self._inbound_counts_lock:
            counts = dict(self._inbound_counts)
        out: Dict[str, int] = {}
        for mt, n in sorted(counts.items()):
            try:
                name = MsgType(mt).name
            except ValueError:
                name = f"0x{mt:x}"
            out[name] = n
        return out

    def _metrics_text(self) -> bytes:
        """Prometheus text built from the SAME TTL-cached status snapshot
        (`_status_snapshot`): a scrape storm acquires the quorum state lock
        at most once per ``TORCHFT_STATUS_TTL_S`` — identical contract to
        /status(.json) — and the rendered text is cached per snapshot
        build, keyed on the rebuild's own clock stamp."""
        snap, _raw = self._status_snapshot()
        key = snap["now_monotonic"]
        with self._metrics_cache_lock:
            cached_key, cached = self._metrics_cache
            if cached and cached_key == key:
                return cached
            rendered = self._render_metrics(snap).encode()
            self._metrics_cache = (key, rendered)
            return rendered

    @staticmethod
    def _render_metrics(snap: dict) -> str:
        sample = obs_metrics.metric_sample
        samples = [
            sample("torchft_lh_quorum_id", snap["quorum_id"]),
            sample("torchft_lh_max_step", snap["max_step"]),
            sample("torchft_lh_participants", snap["num_participants"]),
            sample("torchft_lh_heartbeating", len(snap["heartbeats"])),
            sample("torchft_lh_spares", len(snap["spares"])),
            sample(
                "torchft_lh_lagging_replicas", len(snap["lagging_replicas"])
            ),
            sample("torchft_lh_heal_sources", snap["num_heal_sources"]),
            sample("torchft_lh_promotions_total", snap["promotions_total"]),
            sample("torchft_lh_evictions_total", snap["evictions_total"]),
            sample(
                "torchft_lh_degraded_evictions_total",
                snap["degraded_evictions_total"],
            ),
            sample("torchft_lh_swaps_total", snap["swaps_total"]),
            sample(
                "torchft_lh_status_rebuilds_total", snap["status_rebuilds"]
            ),
            sample(
                "torchft_lh_aggregated_members", snap["aggregated_members"]
            ),
        ]
        for rid, age in sorted(snap["heartbeats"].items()):
            samples.append(
                sample(
                    "torchft_lh_heartbeat_age_seconds",
                    age,
                    {"replica_id": rid},
                )
            )
        for p in snap["participants"]:
            labels = {"replica_id": p["replica_id"]}
            samples.append(sample("torchft_lh_replica_step", p["step"], labels))
            samples.append(
                sample("torchft_lh_replica_capacity", p["capacity"], labels)
            )
        for rid, h in sorted(snap["health"].items()):
            labels = {"replica_id": rid}
            samples.append(
                sample("torchft_lh_stall_rate", h["stall_rate"], labels)
            )
            samples.append(
                sample(
                    "torchft_lh_replica_flagged",
                    1 if h["flagged"] else 0,
                    labels,
                )
            )
        for sp in snap["spares"]:
            samples.append(
                sample(
                    "torchft_lh_spare_warm_lag_steps",
                    sp["warm_lag_steps"],
                    {"replica_id": sp["replica_id"]},
                )
            )
        for msg_type, count in snap["rpc_counts"].items():
            samples.append(
                sample(
                    "torchft_lh_rpc_inbound_total",
                    count,
                    {"msg_type": msg_type},
                )
            )
        for agg_id, age in snap["aggregators"].items():
            samples.append(
                sample(
                    "torchft_lh_agg_flush_age_seconds",
                    age,
                    {"agg_id": agg_id},
                )
            )
        return obs_metrics.render(samples)

    def _handle_http(self, conn: socket.socket) -> None:
        """Minimal dashboard (``templates/status.html`` analog)."""
        path = read_http_path(conn)
        if path is None:
            return

        if path.startswith("/replica/") and path.endswith("/kill"):
            replica_id = path[len("/replica/") : -len("/kill")]
            ok, msg = self._kill_replica(replica_id)
            body = json.dumps({"ok": ok, "msg": msg}).encode()
            status = "200 OK" if ok else "404 Not Found"
            ctype = "application/json"
        elif path == "/status.json":
            body = self._status_json()
            status, ctype = "200 OK", "application/json"
        elif path == "/metrics":
            if knobs.get_bool("TORCHFT_METRICS", True):
                body = self._metrics_text()
                status = "200 OK"
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                body = b"metrics disabled\n"
                status, ctype = "404 Not Found", "text/plain"
        else:
            body = self._render_status_html().encode()
            status, ctype = "200 OK", "text/html; charset=utf-8"
        send_http_response(conn, status, ctype, body)

    def _kill_replica(self, replica_id: str) -> Tuple[bool, str]:
        """Dashboard kill button → Kill RPC at the replica's manager
        (``src/lighthouse.rs:454-479``)."""
        with self._lock:
            prev = self._state.prev_quorum
            addr = next(
                (
                    m.address
                    for m in (prev.participants if prev else [])
                    if m.replica_id == replica_id
                ),
                None,
            )
        if addr is None:
            return False, "failed to find replica"
        try:
            sock = connect(addr, timeout=10.0)
            send_frame(sock, MsgType.MGR_KILL_REQ, Writer().string("killed from dashboard").payload())
            sock.close()
            return True, f"kill sent to {replica_id}"
        except OSError as e:
            return False, f"kill failed: {e}"

    def _render_status_html(self) -> str:
        s = self._status()
        cards = "".join(
            f"<div class='card'><b>{html.escape(p['replica_id'])}</b>"
            f"<br>step {p['step']} · ws {p['world_size']}"
            + (
                f" · <b>capacity {p['capacity']:.2f}</b>"
                if p.get("capacity", 1.0) < 1.0
                else ""
            )
            + f"<br><code>{html.escape(p['address'])}</code>"
            f"<br><a href='/replica/{html.escape(p['replica_id'])}/kill'>kill</a></div>"
            for p in s["participants"]
        )
        beats = "".join(
            f"<li><code>{html.escape(rid)}</code>: {age:.1f}s ago</li>"
            for rid, age in sorted(s["heartbeats"].items())
        )
        health_rows = "".join(
            f"<tr><td><code>{html.escape(rid)}</code></td>"
            f"<td>{h['stall_rate']}</td><td>{h['lane_reconnects']}</td>"
            f"<td>{h['lane_failovers']}</td>"
            f"<td>{'FLAGGED' if h['flagged'] else 'ok'}</td></tr>"
            for rid, h in sorted(s["health"].items())
        )
        health_tbl = (
            "<h2>comm health</h2><table border=1 cellpadding=4>"
            "<tr><th>replica</th><th>stall rate /s</th><th>reconnects</th>"
            "<th>failovers</th><th>status</th></tr>"
            f"{health_rows}</table>"
            f"<p>evict_slow={'on' if s['evict_slow_enabled'] else 'off'}"
            f" · evicted now={html.escape(', '.join(s['evicted_replicas']) or 'none')}"
            f" · evictions_total={s['evictions_total']}</p>"
            if health_rows
            else ""
        )
        spare_rows = "".join(
            f"<tr><td><code>{html.escape(sp['replica_id'])}</code></td>"
            f"<td>{sp['warm_step']}</td><td>{sp['warm_lag_steps']}</td>"
            f"<td>{sp['heartbeat_age_s']}s</td>"
            f"<td><code>{html.escape(sp['address'])}</code></td></tr>"
            for sp in s["spares"]
        )
        spare_tbl = (
            "<h2>hot spares</h2><table border=1 cellpadding=4>"
            "<tr><th>spare</th><th>warm step</th><th>lag (steps)</th>"
            "<th>beat</th><th>address</th></tr>"
            f"{spare_rows}</table>"
            f"<p>spare_promote="
            f"{'on' if s['spare_promote_enabled'] else 'off'}"
            f" · promotions_total={s['promotions_total']}</p>"
            if spare_rows or s["promotions_total"]
            else ""
        )
        return (
            "<html><head><title>torchft_tpu lighthouse</title><style>"
            "body{font-family:monospace;margin:2em}.card{border:1px solid #999;"
            "display:inline-block;padding:1em;margin:.5em}</style></head><body>"
            f"<h1>torchft_tpu lighthouse</h1>"
            f"<p>quorum_id={s['quorum_id']} · status: {html.escape(s['quorum_status'])}</p>"
            f"<p>max_step={s['max_step']} · participants={s['num_participants']}"
            f" · heal sources={s['num_heal_sources']}"
            f" · lagging={html.escape(', '.join(s['lagging_replicas']) or 'none')}</p>"
            f"{cards}{health_tbl}{spare_tbl}"
            f"<h2>heartbeats</h2><ul>{beats}</ul></body></html>"
        )


class LighthouseClient(RpcClient):
    """Client for :class:`LighthouseServer` (pyo3 analog ``src/lib.rs:486-594``).

    Under wire v4 the client caches the last quorum it decoded and
    advertises its digest on every request; the server answers with a
    membership delta (``LH_QUORUM_DELTA_RESP``) when it still holds that
    base, and with the full snapshot otherwise — so steady-state broadcast
    bytes are O(changes), not O(members).  ``delta_responses`` /
    ``full_responses`` count which path each round took (harness +
    observability input)."""

    def __init__(self, addr: str, connect_timeout: float = 60.0) -> None:
        super().__init__(addr, connect_timeout=connect_timeout)
        # delta-coded broadcast cache: mutated only inside quorum(), which
        # callers serialize like every other rpc on this client
        self._quorum_cache: Optional[Quorum] = None
        self._quorum_cache_digest = 0
        self.delta_responses = 0
        self.full_responses = 0

    def quorum(
        self,
        replica_id: str,
        timeout: float,
        address: str = "",
        store_address: str = "",
        step: int = 0,
        world_size: int = 1,
        shrink_only: bool = False,
        commit_failures: int = 0,
        data: Optional[dict] = None,
        role: int = ROLE_ACTIVE,
        capacity: float = 1.0,
    ) -> Quorum:
        """Block until a quorum containing this replica is issued (or, for
        ``role=ROLE_SPARE``, until ANY quorum is issued — the spare's live
        view of membership and the commit front).

        ``data`` is an arbitrary JSON-serializable dict carried opaquely in
        the member record (``src/lib.rs:430-451``).
        """
        member = QuorumMember(
            replica_id=replica_id,
            address=address,
            store_address=store_address,
            step=step,
            world_size=world_size,
            shrink_only=shrink_only,
            commit_failures=commit_failures,
            data=json.dumps(data) if data else "",
            role=role,
        )
        w = Writer()
        member.encode(w)
        w.u64(int(timeout * 1000))
        wire_version = manager_quorum_wire_version()
        if role != ROLE_ACTIVE and wire_version < 3:
            # never degrade silently: dropping the role tail would
            # register this spare as a full ACTIVE (counted toward
            # min_replicas/majority) on the lighthouse
            raise ValueError(
                f"role={role} requires quorum wire v3 "
                f"({WIRE_COMPAT_ENV} pins an older version)"
            )
        base = self._quorum_cache if wire_version >= 4 else None
        has_capacity_tail = wire_version >= 5 and capacity != 1.0
        if wire_version >= 4:
            # v4 tail: role + the delta base this client can apply edits
            # to.  A v3 (or older) server reads the role and ignores the
            # rest; it can only ever answer with a full snapshot.  v5
            # appends the degraded-capacity fraction, emitted only when
            # this replica is actually wounded — a full-capacity request
            # stays byte-identical to v4 (a full-capacity registration is
            # also how a healed replica advertises its restoration).
            w.u32(5 if has_capacity_tail else 4)
            w.u8(role)
            w.boolean(base is not None)
            if base is not None:
                w.i64(base.quorum_id)
                w.u64(self._quorum_cache_digest)
            if has_capacity_tail:
                w.f64(capacity)
        elif role != ROLE_ACTIVE:
            # version-gated v3 tail: active members stay byte-identical to
            # v2 (a legacy or native-tier lighthouse never sees spare
            # frames)
            w.u32(3)
            w.u8(role)
        msg_type, r = self.call(MsgType.LH_QUORUM_REQ, w.payload(), timeout)
        raise_if_error(msg_type, r)
        if msg_type == MsgType.LH_QUORUM_DELTA_RESP:
            delta = QuorumDelta.decode(r)
            try:
                quorum = apply_quorum_delta(
                    base, delta, base_digest=self._quorum_cache_digest
                )
            except WireError:
                # divergent base: clear the cache so the retry advertises
                # no base and receives a full snapshot
                self._quorum_cache = None
                raise
            self.delta_responses += 1
        else:
            quorum = Quorum.decode(r)
            self.full_responses += 1
        if wire_version >= 4:
            self._quorum_cache = quorum
            self._quorum_cache_digest = quorum_digest(quorum)
        return quorum

    def heartbeat(
        self,
        replica_id: str,
        timeout: float = 5.0,
        health: Optional[CommHealth] = None,
        warm_step: Optional[int] = None,
        capacity: Optional[float] = None,
    ) -> None:
        """Heartbeat, optionally carrying a cumulative comm-health summary
        (straggler detection input), a spare warm-step watermark under wire
        v4 (keeps the lighthouse's promotion-eligibility view fresh at beat
        cadence), and a degraded-capacity fraction under wire v5 (keeps
        the wound→swap→evict ladder fresh at beat cadence; emitted only
        when degraded, so full-capacity beats stay byte-identical to v4).
        Idempotent: one reconnect-retry rides out a lighthouse connection
        blip instead of crashing the sender."""
        w = Writer().string(replica_id)
        send_warm = warm_step is not None and manager_quorum_wire_version() >= 4
        send_cap = (
            capacity is not None
            and capacity != 1.0
            and manager_quorum_wire_version() >= 5
        )
        if health is not None or send_warm or send_cap:
            w.u8(1 if health is not None else 0)
            if health is not None:
                health.encode(w)
        if send_warm or send_cap:
            w.u8(1 if send_warm else 0)
            if send_warm:
                w.i64(warm_step)
        if send_cap:
            w.u8(1)
            w.f64(capacity)
        msg_type, r = self.call(
            MsgType.LH_HEARTBEAT_REQ, w.payload(), timeout, idempotent=True
        )
        raise_if_error(msg_type, r)

    def status(self, timeout: float = 5.0) -> dict:
        msg_type, r = self.call(
            MsgType.LH_STATUS_REQ, b"", timeout, idempotent=True
        )
        raise_if_error(msg_type, r)
        return json.loads(r.string())


def lighthouse_main(argv: Optional[List[str]] = None) -> None:
    """CLI entry point (``src/bin/lighthouse.rs``)."""
    parser = argparse.ArgumentParser("torchft_tpu_lighthouse")
    parser.add_argument("--bind", default="0.0.0.0:29510")
    parser.add_argument("--min_replicas", type=int, required=True)
    parser.add_argument("--join_timeout_ms", type=int, default=60_000)
    parser.add_argument("--quorum_tick_ms", type=int, default=100)
    parser.add_argument("--heartbeat_timeout_ms", type=int, default=5_000)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = LighthouseServer(
        bind=args.bind,
        min_replicas=args.min_replicas,
        join_timeout_ms=args.join_timeout_ms,
        quorum_tick_ms=args.quorum_tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    lighthouse_main()
