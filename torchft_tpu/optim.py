"""Optimizer wrapper: the whole per-step protocol in two verbs.

Twin of the reference wrapper (``torchft/optim.py:24-63``) adapted to optax's
functional style: ``start_step()`` (the reference's ``zero_grad``) computes
the quorum, and ``apply()`` (the reference's ``step``) performs the optax
update only when ``manager.should_commit()`` voted yes — failed steps leave
params and optimizer state untouched, which is exactly how a discarded step
stays invisible.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from torchft_tpu.manager import Manager


class OptimizerWrapper:
    """Wraps an ``optax.GradientTransformation`` with the FT step protocol.

    Usage::

        opt = OptimizerWrapper(manager, optax.adam(3e-4))
        opt_state = opt.init(params)
        for batch in data:
            opt.start_step()                        # quorum (async) begins
            grads, aux = grad_fn(params, batch)     # compiled forward/backward
            grads = ft_allreduce(manager, grads)    # replica-dim average
            params, opt_state, committed = opt.apply(params, opt_state, grads)
    """

    def __init__(self, manager: Manager, tx: Any) -> None:
        self.manager = manager
        self.tx = tx

    def init(self, params: Any) -> Any:
        return self.tx.init(params)

    # -- the two verbs ------------------------------------------------------

    def start_step(self, **kwargs: Any) -> None:
        """Begin a step: compute quorum (``optim.py:48-50``)."""
        self.manager.start_quorum(**kwargs)

    # reference-compatible alias
    zero_grad = start_step

    def apply(
        self,
        params: Any,
        opt_state: Any,
        grads: Any,
        refresh: Optional[Any] = None,
    ) -> Tuple[Any, Any, bool]:
        """Commit-gated optimizer step (``optim.py:52-55``).

        Returns ``(params, opt_state, committed)``; on a failed vote the
        inputs are returned unchanged and the step is discarded.

        .. warning:: ``should_commit`` may *heal*: it applies a peer's
           checkpoint through the registered ``load_state_dict`` fns.  Torch
           params mutate in place so the reference gets the healed values for
           free; jax pytrees are immutable, so if your load fn writes into a
           holder, pass ``refresh=lambda: (params, opt_state)`` reading from
           that holder — it is called *after* the vote so the update applies
           to post-heal state.  (Or use :meth:`step` which handles this.)
        """
        if not self.manager.should_commit():
            return params, opt_state, False
        if refresh is not None:
            params, opt_state = refresh()
        params, opt_state = self._apply_update(params, opt_state, grads)
        return params, opt_state, True

    def step(self, holder: Any, grads: Any) -> bool:
        """In-place-style verb (the reference's ``optimizer.step()``):
        ``holder`` is a mutable mapping with ``"params"`` / ``"opt_state"``
        keys — the same object your registered state_dict fns read/write, so
        healing composes correctly.  Returns whether the step committed."""
        if not self.manager.should_commit():
            return False
        params, opt_state = self._apply_update(
            holder["params"], holder["opt_state"], grads
        )
        holder["params"] = params
        holder["opt_state"] = opt_state
        return True

    def _apply_update(self, params: Any, opt_state: Any, grads: Any):
        if not hasattr(self, "_cached_update"):
            import optax

            def _upd(params, opt_state, grads):
                updates, new_state = self.tx.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), new_state

            # donate params + opt_state: the update replaces them, and NOT
            # donating doubles resident params+optimizer HBM at the peak of
            # every step — the difference between a ~1B model fitting one
            # chip or OOMing.  Callers must treat the inputs as consumed
            # (step() swaps the holder entries; step_fn returns the new
            # pytrees); grads stay readable.
            self._cached_update = jax.jit(_upd, donate_argnums=(0, 1))
        return self._cached_update(params, opt_state, grads)
