"""ctypes bindings for the C++ runtime (``native/libtpuft.so``).

The reference ships its control plane as a Rust cdylib bound via pyo3
(``src/lib.rs``); torchft_tpu's equivalent is a C++ shared library bound via
ctypes (no pybind11 in the environment).  The C++ servers speak the exact
wire protocol of the Python implementations, so the Python clients
(``RpcClient`` subclasses) work against either — the classes here mirror the
Python servers' construction surface and are drop-in replacements.

The library is built on demand with ``make`` (g++ -O3); if the toolchain or
build fails, ``available()`` returns False and callers fall back to the
pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import logging
import os
import queue
import subprocess
import threading
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu import knobs
from torchft_tpu.communicator import (
    Buffers,
    Communicator,
    CommunicatorAborted,
    CommunicatorError,
    ReduceOp,
)
from torchft_tpu.futures import TimerHandle, schedule_timeout
from torchft_tpu.obs.flight import FlightEvent, FlightRecorder
from torchft_tpu.obs.spans import span as obs_span
from torchft_tpu.work import Work

logger = logging.getLogger(__name__)

# Mirror of native/comm.h kMaxIovSegs — the max payload iovec segments the
# NATIVE side packs into one sendmsg/recvmsg syscall (the binding itself
# passes arbitrarily many buffers; batching happens in C).  Declared here
# so the ftlint native-mirror checker pins the two sides together.
_MAX_IOV_SEGS = 64


def _native_dir() -> str:
    """Directory holding the native build.  Native sources live beside the
    repo checkout; for installed wheels (where no sibling native/ exists)
    point TORCHFT_NATIVE_DIR at a sources/lib dir.  Read through the typed
    knob accessor at call time so monkeypatched tests behave like every
    other knob."""
    return knobs.get_str(
        "TORCHFT_NATIVE_DIR",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "native",
        ),
    )

_lib: Optional[ctypes.CDLL] = None
_lib_error: Optional[str] = None
_lib_lock = threading.Lock()

_DTYPE_CODES = {
    "float32": 0,
    "float64": 1,
    "int32": 2,
    "int64": 3,
    "bfloat16": 4,
    "uint8": 5,
    "int8": 6,
}
_OP_CODES = {ReduceOp.SUM: 0, ReduceOp.AVG: 0, ReduceOp.MAX: 1, ReduceOp.MIN: 2}


def _build_lib(native_dir: str, lib_path: str) -> None:
    sources = [
        os.path.join(native_dir, f)
        for f in os.listdir(native_dir)
        if f.endswith((".cc", ".h"))
    ]
    if os.path.exists(lib_path):
        lib_mtime = os.path.getmtime(lib_path)
        if all(os.path.getmtime(s) <= lib_mtime for s in sources):
            return
    logger.info("building native runtime (make -C %s)", native_dir)
    subprocess.run(
        ["make", "-C", native_dir],
        check=True,
        capture_output=True,
        timeout=300,
    )


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_error
    with _lib_lock:
        if _lib is not None or _lib_error is not None:
            return _lib
        try:
            native_dir = _native_dir()
            lib_path = os.path.join(native_dir, "libtpuft.so")
            _build_lib(native_dir, lib_path)
            lib = ctypes.CDLL(lib_path)
        except Exception as e:  # noqa: BLE001
            _lib_error = str(e)
            logger.warning("native runtime unavailable: %s", e)
            return None

        lib.tpuft_last_error.restype = ctypes.c_char_p
        lib.tpuft_store_new.restype = ctypes.c_void_p
        lib.tpuft_store_new.argtypes = [ctypes.c_char_p]
        lib.tpuft_store_port.argtypes = [ctypes.c_void_p]
        lib.tpuft_store_free.argtypes = [ctypes.c_void_p]
        lib.tpuft_lighthouse_new.restype = ctypes.c_void_p
        lib.tpuft_lighthouse_new.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_uint64,
        ]
        lib.tpuft_lighthouse_port.argtypes = [ctypes.c_void_p]
        lib.tpuft_lighthouse_free.argtypes = [ctypes.c_void_p]
        lib.tpuft_manager_new.restype = ctypes.c_void_p
        lib.tpuft_manager_new.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_int64,
        ]
        lib.tpuft_manager_port.argtypes = [ctypes.c_void_p]
        lib.tpuft_manager_free.argtypes = [ctypes.c_void_p]
        lib.tpuft_comm_new.restype = ctypes.c_void_p
        lib.tpuft_comm_new.argtypes = [ctypes.c_double]
        lib.tpuft_comm_configure.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.tpuft_comm_allreduce.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.tpuft_comm_allreduce_iov.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.tpuft_comm_alltoall_ptrs.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
        ]
        lib.tpuft_comm_lane_stats.restype = ctypes.c_uint64
        lib.tpuft_comm_lane_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tpuft_comm_reduce_scatter.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tpuft_comm_broadcast.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_int64,
        ]
        lib.tpuft_comm_send.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_int64,
            ctypes.c_uint64,
        ]
        lib.tpuft_comm_recv_alloc.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tpuft_buffer_free.argtypes = [ctypes.c_void_p]
        lib.tpuft_comm_recv_into.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_uint64,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tpuft_comm_alltoall.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
        ]
        lib.tpuft_comm_allgather.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
        ]
        lib.tpuft_comm_flight_drain.restype = ctypes.c_uint64
        lib.tpuft_comm_flight_drain.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_uint64,
        ]
        lib.tpuft_comm_barrier.argtypes = [ctypes.c_void_p]
        lib.tpuft_comm_abort.argtypes = [ctypes.c_void_p]
        lib.tpuft_comm_free.argtypes = [ctypes.c_void_p]
        lib.tpuft_quantize_rowwise.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.tpuft_dequantize_rowwise.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.tpuft_reduce_rowwise.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# host quantization kernels (native/quant.h) — one-pass, multithreaded,
# -march=native; the numpy fallbacks in quantization.py make several full
# passes with temporaries and dominate the DCN quantized pipeline
# ---------------------------------------------------------------------------


def _check(lib: ctypes.CDLL, rc: int) -> None:
    if rc != 0:
        raise RuntimeError(lib.tpuft_last_error().decode())


def quantize_rowwise_native(
    flat: np.ndarray, row_size: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    lib = _load()
    if lib is None:
        return None
    flat = np.ascontiguousarray(flat, dtype=np.float32)
    n = flat.size
    rows = max(1, -(-n // row_size))
    q = np.empty((rows, row_size), np.int8)
    scales = np.empty(rows, np.float32)
    if n == 0:
        q[:] = 0
        scales[:] = 0.0
        return q, scales
    _check(
        lib,
        lib.tpuft_quantize_rowwise(
            _data_ptr(flat), n, row_size, _data_ptr(q), _data_ptr(scales)
        ),
    )
    return q, scales


def dequantize_rowwise_native(
    q: np.ndarray, scales: np.ndarray, n: int
) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    q = np.ascontiguousarray(q, dtype=np.int8)
    scales = np.ascontiguousarray(scales, dtype=np.float32)
    out = np.empty(n, np.float32)
    if n == 0:
        return out
    _check(
        lib,
        lib.tpuft_dequantize_rowwise(
            _data_ptr(q), _data_ptr(scales), n, q.shape[1], _data_ptr(out)
        ),
    )
    return out


def reduce_rowwise_native(
    qs: np.ndarray, scales: np.ndarray
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """qs int8 [w, rows, row_size], scales f32 [w, rows] → requantized
    (q [rows, row_size], scales [rows]) of the float32 sum."""
    lib = _load()
    if lib is None:
        return None
    qs = np.ascontiguousarray(qs, dtype=np.int8)
    scales = np.ascontiguousarray(scales, dtype=np.float32)
    w, rows, row_size = qs.shape
    q_out = np.empty((rows, row_size), np.int8)
    s_out = np.empty(rows, np.float32)
    _check(
        lib,
        lib.tpuft_reduce_rowwise(
            _data_ptr(qs),
            _data_ptr(scales),
            w,
            rows,
            row_size,
            _data_ptr(q_out),
            _data_ptr(s_out),
        ),
    )
    return q_out, s_out


def _data_ptr(arr: np.ndarray) -> ctypes.c_void_p:
    """C pointer to a contiguous array's data; extension dtypes (bfloat16)
    reject .ctypes on some views, so reinterpret through uint8."""
    if arr.dtype.name == "bfloat16":
        return arr.view(np.uint8).ctypes.data_as(ctypes.c_void_p)
    return arr.ctypes.data_as(ctypes.c_void_p)


def as_host_array(data) -> np.ndarray:
    """Zero-copy numpy view of any host buffer: numpy arrays pass through,
    buffer-protocol objects (bytes, bytearray, memoryview) come back as
    uint8 views, and dlpack-capable sources — JAX CPU arrays included —
    come back via ``np.from_dlpack`` (read-only, aliasing the producer's
    buffer).  Only objects that support none of those are copied
    (``np.asarray`` fallback).  The native data plane reads frames straight
    out of (and, for writable views, lands receives straight into) the
    returned array's memory — no staging copy."""
    if isinstance(data, np.ndarray):
        return data
    if hasattr(data, "__dlpack__"):
        # dlpack first for array-likes (jax CPU arrays): preserves
        # dtype/shape where the raw buffer protocol would flatten to bytes
        try:
            return np.from_dlpack(data)
        except (TypeError, AttributeError, RuntimeError, BufferError):
            pass
    try:
        # buffer protocol: bytes-like objects keep their exact bytes
        return np.frombuffer(memoryview(data).cast("B"), dtype=np.uint8)
    except TypeError:
        return np.asarray(data)


def _buffer_ptr(data) -> Tuple[ctypes.c_void_p, int, object]:
    """(pointer, nbytes, keepalive) into any contiguous buffer-protocol or
    dlpack-capable object with NO copy — the round-1 send path built
    intermediate ``bytes`` objects, a full-payload copy per hop.
    ``keepalive`` is the object that actually backs the pointer; the caller
    must pin it until the op is done (it is ``data`` itself unless a
    contiguity copy was required)."""
    arr = as_host_array(data)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    return _data_ptr(arr), int(arr.nbytes), (arr, data)


def _last_error(lib: ctypes.CDLL) -> str:
    return lib.tpuft_last_error().decode("utf-8", "replace")


# ---------------------------------------------------------------------------
# server wrappers (drop-in for the Python servers)
# ---------------------------------------------------------------------------


class CppStoreServer:
    def __init__(self, bind: str = "0.0.0.0:0") -> None:
        lib = _load()
        assert lib is not None, "native runtime unavailable"
        self._lib = lib
        self._h = lib.tpuft_store_new(bind.encode())
        if not self._h:
            raise RuntimeError(f"store server failed: {_last_error(lib)}")

    @property
    def port(self) -> int:
        return self._lib.tpuft_store_port(self._h)

    def local_address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def address(self) -> str:
        import socket

        return f"{socket.gethostname()}:{self.port}"

    def shutdown(self) -> None:
        if self._h:
            self._lib.tpuft_store_free(self._h)
            self._h = None


class CppLighthouseServer:
    def __init__(
        self,
        bind: str = "0.0.0.0:0",
        min_replicas: int = 1,
        join_timeout_ms: int = 100,
        quorum_tick_ms: int = 100,
        heartbeat_timeout_ms: int = 5000,
    ) -> None:
        lib = _load()
        assert lib is not None, "native runtime unavailable"
        self._lib = lib
        self._h = lib.tpuft_lighthouse_new(
            bind.encode(),
            min_replicas,
            join_timeout_ms,
            quorum_tick_ms,
            heartbeat_timeout_ms,
        )
        if not self._h:
            raise RuntimeError(f"lighthouse failed: {_last_error(lib)}")

    @property
    def port(self) -> int:
        return self._lib.tpuft_lighthouse_port(self._h)

    def local_address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def address(self) -> str:
        import socket

        return f"{socket.gethostname()}:{self.port}"

    def shutdown(self) -> None:
        if self._h:
            self._lib.tpuft_lighthouse_free(self._h)
            self._h = None


class CppManagerServer:
    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        hostname: str = "",
        bind: str = "0.0.0.0:0",
        store_addr: str = "",
        world_size: int = 1,
        heartbeat_interval: float = 0.1,
        connect_timeout: float = 10.0,
        quorum_retries: int = 0,
        health_fn: Optional[object] = None,
        role: int = 0,
        warm_fn: Optional[object] = None,
        warm_step_fn: Optional[object] = None,
        capacity_fn: Optional[object] = None,
        metrics_fn: Optional[object] = None,
    ) -> None:
        import socket

        # health_fn (comm-health heartbeat summaries for straggler
        # detection) is accepted for construction parity with the Python
        # ManagerServer but unused: the C++ sidecar sends legacy
        # heartbeats, which the lighthouse treats as "no health report".
        # warm_fn (spare warm-snapshot serving) and warm_step_fn (the
        # beat-carried spare warm watermark) likewise: the C++ sidecar
        # cannot host a spare or feed one — spare roles require the Python
        # tier (Manager(role="spare") refuses a native server_cls).
        # capacity_fn (the wire-v5 degraded-capacity fraction) likewise:
        # the C++ sidecar always registers full-width — a degraded-mode
        # replica needs the Python control plane (Manager refuses to
        # complete a re-lower on a native server_cls; docs/operations.md
        # §16 has the fallback matrix entry).
        # metrics_fn (/metrics gauges) likewise: the C++ sidecar serves no
        # HTTP endpoint — scrape the lighthouse for fleet-level facts.
        del health_fn, warm_fn, warm_step_fn, capacity_fn, metrics_fn
        if role != 0:
            raise ValueError(
                "CppManagerServer does not support the SPARE role; use the "
                "Python tier for spare replicas"
            )
        lib = _load()
        assert lib is not None, "native runtime unavailable"
        self._lib = lib
        self.role = role  # attribute parity with ManagerServer
        self._hostname = hostname or socket.gethostname()
        self._h = lib.tpuft_manager_new(
            replica_id.encode(),
            lighthouse_addr.encode(),
            self._hostname.encode(),
            bind.encode(),
            store_addr.encode(),
            world_size,
            heartbeat_interval,
            connect_timeout,
            quorum_retries,
        )
        if not self._h:
            raise RuntimeError(f"manager server failed: {_last_error(lib)}")

    @property
    def port(self) -> int:
        return self._lib.tpuft_manager_port(self._h)

    def address(self) -> str:
        return f"{self._hostname}:{self.port}"

    def shutdown(self) -> None:
        if self._h:
            self._lib.tpuft_manager_free(self._h)
            self._h = None


# ---------------------------------------------------------------------------
# CppCommunicator
# ---------------------------------------------------------------------------


class CppCommunicator(Communicator):
    """Data-plane communicator backed by the C++ runtime.

    Same semantics as :class:`torchft_tpu.communicator.TCPCommunicator`
    (repeatable configure, abort-poisons, per-op userspace timeouts) with the
    wire IO and reductions in native code.  ctypes releases the GIL during
    foreign calls, so the op thread never stalls Python.
    """

    def __init__(self, timeout_s: float = 60.0) -> None:
        lib = _load()
        assert lib is not None, "native runtime unavailable"
        self._lib = lib
        self._timeout_s = timeout_s
        self._h = lib.tpuft_comm_new(ctypes.c_double(timeout_s))
        self._rank = 0
        self._world_size = 1
        self._errored: Optional[Exception] = None
        self._lock = threading.Lock()
        self._epoch = 0
        self._ops: "queue.Queue[Optional[Tuple[Callable[[], object], Future]]]" = queue.Queue()
        self._op_thread: Optional[threading.Thread] = None
        # ops currently EXECUTING (the queue no longer holds them) — the
        # busy() probe's other half; own lock because overlapping old/new
        # epoch op threads can race the += / -= pair (same doctrine as
        # TCPCommunicator._inflight_ops)
        self._inflight_ops = 0
        self._inflight_lock = threading.Lock()
        # flight recorder attachment point (set by the owning Manager):
        # epoch lifecycle records Python-side, and the C-side fixed-slot
        # ring drains into every dump via tpuft_comm_flight_drain
        self.flight: Optional[FlightRecorder] = None
        self._flight_registered = False

    # -- lifecycle ---------------------------------------------------------

    def configure(
        self,
        store_addr: str,
        replica_id: str,
        rank: int,
        world_size: int,
        quorum_id: int = 0,
        group_rank: int = 0,
        group_world_size: int = 1,
        global_ranks: Sequence[int] = (),
    ) -> None:
        with self._lock:
            self._teardown_locked("superseded by reconfigure")
            self._epoch += 1
            epoch = self._epoch
            self._errored = None
            self._rank = rank
            self._world_size = world_size
        # the C configure blocks on rendezvous; run outside the lock
        rc = self._lib.tpuft_comm_configure(
            self._h, store_addr.encode(), rank, world_size
        )
        if rc != 0:
            err = CommunicatorError(
                f"configure failed: {_last_error(self._lib)}"
            )
            with self._lock:
                self._errored = err
            raise err
        with self._lock:
            if self._epoch != epoch:
                raise CommunicatorAborted("configure superseded")
            self._ops = queue.Queue()
            self._op_thread = threading.Thread(
                target=self._run_ops,
                args=(self._ops, epoch),
                name=f"tpuft_cppcomm_ops_{epoch}",
                daemon=True,
            )
            self._op_thread.start()
        if self.flight is not None:
            self.flight.set_comm_epoch(epoch)
            self.flight.record(
                FlightEvent.COMM_CONFIGURE,
                comm_epoch=epoch,
                quorum_id=quorum_id,
                rank=rank,
                world=world_size,
                tier="cpp",
            )
            if not self._flight_registered:
                # the C ring drains into every dump from here on
                self.flight.register_native_source(self)
                self._flight_registered = True
        logger.info(
            "cpp communicator configured: replica_id=%s rank=%d/%d quorum_id=%d",
            replica_id,
            rank,
            world_size,
            quorum_id,
        )

    def _teardown_locked(self, reason: str) -> None:
        # No join here: the op thread's error path takes self._lock, so
        # joining under the lock would deadlock.  The in-flight C op
        # observes the abort and errors out; the C layer parks superseded
        # fds in a graveyard until destruction, so the late-returning op can
        # never touch a recycled fd.
        if self._h:
            self._lib.tpuft_comm_abort(self._h)  # unblocks in-flight op
        try:
            while True:
                item = self._ops.get_nowait()
                if item is not None:
                    item[1].set_exception(CommunicatorAborted(reason))
        except queue.Empty:
            pass
        if self._op_thread is not None:
            self._ops.put(None)
            self._op_thread = None

    def abort(self, reason: str = "aborted") -> None:
        with self._lock:
            newly_poisoned = self._errored is None
            if self._errored is None:
                self._errored = CommunicatorAborted(reason)
            self._teardown_locked(reason)
            self._epoch += 1
        self._flight_poison(reason, newly_poisoned)
        logger.warning("cpp communicator aborted: %s", reason)

    def _flight_poison(self, reason: str, newly_poisoned: bool) -> None:
        """Record the epoch teardown (+ poison/dump when an error actually
        latched) — outside every lock, since a dump does file IO."""
        flight = self.flight
        if flight is None:
            return
        flight.record(FlightEvent.COMM_ABORT, reason=reason, tier="cpp")
        if newly_poisoned and reason != "shutdown":
            flight.record(
                FlightEvent.COMM_POISON, reason=reason, tier="cpp"
            )
            flight.maybe_dump("comm_poison")

    def _abort_if_epoch(self, epoch: int, reason: str) -> None:
        def _do() -> None:
            with self._lock:
                if self._epoch != epoch:
                    return
                newly_poisoned = self._errored is None
                if self._errored is None:
                    self._errored = CommunicatorAborted(reason)
                self._teardown_locked(reason)
                self._epoch += 1
            self._flight_poison(reason, newly_poisoned)
            logger.warning("cpp communicator aborted: %s", reason)

        threading.Thread(target=_do, name="tpuft_cppcomm_abort", daemon=True).start()

    def errored(self) -> Optional[Exception]:
        return self._errored

    def shutdown(self) -> None:
        with self._lock:
            thread = self._op_thread
            self._teardown_locked("shutdown")
            if self._errored is None:
                self._errored = CommunicatorAborted("shutdown")
            self._epoch += 1
        # join OUTSIDE the lock (the op thread's error path takes it); the C
        # object must not be freed while an op thread is inside a C call
        if thread is not None:
            thread.join(timeout=15.0)
        with self._lock:
            if self._h and (thread is None or not thread.is_alive()):
                self._lib.tpuft_comm_free(self._h)
                self._h = None

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._world_size

    def set_timeout(self, timeout_s: float) -> None:
        self._timeout_s = timeout_s

    def busy(self) -> bool:
        """True while an op is executing or queued in the current epoch —
        the idle-priority yield probe (see TCPCommunicator.busy).  The
        queue alone is not enough: ``_run_ops`` dequeues BEFORE running,
        so a multi-second in-flight collective leaves the queue empty."""
        if self._inflight_ops > 0:
            return True
        ops = self._ops
        return ops is not None and not ops.empty()

    def _op_started(self) -> None:
        """Enter the in-flight window of :meth:`busy` — counter under its
        own lock; see TCPCommunicator._op_started (same doctrine, pinned by
        the same contention regression test)."""
        with self._inflight_lock:
            self._inflight_ops += 1

    def _op_finished(self) -> None:
        with self._inflight_lock:
            self._inflight_ops -= 1

    def lane_stats(self) -> Dict[str, object]:
        """Per-lane observability of the current epoch, tier-agnostic with
        :meth:`TCPCommunicator.lane_stats`: lane count, stripe floor,
        payload bytes sent/received per lane, and stall events (pacer
        denials / kernel would-block).  The gray-failure counters the
        Python tier additionally exports (reconnects/failovers/injected
        faults) report 0 — the native tier has no fault injection or
        in-epoch lane recovery yet.  Empty when unconfigured or
        single-member."""
        with self._lock:
            if self._h is None or self._world_size <= 1:
                return {}
            cap = 64
            tx = (ctypes.c_uint64 * cap)()
            rx = (ctypes.c_uint64 * cap)()
            stalls = (ctypes.c_uint64 * cap)()
            floor = ctypes.c_uint64()
            lanes = int(
                self._lib.tpuft_comm_lane_stats(
                    self._h, tx, rx, stalls, cap, ctypes.byref(floor)
                )
            )
        if lanes <= 0:
            return {}
        n = min(lanes, cap)
        return {
            "lanes": lanes,
            "stripe_floor_bytes": int(floor.value),
            "lane_tx_bytes": [int(tx[i]) for i in range(n)],
            "lane_rx_bytes": [int(rx[i]) for i in range(n)],
            "lane_stalls": [int(stalls[i]) for i in range(n)],
            "lane_reconnects": 0,
            "lane_failovers": 0,
            "faults_injected": 0,
            "dead_lanes": 0,
        }

    def flight_drain(self) -> List[Dict[str, object]]:
        """Consume the C-side flight ring (``tpuft_comm_flight_drain``)
        into event dicts shaped like the Python recorder's, marked
        ``native``; repeated drains never duplicate events."""
        with self._lock:
            if self._h is None:
                return []
            cap = 256  # mirror of comm.h kFlightRingSlots
            seqs = (ctypes.c_uint64 * cap)()
            ts = (ctypes.c_double * cap)()
            evs = (ctypes.c_uint32 * cap)()
            a = (ctypes.c_int64 * cap)()
            b = (ctypes.c_int64 * cap)()
            n = int(
                self._lib.tpuft_comm_flight_drain(
                    self._h, seqs, ts, evs, a, b, cap
                )
            )
        out: List[Dict[str, object]] = []
        for i in range(n):
            ev = int(evs[i])
            out.append(
                {
                    "seq": int(seqs[i]),
                    "t": round(float(ts[i]), 6),
                    "ev": ev,
                    "name": (
                        FlightEvent(ev).name
                        if ev in FlightEvent._value2member_map_
                        else f"EV_{ev}"
                    ),
                    "a": int(a[i]),
                    "b": int(b[i]),
                    "native": True,
                }
            )
        return out

    # -- op machinery ------------------------------------------------------

    def _run_ops(self, ops: "queue.Queue", epoch: int) -> None:
        while True:
            item = ops.get()
            if item is None:
                return
            fn, fut = item
            if not fut.set_running_or_notify_cancel():
                continue
            timeout_s = self._timeout_s
            handle: TimerHandle = schedule_timeout(
                timeout_s,
                lambda: self._abort_if_epoch(
                    epoch, f"op timed out after {timeout_s}s"
                ),
            )
            self._op_started()
            try:
                with obs_span("comm::op", epoch=epoch, tier="cpp"):
                    result = fn()
            except BaseException as e:  # noqa: BLE001
                latched = False
                with self._lock:
                    if self._epoch == epoch and self._errored is None:
                        self._errored = (
                            e if isinstance(e, Exception) else RuntimeError(str(e))
                        )
                        latched = True
                if latched:
                    self._flight_poison(str(e), True)
                fut.set_exception(e)
            else:
                fut.set_result(result)
            finally:
                self._op_finished()
                handle.cancel()

    def _submit(self, fn: Callable[[], object]) -> Work:
        with self._lock:
            if self._errored is not None:
                fut: Future = Future()
                fut.set_exception(self._errored)
                return Work(fut)
            if self._op_thread is None:
                fut = Future()
                fut.set_exception(CommunicatorError("communicator not configured"))
                return Work(fut)
            fut = Future()
            self._ops.put((fn, fut))
            return Work(fut)

    def _check(self, rc: int, what: str) -> None:
        if rc != 0:
            raise CommunicatorError(f"{what} failed: {_last_error(self._lib)}")

    # -- collectives -------------------------------------------------------

    @staticmethod
    def _as_list(buffers: Buffers) -> List[np.ndarray]:
        """Host views of the input buffers — numpy passes through, dlpack /
        buffer-protocol sources (JAX CPU arrays included) come back as
        zero-copy views (:func:`as_host_array`)."""
        if isinstance(buffers, np.ndarray):
            return [buffers]
        return [as_host_array(b) for b in buffers]

    def allreduce(
        self,
        buffers: Buffers,
        op: ReduceOp = ReduceOp.SUM,
        in_place: bool = False,
    ) -> Work:
        arrays = self._as_list(buffers)
        single = isinstance(buffers, np.ndarray)
        ws = self._world_size

        def _run() -> object:
            out: List[np.ndarray] = [None] * len(arrays)  # type: ignore[list-item]
            # one native call per dtype (each dtype needs its own reduce
            # loop); the arrays of a group ride ONE ring as scattered iovec
            # segments — the round-1 binding np.concatenate'd them into a
            # staging buffer and sliced the result back out, a full extra
            # payload copy each way
            by_dtype: Dict[str, List[int]] = {}
            for i, a in enumerate(arrays):
                by_dtype.setdefault(a.dtype.name, []).append(i)
            for dtype_name, idxs in by_dtype.items():
                code = _DTYPE_CODES.get(dtype_name)
                if code is None:
                    raise CommunicatorError(f"unsupported dtype {dtype_name}")
                flats: List[np.ndarray] = []
                for i in idxs:
                    a = arrays[i]
                    if (
                        in_place
                        and a.flags.c_contiguous
                        and a.flags.writeable
                    ):
                        # zero-copy: the native ring reduces straight into
                        # the caller's buffer (returned aliased)
                        flat = a.reshape(-1)
                    else:
                        # the native op is in-place; copy this one array to
                        # preserve the caller's buffer (also the landing
                        # spot for read-only dlpack views)
                        flat = np.array(a, copy=True).reshape(-1)
                    flats.append(flat)
                    out[i] = flat
                total = sum(int(f.nbytes) for f in flats)
                if total > 0:
                    n = len(flats)
                    ptrs = (ctypes.c_void_p * n)(
                        *(_data_ptr(f) for f in flats)
                    )
                    lens = (ctypes.c_uint64 * n)(
                        *(int(f.nbytes) for f in flats)
                    )
                    self._check(
                        self._lib.tpuft_comm_allreduce_iov(
                            self._h, ptrs, lens, n, code, _OP_CODES[op]
                        ),
                        "allreduce",
                    )
                if op == ReduceOp.AVG:
                    for f in flats:
                        if np.issubdtype(f.dtype, np.integer):
                            f //= ws
                        else:
                            np.divide(f, ws, out=f)
                for i in idxs:
                    out[i] = out[i].reshape(arrays[i].shape)
            return out[0] if single else out

        return self._submit(_run)

    def reduce_scatter(
        self, data: np.ndarray, op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        arr = np.asarray(data)
        ws = self._world_size

        def _run() -> object:
            code = _DTYPE_CODES.get(arr.dtype.name)
            if code is None:
                raise CommunicatorError(f"unsupported dtype {arr.dtype.name}")
            # the native op reduces in place; work on a copy so the caller's
            # buffer survives
            flat = np.array(arr, copy=True).reshape(-1)
            n = flat.size
            base, extra = divmod(n, ws)
            own_elems = base + (1 if self._rank < extra else 0)
            out = np.empty(own_elems, dtype=flat.dtype)
            got = ctypes.c_uint64()
            self._check(
                self._lib.tpuft_comm_reduce_scatter(
                    self._h,
                    _data_ptr(flat),
                    flat.nbytes,
                    code,
                    _OP_CODES[op],
                    _data_ptr(out),
                    out.nbytes,
                    ctypes.byref(got),
                ),
                "reduce_scatter",
            )
            assert got.value == out.nbytes, "reduce_scatter size mismatch"
            if op == ReduceOp.AVG:
                if np.issubdtype(out.dtype, np.integer):
                    out //= ws
                else:
                    np.divide(out, ws, out=out)
            return out

        return self._submit(_run)

    def broadcast(self, buffers: Buffers, root: int = 0) -> Work:
        arrays = [np.ascontiguousarray(a) for a in self._as_list(buffers)]
        single = isinstance(buffers, np.ndarray)

        def _run() -> object:
            out = []
            for a in arrays:
                buf = np.array(a, copy=True)
                view = buf.reshape(-1).view(np.uint8)
                self._check(
                    self._lib.tpuft_comm_broadcast(
                        self._h,
                        view.ctypes.data_as(ctypes.c_void_p),
                        view.nbytes,
                        root,
                    ),
                    "broadcast",
                )
                out.append(buf)
            return out[0] if single else out

        return self._submit(_run)

    def send_bytes(self, data, dst: int, tag: int = 0) -> Work:
        """Send any contiguous buffer (bytes, memoryview, numpy array)
        WITHOUT copying: the C call reads straight from the object's buffer
        (the closure keeps it alive until the op completes)."""
        ptr, nbytes, keepalive = _buffer_ptr(data)

        def _run(_keep=keepalive) -> object:
            # _keep pins the backing buffer for the C call's lifetime
            self._check(
                self._lib.tpuft_comm_send(self._h, ptr, nbytes, dst, tag),
                "send",
            )
            return nbytes

        return self._submit(_run)

    def recv_bytes(self, src: int, tag: int = 0) -> Work:
        def _run() -> object:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = ctypes.c_uint64()
            self._check(
                self._lib.tpuft_comm_recv_alloc(
                    self._h, src, tag, ctypes.byref(out), ctypes.byref(n)
                ),
                "recv",
            )
            try:
                return ctypes.string_at(out, n.value)
            finally:
                self._lib.tpuft_buffer_free(out)

        return self._submit(_run)

    def recv_bytes_into(self, src: int, out: np.ndarray, tag: int = 0) -> Work:
        assert out.flags.c_contiguous and out.flags.writeable

        def _run() -> object:
            n = ctypes.c_uint64()
            self._check(
                self._lib.tpuft_comm_recv_into(
                    self._h,
                    src,
                    tag,
                    out.ctypes.data_as(ctypes.c_void_p),
                    out.nbytes,
                    ctypes.byref(n),
                ),
                "recv_into",
            )
            return int(n.value)

        return self._submit(_run)

    def alltoall(self, chunks: List[np.ndarray], tag: int = 0) -> Work:
        arrays = [
            np.ascontiguousarray(as_host_array(c)) for c in chunks
        ]

        def _run() -> object:
            ws = self._world_size
            if ws == 1:
                return [arrays[0]]
            assert len(arrays) == ws
            chunk_bytes = arrays[0].nbytes
            assert all(a.nbytes == chunk_bytes for a in arrays), (
                "cpp alltoall requires equal-size chunks"
            )
            # one pointer per destination chunk: frames leave straight from
            # the callers' buffers (the round-1 binding packed them into a
            # staging concatenation first); receives land in one buffer
            # handed back as per-source views
            ptrs = (ctypes.c_void_p * ws)(*(_data_ptr(a) for a in arrays))
            out = np.empty(ws * chunk_bytes, dtype=np.uint8)
            self._check(
                self._lib.tpuft_comm_alltoall_ptrs(
                    self._h,
                    ptrs,
                    out.ctypes.data_as(ctypes.c_void_p),
                    chunk_bytes,
                    tag,
                ),
                "alltoall",
            )
            return [
                out[p * chunk_bytes : (p + 1) * chunk_bytes]
                .view(arrays[0].dtype)
                .reshape(arrays[0].shape)
                for p in range(ws)
            ]

        return self._submit(_run)

    def allgather(self, data: np.ndarray, tag: int = 0) -> Work:
        array = np.ascontiguousarray(data)

        def _run() -> object:
            ws = self._world_size
            if ws == 1:
                return [array]
            chunk_bytes = array.nbytes
            out = np.empty(ws * chunk_bytes, dtype=np.uint8)
            self._check(
                self._lib.tpuft_comm_allgather(
                    self._h,
                    array.reshape(-1).view(np.uint8).ctypes.data_as(ctypes.c_void_p),
                    out.ctypes.data_as(ctypes.c_void_p),
                    chunk_bytes,
                    tag,
                ),
                "allgather",
            )
            return [
                out[p * chunk_bytes : (p + 1) * chunk_bytes]
                .view(array.dtype)
                .reshape(array.shape)
                for p in range(ws)
            ]

        return self._submit(_run)

    def barrier(self) -> Work:
        def _run() -> object:
            self._check(self._lib.tpuft_comm_barrier(self._h), "barrier")
            return None

        return self._submit(_run)
