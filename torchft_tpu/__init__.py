"""torchft_tpu: a TPU-native per-step fault-tolerance framework.

This package provides the capabilities of the reference system
(meta-pytorch/torchft, see /root/reference) re-designed TPU-first on top of
JAX/XLA:

- A **coordination plane**: a Lighthouse quorum/heartbeat service and a
  per-replica-group Manager server (reference: ``src/lighthouse.rs``,
  ``src/manager.rs``) speaking a compact framed wire protocol, with both a
  pure-Python implementation and a C++ implementation (``native/``).
- A **data plane**: reconfigurable ``Communicator`` objects for the replica
  (outer data-parallel) dimension that run host-side over DCN/TCP and can be
  torn down and re-formed on a live TPU job without restarting XLA
  (reference: ``torchft/process_group.py``).  Inside a replica group,
  parallelism is expressed with ``jax.sharding`` over an ICI mesh and stays
  inside compiled XLA programs.
- A **Manager** state machine driving per-step quorum, gradient averaging,
  commit voting, and live peer-to-peer healing (reference:
  ``torchft/manager.py``).
- **Training-loop wrappers**: an optax ``OptimizerWrapper``, fault-tolerant
  gradient averaging, ``LocalSGD`` and (Streaming) ``DiLoCo``
  (reference: ``torchft/optim.py``, ``torchft/ddp.py``,
  ``torchft/local_sgd.py``).
- **Checkpoint transports** that stream live weights between peers for
  heal-in (reference: ``torchft/checkpointing/``).

The key TPU-first design decision (SURVEY.md §7): the replica dimension is
*outside* the XLA program.  Compiled train steps never bake in the replica
count — the gradient divisor is a runtime scalar — so membership changes only
swap the host-side communicator and never trigger recompilation.
"""

__version__ = "0.1.0"

_LAZY = {
    # FT state machine + train-loop API
    "Manager": ("torchft_tpu.manager", "Manager"),
    "WorldSizeMode": ("torchft_tpu.manager", "WorldSizeMode"),
    "OptimizerWrapper": ("torchft_tpu.optim", "OptimizerWrapper"),
    "ft_allreduce": ("torchft_tpu.ddp", "ft_allreduce"),
    "allreduce_pytree": ("torchft_tpu.ddp", "allreduce_pytree"),
    "DistributedDataParallel": ("torchft_tpu.ddp", "DistributedDataParallel"),
    "DistributedSampler": ("torchft_tpu.data", "DistributedSampler"),
    "LocalSGD": ("torchft_tpu.local_sgd", "LocalSGD"),
    "DiLoCo": ("torchft_tpu.local_sgd", "DiLoCo"),
    # data plane
    "Communicator": ("torchft_tpu.communicator", "Communicator"),
    "TCPCommunicator": ("torchft_tpu.communicator", "TCPCommunicator"),
    "DummyCommunicator": ("torchft_tpu.communicator", "DummyCommunicator"),
    "ManagedCommunicator": ("torchft_tpu.communicator", "ManagedCommunicator"),
    "BabyCommunicator": ("torchft_tpu.baby", "BabyCommunicator"),
    "CppCommunicator": ("torchft_tpu.native", "CppCommunicator"),
    "ReduceOp": ("torchft_tpu.communicator", "ReduceOp"),
    # control plane
    "LighthouseServer": ("torchft_tpu.lighthouse", "LighthouseServer"),
    "LighthouseClient": ("torchft_tpu.lighthouse", "LighthouseClient"),
    "ManagerServer": ("torchft_tpu.manager_server", "ManagerServer"),
    "ManagerClient": ("torchft_tpu.manager_server", "ManagerClient"),
    # checkpointing
    "CheckpointTransport": ("torchft_tpu.checkpointing.transport", "CheckpointTransport"),
    "HTTPTransport": ("torchft_tpu.checkpointing.http_transport", "HTTPTransport"),
    "CommTransport": ("torchft_tpu.checkpointing.comm_transport", "CommTransport"),
    # parallelism
    "make_mesh": ("torchft_tpu.parallel.mesh", "make_mesh"),
    "HSDPTrainer": ("torchft_tpu.parallel.hsdp", "HSDPTrainer"),
    "ring_attention_sharded": (
        "torchft_tpu.parallel.ring_attention",
        "ring_attention_sharded",
    ),
    # chaos / scale validation
    "ChaosController": ("torchft_tpu.chaos", "ChaosController"),
    "Failure": ("torchft_tpu.chaos", "Failure"),
    "rehearse": ("torchft_tpu.parallel.rehearsal", "rehearse"),
    # gray-failure surface: fault-program parsing (TORCHFT_NET_FAULTS /
    # TCPCommunicator.arm_faults) and the heartbeat comm-health record
    "parse_fault_spec": ("torchft_tpu.communicator", "parse_fault_spec"),
    "CommHealth": ("torchft_tpu.wire", "CommHealth"),
    "gray_failure_drill": ("torchft_tpu.drill", "gray_failure_drill"),
}

__all__ = list(_LAZY)


def __getattr__(name: str):  # lazy so partial builds / light deps stay importable
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)
