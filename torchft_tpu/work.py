"""Async work handles for host-side collectives.

The reference returns c10d ``Work`` objects from every collective and layers
lazy future chaining on top (``torchft/work.py:15-26``,
``torchft/manager.py:1080-1363``).  On TPU there are no user-visible device
streams — XLA dispatch is already async — so the host-side communicator's
``Work`` is a thin wrapper over a ``concurrent.futures.Future`` with value
mapping (``then``) used for AVG normalization and error funneling.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Callable, Optional


class Work:
    """Handle for an in-flight collective.

    ``wait()`` blocks for completion and returns the op's value (the reduced
    arrays for allreduce and friends).  ``then(fn)`` returns a new Work whose
    value is ``fn(value)`` — the analog of the reference's lazy managed-future
    callbacks (``torchft/manager.py:1256-1307``) minus stream bookkeeping.
    """

    def __init__(self, future: "Future[Any]") -> None:
        self._future = future

    def wait(self, timeout: Optional[float] = None) -> Any:
        return self._future.result(timeout=timeout)

    def exception(self, timeout: Optional[float] = None) -> Optional[BaseException]:
        return self._future.exception(timeout=timeout)

    def done(self) -> bool:
        return self._future.done()

    def future(self) -> "Future[Any]":
        return self._future

    def then(self, fn: Callable[[Any], Any]) -> "Work":
        out: Future[Any] = Future()

        def _chain(f: "Future[Any]") -> None:
            err = f.exception()
            if err is not None:
                out.set_exception(err)
                return
            try:
                out.set_result(fn(f.result()))
            except BaseException as e:  # noqa: BLE001 - funnel into the future
                out.set_exception(e)

        self._future.add_done_callback(_chain)
        return Work(out)


class DummyWork(Work):
    """Already-completed work with a preset value.

    Returned after recorded errors and by the dummy communicator so the train
    loop never sees an exception from a collective
    (``torchft/work.py:15-26``, ``torchft/manager.py:435-436``).
    """

    def __init__(self, value: Any = None) -> None:
        fut: Future[Any] = Future()
        fut.set_result(value)
        super().__init__(fut)


def completed_future(value: Any = None) -> "Future[Any]":
    fut: Future[Any] = Future()
    fut.set_result(value)
    return fut


def failed_work(err: BaseException) -> Work:
    fut: Future[Any] = Future()
    fut.set_exception(err)
    return Work(fut)


class Event:
    """Host-side completion event (stand-in for CUDA events in the reference's
    recovery-stream synchronization, ``torchft/manager.py:880-892``)."""

    def __init__(self) -> None:
        self._event = threading.Event()

    def record(self) -> None:
        self._event.set()

    def synchronize(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout=timeout)
