"""LocalSGD and (Streaming) DiLoCo: communication-reduced fault-tolerant DP.

Behavioral twins of the reference wrappers (``torchft/local_sgd.py``):

- :class:`LocalSGD` (``local_sgd.py:45-172``): train locally for
  ``sync_every`` steps, then average *parameters* across replicas and commit.
- :class:`DiLoCo` (``local_sgd.py:175-795``): the DiLoCo / Streaming DiLoCo
  algorithm — keep a host-side backup of the globally-synced parameters;
  every ``sync_every`` steps compute **pseudogradients** (backup − local),
  average them across replicas (optionally int8-quantized over DCN), step an
  **outer optimizer** on the backup, and mix local/global by
  ``fragment_update_alpha``.  The model is split into fragments whose syncs
  are staggered and overlapped with training (the streaming variant's τ =
  ``fragment_sync_delay``).

jax adaptation: model state lives in a mutable ``holder`` mapping
(``{"params": pytree, ...}``) — the same object registered with the Manager
for healing.  Fragments are index sets over the flattened params, split by
byte size rather than by module boundaries (the reference carves fragments
with torch pipelining; leaf groups are the natural jax equivalent).  Backups
are host numpy (the reference pins them to CPU, ``local_sgd.py:241-253``);
pseudogradient math runs on host, the outer optimizer step runs through
optax.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Union

import jax
import numpy as np

from torchft_tpu.ddp import allreduce_pytree
from torchft_tpu.manager import Manager

logger = logging.getLogger(__name__)


def _like_leaf(value: np.ndarray, ref: Any) -> Any:
    """Return ``value`` with the container type/placement of ``ref``."""
    if isinstance(ref, jax.Array):
        return jax.device_put(value, ref.sharding)
    return value


def partition_leaves(
    params: Any, num_fragments: int
) -> List[List[int]]:
    """Split the flattened leaves of ``params`` into ``num_fragments``
    contiguous groups of roughly equal byte size."""
    leaves = jax.tree_util.tree_leaves(params)
    if len(leaves) < num_fragments:
        raise ValueError(
            f"cannot split {len(leaves)} leaves into {num_fragments} fragments"
        )
    sizes = [int(np.asarray(leaf).nbytes) for leaf in leaves]
    total = sum(sizes)
    target = total / max(num_fragments, 1)
    groups: List[List[int]] = [[] for _ in range(num_fragments)]
    acc, g = 0.0, 0
    for i, size in enumerate(sizes):
        groups[g].append(i)
        acc += size
        # advance AFTER placing, based on accumulated bytes including this
        # leaf, and never leave fewer leaves than remaining groups
        remaining_leaves = len(leaves) - (i + 1)
        remaining_groups = num_fragments - (g + 1)
        if g < num_fragments - 1 and (
            acc >= target * (g + 1) or remaining_leaves <= remaining_groups
        ):
            g += 1
    assert all(groups), "internal error: empty fragment"
    return groups


class LocalSGD:
    """Parameter-averaging LocalSGD (``local_sgd.py:45-172``).

    Usage::

        local_sgd = LocalSGD(manager, holder, sync_every=32)
        with local_sgd:
            for batch in data:
                ...inner optimizer step on holder...
                local_sgd.step()
    """

    def __init__(self, manager: Manager, holder: Dict[str, Any], sync_every: int) -> None:
        if sync_every < 1:
            raise ValueError("sync_every must be >= 1")
        self._manager = manager
        self._holder = holder
        self._sync_every = sync_every
        self._local_step = 0

    def __enter__(self) -> "LocalSGD":
        return self

    def __exit__(self, *exc: object) -> bool:
        return False

    def step(self) -> Optional[bool]:
        """Call after every inner optimizer step; returns the commit decision
        on sync steps, None otherwise."""
        self._local_step += 1
        if self._local_step < self._sync_every:
            return None
        self._local_step = 0
        return self.sync()

    def sync(self) -> bool:
        """Average parameters across replicas and commit
        (``local_sgd.py:129-172``).

        Routed through ``ddp.allreduce_pytree``'s bucketed pipeline — the
        same path DiLoCo fragments ride: device→host copies start
        asynchronously up front (``copy_to_host_async``) and overlap bucket
        assembly, each bucket's ring runs while the next bucket stages, and
        the rings reduce ``in_place`` in the staging buffers (the live
        params are never aliased).  The old path shipped the whole model as
        one blocking collective with synchronous host copies."""
        self._manager.start_quorum()
        work = allreduce_pytree(self._manager, self._holder["params"])
        averaged = work.wait()
        committed = self._manager.should_commit()
        if committed:
            self._holder["params"] = averaged
        return committed


class _Fragment:
    """One streaming fragment (``_StreamingDiLoCoFragment``,
    ``local_sgd.py:175-566``): backup params, pseudogradients, outer
    optimizer state, alpha mixing."""

    def __init__(
        self,
        manager: Manager,
        holder: Dict[str, Any],
        index: int,
        leaf_idxs: List[int],
        outer_tx: Any,
        should_quantize: bool,
        fragment_update_alpha: float,
    ) -> None:
        self._manager = manager
        self._holder = holder
        self._index = index
        self._leaf_idxs = leaf_idxs
        self._outer_tx = outer_tx
        self._should_quantize = should_quantize
        self._alpha = fragment_update_alpha
        self._work = None

        backup = self._current_local()
        self.backup: List[np.ndarray] = [np.array(a, copy=True) for a in backup]
        self.outer_state = outer_tx.init(self.backup)

        # fragment state rides the healing checkpoint
        # (``local_sgd.py:255-286``)
        key = f"StreamingDiLoCoFragment_{index}"
        manager.register_state_dict_fn(key, self._load_state, self._save_state)

    def _save_state(self) -> Dict[str, Any]:
        return {"backup": self.backup, "outer_state": self.outer_state}

    def _load_state(self, state: Dict[str, Any]) -> None:
        self.backup = [np.asarray(a) for a in state["backup"]]
        self.outer_state = state["outer_state"]

    def _current_local(self) -> List[np.ndarray]:
        leaves = jax.tree_util.tree_leaves(self._holder["params"])
        return [np.asarray(leaves[i]) for i in self._leaf_idxs]

    def save_parameters(self) -> None:
        self.backup = [np.array(a, copy=True) for a in self._current_local()]

    def prepare_sync(self) -> None:
        """pseudogradient = backup − local, then async average
        (``local_sgd.py:401-420``)."""
        local = self._current_local()
        pseudograds = [b - l for b, l in zip(self.backup, local)]
        assert self._work is None, "fragment already has an allreduce in flight"
        # in_place: pseudograds are freshly computed for this call and only
        # the returned average is read afterwards
        self._work = self._manager.allreduce(
            pseudograds, should_quantize=self._should_quantize, in_place=True
        )

    def perform_sync(self) -> bool:
        """Wait for the averaged pseudogradients, vote, and apply the outer
        step (``local_sgd.py:422-475``)."""
        assert self._work is not None, "prepare_sync must run first"
        averaged = self._work.wait()
        self._work = None

        local = self._current_local()
        committed = self._manager.should_commit()

        leaves, treedef = jax.tree_util.tree_flatten(self._holder["params"])
        if committed:
            import optax

            updates, self.outer_state = self._outer_tx.update(
                averaged, self.outer_state, self.backup
            )
            global_params = optax.apply_updates(self.backup, updates)
            global_params = [np.asarray(g) for g in global_params]
            # model = (1−α)·global + α·local (``local_sgd.py:366-384``)
            for j, i in enumerate(self._leaf_idxs):
                mixed = (
                    global_params[j]
                    if self._alpha == 0.0
                    else (1.0 - self._alpha) * global_params[j]
                    + self._alpha * local[j]
                ).astype(local[j].dtype)
                leaves[i] = _like_leaf(mixed, leaves[i])
            self.backup = global_params
        else:
            # failed sync: reset to the last globally-consistent state so we
            # never overtrain on unsynced data (``local_sgd.py:785-790``)
            for j, i in enumerate(self._leaf_idxs):
                leaves[i] = _like_leaf(self.backup[j], leaves[i])
        self._holder["params"] = jax.tree_util.tree_unflatten(treedef, leaves)
        return committed


class DiLoCo:
    """(Streaming) DiLoCo (``local_sgd.py:569-795``).

    Usage::

        manager = Manager(..., use_async_quorum=False)
        diloco = DiLoCo(manager, holder, outer_tx=optax.sgd(0.7, momentum=0.9,
                        nesterov=True), sync_every=20, num_fragments=2)
        with diloco:
            for batch in data:
                ...inner optimizer step on holder...
                diloco.step()
    """

    def __init__(
        self,
        manager: Manager,
        holder: Dict[str, Any],
        outer_tx: Union[Any, List[Any]],
        sync_every: int,
        num_fragments: int = 1,
        fragments: Optional[List[List[int]]] = None,
        should_quantize: bool = False,
        fragment_sync_delay: int = 0,
        fragment_update_alpha: float = 0.0,
    ) -> None:
        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum: construct the Manager "
                "with use_async_quorum=False"
            )
        if fragments is None:
            fragments = partition_leaves(holder["params"], num_fragments)
        n = len(fragments)
        if sync_every < n:
            raise ValueError("Only 1 fragment can be synchronized at a time")
        if sync_every % n != 0:
            raise ValueError("sync_every must be divisible by the fragment count")
        self._sync_every = sync_every // n
        if fragment_sync_delay >= self._sync_every:
            raise ValueError("Fragment must be synced before it is reduced again")
        if not 0.0 <= fragment_update_alpha <= 1.0:
            raise ValueError("fragment_update_alpha must be between 0 and 1")

        self._manager = manager
        self._holder = holder
        self._local_step = 0
        self._fragment_sync_delay = fragment_sync_delay

        outer_txs = (
            outer_tx if isinstance(outer_tx, list) else [outer_tx] * n
        )
        if len(outer_txs) != n:
            raise ValueError("need one outer optimizer per fragment")
        self._fragments = [
            _Fragment(
                manager,
                holder,
                i,
                leaf_idxs,
                outer_txs[i],
                should_quantize,
                fragment_update_alpha,
            )
            for i, leaf_idxs in enumerate(fragments)
        ]

    def __enter__(self) -> "DiLoCo":
        return self

    def __exit__(self, *exc: object) -> bool:
        return False

    def _current_fragment(self) -> int:
        """All replicas must prepare/sync fragments in the same order to
        avoid cross-replica deadlock (``local_sgd.py:745-763``)."""
        return self._manager.current_step() % len(self._fragments)

    def pre_step(self):
        """Guard the holder against concurrent checkpoint reads while the
        inner optimizer mutates it (the reference's inner optimizer
        pre-hook, ``local_sgd.py:716-720``).  Returns a context manager so
        the lock is released even when the inner step raises::

            with diloco.pre_step():
                ...inner optimizer step...
            diloco.step()
        """
        import contextlib

        manager = self._manager

        @contextlib.contextmanager
        def _guard():
            manager.disallow_state_dict_read()
            try:
                yield
            finally:
                manager.allow_state_dict_read()

        return _guard()

    def step(self) -> Optional[bool]:
        """Call after every inner optimizer step (the reference's optimizer
        post-hook, ``local_sgd.py:745-795``); returns the commit decision on
        sync steps, None otherwise."""
        self._manager.allow_state_dict_read()
        self._local_step += 1

        if self._local_step == self._sync_every - self._fragment_sync_delay:
            # quorum + overlap the pseudogradient allreduce with the next τ
            # inner steps
            self._manager.start_quorum()
            fragment = self._current_fragment()
            logger.info(
                "Preparing fragment=%d step=%d", fragment, self._local_step
            )
            self._fragments[fragment].prepare_sync()
            if self._fragment_sync_delay > 0:
                return None

        if self._local_step < self._sync_every:
            return None

        assert self._local_step == self._sync_every, (
            f"local_step={self._local_step} overran sync_every={self._sync_every}"
        )
        fragment = self._current_fragment()
        logger.info(
            "Syncing fragment=%d step=%d manager_step=%d",
            fragment,
            self._local_step,
            self._manager.current_step(),
        )
        committed = self._fragments[fragment].perform_sync()
        self._local_step = 0
        return committed
