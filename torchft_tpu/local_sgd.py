"""LocalSGD and (Streaming) DiLoCo: communication-reduced fault-tolerant DP.

Behavioral twins of the reference wrappers (``torchft/local_sgd.py``):

- :class:`LocalSGD` (``local_sgd.py:45-172``): train locally for
  ``sync_every`` steps, then average *parameters* across replicas and commit.
- :class:`DiLoCo` (``local_sgd.py:175-795``): the DiLoCo / Streaming DiLoCo
  algorithm — keep a host-side backup of the globally-synced parameters;
  every ``sync_every`` steps compute **pseudogradients** (backup − local),
  average them across replicas (optionally int8-quantized over DCN), step an
  **outer optimizer** on the backup, and mix local/global by
  ``fragment_update_alpha``.  The model is split into fragments whose syncs
  are staggered and overlapped with training (the streaming variant's τ =
  ``fragment_sync_delay``).

jax adaptation: model state lives in a mutable ``holder`` mapping
(``{"params": pytree, ...}``) — the same object registered with the Manager
for healing.  Fragments are index sets over the flattened params, split by
byte size rather than by module boundaries (the reference carves fragments
with torch pipelining; leaf groups are the natural jax equivalent).  Backups
are host numpy (the reference pins them to CPU, ``local_sgd.py:241-253``);
pseudogradient math runs on host, the outer optimizer step runs through
optax.

Degraded fleets (wire v5): when the quorum carries wounded replicas, the
outer reduce both wrappers ride (``Manager.allreduce`` for LocalSGD and the
legacy DiLoCo path, ``Manager.outer_shard_allreduce`` for the sharded one)
automatically becomes a capacity-WEIGHTED average — each replica's
pseudogradient counts by its capacity share, matching the
capacity-proportional data shard it actually trained on
(``data.DistributedSampler(capacities=...)``).  Nothing here changes:
the weighting is a pure pre-scale of each replica's contribution, the
allgathered wire-format delta stays bit-identical across replicas, and the
``_OuterShard`` layout is untouched (a wound never bumps ``quorum_id``, so
no reshard fires; the shard geometry depends on membership, not capacity).
"""

from __future__ import annotations

import logging
import os
import pickle
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import numpy as np

from torchft_tpu import knobs, wire
from torchft_tpu.ddp import allreduce_pytree
from torchft_tpu.manager import Manager
from torchft_tpu.obs.spans import span as obs_span

logger = logging.getLogger(__name__)

# Sharded outer optimizer (ZeRO-1 over the replica dimension):
#   auto/1 — the outer sync runs as a chunk-pipelined
#            reduce_scatter → sharded outer update → allgather(delta):
#            each replica (each HOST on hierarchical topologies) holds only
#            its shard of the outer optimizer state, updates it the moment
#            its reduce-scatter chunk lands (while later chunks are still
#            on the wire), and the updates fan back out as deltas applied
#            identically everywhere.  Outer compute and optimizer memory
#            divide by the shard count; membership changes reshard.
#   0      — the legacy replicated path, byte-for-byte: allreduce the full
#            pseudo-gradient, every replica runs the identical full outer
#            update.
OUTER_SHARD_ENV = "TORCHFT_OUTER_SHARD"

# reshard-exchange collective tags (allgather wire tags 5880/5881 — clear
# of the sharded pipeline's 900+ chunk tag range and every legacy tag base;
# allocated centrally in wire.USER_TAG_ALLOCATIONS)
_RESHARD_LEN_TAG = wire.RESHARD_LEN_TAG
_RESHARD_BLOB_TAG = wire.RESHARD_BLOB_TAG


def _tri_state_mode(env_name: str) -> str:
    """Parse an auto/0/1 mode knob (live-read: the drills flip these
    mid-process)."""
    raw = knobs.get_str(env_name, "auto").strip().lower()
    if raw in ("", "auto"):
        return "auto"
    if raw in ("1", "true", "on"):
        return "1"
    if raw in ("0", "false", "off"):
        return "0"
    raise ValueError(f"unparseable {env_name}={raw!r} (auto|0|1)")


def _outer_shard_mode() -> str:
    return _tri_state_mode(OUTER_SHARD_ENV)


# Streamed outer sync (zero-overhead DiLoCo fragments):
#   auto — stream when the operator set a staleness budget
#          (TORCHFT_STREAM_MAX_STALENESS >= 1) and the sync cadence has
#          room for it; otherwise the legacy blocking schedule.  The
#          staleness bar is an algorithmic hyperparameter (it decides how
#          many inner steps run against pre-sync params before the delta
#          lands), so auto never picks one silently.
#   1    — force streaming with a derived default bar when none is set;
#          falls back (loudly) to blocking when the cadence has no room
#          (per-fragment sync_every - delay - 1 < 1).
#   0    — the legacy blocking path, byte-for-byte (golden-fixture pinned).
STREAM_SYNC_ENV = "TORCHFT_STREAM_SYNC"
STREAM_MAX_STALENESS_ENV = "TORCHFT_STREAM_MAX_STALENESS"
DEFAULT_STREAM_STALENESS = 4


def _stream_mode() -> str:
    return _tri_state_mode(STREAM_SYNC_ENV)


def stream_stall_for(per_frag_sync: int, delay: int) -> int:
    """The effective bounded-staleness bar, in inner steps, for one
    fragment's streamed sync — 0 means streaming is off (blocking path).

    The bar is clamped to the schedule's room: the barrier must fire
    strictly before the NEXT fragment's prepare point (``per_frag_sync -
    delay`` steps into the next round) so at most one streamed sync is
    ever in flight and the round's quorum/vote protocol stays sequential.
    A pure function of env + the (uniform, ctor-validated) cadence, so
    every replica derives the identical schedule — the apply point being
    deterministic is what keeps replicas bit-identical."""
    mode = _stream_mode()
    if mode == "0":
        return 0
    room = per_frag_sync - delay - 1
    bar = knobs.get_int(STREAM_MAX_STALENESS_ENV, 0)
    if mode == "auto":
        return min(bar, room) if bar >= 1 and room >= 1 else 0
    # mode == "1": forced — derive a bar when none is set
    if room < 1:
        logger.warning(
            "%s=1 but the sync cadence has no staleness room "
            "(per-fragment sync_every=%d, delay=%d): falling back to the "
            "blocking outer sync",
            STREAM_SYNC_ENV,
            per_frag_sync,
            delay,
        )
        return 0
    return min(bar if bar >= 1 else DEFAULT_STREAM_STALENESS, room)


class _OuterShard:
    """This owner's shard of one fragment's outer optimizer state.

    The flat f32 element space of the fragment is split into deterministic
    equal shards (``collectives.outer_shard_layout``, 64-byte / row aligned,
    mirrored in ``native/comm.h``); this object holds the optax state for
    ONE shard as numpy leaves, serves per-chunk slices to the pipelined
    sync (``update_cb``), stages the updated state until the commit vote,
    and re-partitions on membership change.

    Resharding: whenever the quorum id moved since the layout was built,
    every replica contributes its (meta, state-shard) over two allgathers
    (lengths, then padded pickles) and reassembles the new shard from
    whichever contributions cover each element range.  Ranges owned by a
    replica that died are re-initialized fresh (momentum history is the
    only loss — parameters are replicated everywhere and unaffected); a
    healed replica contributes the shard it received in the checkpoint, so
    a kill/rejoin cycle conserves every surviving byte of state."""

    def __init__(self, outer_tx: Any, n: int, should_quantize: bool) -> None:
        self._outer_tx = outer_tx
        self._n = n
        self._quant = should_quantize
        # (quorum_id, gsize, gidx, per, owns) of the current layout
        self.meta: Optional[Dict[str, Any]] = None
        self._state_leaves: Optional[List[Any]] = None
        self._state_treedef: Optional[Any] = None
        self._staged: Optional[List[Any]] = None
        # (meta, leaves) recovered from a healing checkpoint, contributed at
        # the next reshard (our own rank may differ from the source's)
        self._loaded: List[Tuple[Dict[str, Any], List[Any]]] = []

    # -- layout ----------------------------------------------------------

    def _fresh_leaves(self, per: int) -> Tuple[List[Any], Any]:
        state = self._outer_tx.init(np.zeros(per, dtype=np.float32))
        leaves, treedef = jax.tree_util.tree_flatten(state)
        return [
            np.array(l, copy=True) if getattr(l, "shape", None) == (per,) else l
            for l in map(np.asarray, leaves)
        ], treedef

    def _is_shard_leaf(self, leaf: Any, per: int) -> bool:
        return getattr(leaf, "shape", None) == (per,)

    def maybe_reshard(self, manager: Manager) -> None:
        """(Re)build this owner's shard for the current quorum.  Gated on
        the quorum id alone — a shared fact, so every replica enters (or
        skips) the collective exchange in lock-step; steady-state syncs
        skip everything."""
        qid = manager._quorum_id
        if self.meta is not None and self.meta["q"] == qid:
            return
        from torchft_tpu.collectives import outer_shard_layout

        gsize, gidx, owns = manager.outer_shard_group()
        _padded, per, unit = outer_shard_layout(self._n, gsize, self._quant)
        meta = {
            "q": qid,
            "gsize": gsize,
            "gidx": gidx,
            "per": per,
            "n": self._n,
            "owns": owns,
        }
        contribs = self._export_contribs()
        comm = manager._comm
        if comm.size() > 1 and not getattr(comm, "is_passthrough", False):
            blob = pickle.dumps(contribs)
            try:
                lens = comm.allgather(
                    np.array([len(blob)], dtype=np.int64), tag=_RESHARD_LEN_TAG
                ).wait()
                maxlen = max(int(np.asarray(l).reshape(-1)[0]) for l in lens)
                padded_blob = np.zeros(max(1, maxlen), dtype=np.uint8)
                padded_blob[: len(blob)] = np.frombuffer(blob, dtype=np.uint8)
                blobs = comm.allgather(padded_blob, tag=_RESHARD_BLOB_TAG).wait()
                contribs = []
                for l, b in zip(lens, blobs):
                    size = int(np.asarray(l).reshape(-1)[0])
                    try:
                        contribs.extend(pickle.loads(bytes(bytearray(b[:size]))))
                    except Exception:  # noqa: BLE001 — skip a bad peer blob
                        logger.warning("outer-shard reshard: bad peer blob")
            except Exception as e:  # noqa: BLE001 — the sync right after
                # this will surface comm errors; reshard falls back to the
                # locally-held contributions (peers' shards re-init fresh)
                logger.warning("outer-shard reshard exchange failed: %s", e)
                contribs = self._export_contribs()
        self._rebuild(contribs, meta)

    def _export_contribs(self) -> List[Tuple[Dict[str, Any], List[Any]]]:
        out = list(self._loaded)
        if self.meta is not None and self._state_leaves is not None:
            out.append((dict(self.meta), self._state_leaves))
        return out

    def _rebuild(
        self,
        contribs: List[Tuple[Dict[str, Any], List[Any]]],
        meta: Dict[str, Any],
    ) -> None:
        self._loaded = []
        self._staged = None
        self.meta = meta
        if not meta["owns"]:
            self._state_leaves, self._state_treedef = None, None
            return
        per = meta["per"]
        leaves, treedef = self._fresh_leaves(per)
        my_lo, my_hi = meta["gidx"] * per, meta["gidx"] * per + per
        for cmeta, cleaves in contribs:
            if cmeta.get("n") != self._n or not cmeta.get("owns", True):
                continue
            cper = cmeta["per"]
            c_lo = cmeta["gidx"] * cper
            lo, hi = max(my_lo, c_lo), min(my_hi, c_lo + cper)
            if lo >= hi or len(cleaves) != len(leaves):
                continue
            for j, (mine, theirs) in enumerate(zip(leaves, cleaves)):
                theirs = np.asarray(theirs)
                if self._is_shard_leaf(mine, per) and self._is_shard_leaf(
                    theirs, cper
                ):
                    mine[lo - my_lo : hi - my_lo] = theirs[lo - c_lo : hi - c_lo]
                elif getattr(theirs, "shape", None) == ():
                    # scalar leaves (step counts): keep the max seen so a
                    # recovered shard never rewinds schedules
                    leaves[j] = np.maximum(np.asarray(leaves[j]), theirs)
        self._state_leaves, self._state_treedef = leaves, treedef

    # -- sync ------------------------------------------------------------

    def make_update_cb(self, backup_flat: np.ndarray):
        """Per-chunk outer update for the pipelined sync: slices this
        shard's state, steps the outer optimizer on the chunk, stages the
        new state (adopted only on commit), returns the delta."""
        assert self.meta is not None and self.meta["owns"]
        assert self._state_leaves is not None
        per = self.meta["per"]
        base = self.meta["gidx"] * per
        old = self._state_leaves
        treedef = self._state_treedef
        self._staged = [
            np.array(l, copy=True) if self._is_shard_leaf(l, per) else l
            for l in old
        ]
        staged = self._staged
        tx = self._outer_tx

        def _cb(lo: int, hi: int, avg: np.ndarray) -> np.ndarray:
            s, e = lo - base, hi - base
            # chunks slice the ORIGINAL state (scalar leaves update from
            # the same pre-sync value on every chunk — consistent)
            state_slice = jax.tree_util.tree_unflatten(
                treedef,
                [l[s:e] if self._is_shard_leaf(l, per) else l for l in old],
            )
            updates, new_state = tx.update(
                avg, state_slice, backup_flat[lo:hi]
            )
            for j, leaf in enumerate(jax.tree_util.tree_leaves(new_state)):
                leaf = np.asarray(leaf)
                if self._is_shard_leaf(staged[j], per):
                    staged[j][s:e] = leaf
                else:
                    staged[j] = leaf
            return np.asarray(updates, dtype=np.float32)

        return _cb

    def commit_stage(self) -> None:
        if self._staged is not None:
            self._state_leaves = self._staged
        self._staged = None

    def abort_stage(self) -> None:
        self._staged = None

    # -- checkpoint round trip -------------------------------------------

    def save_state(self) -> Optional[Dict[str, Any]]:
        if self.meta is None:
            return None
        return {
            "meta": dict(self.meta),
            "leaves": self._state_leaves,
        }

    def load_state(self, state: Optional[Dict[str, Any]]) -> None:
        """A healed checkpoint carries the SOURCE's shard; hold it as a
        reshard contribution (the heal always rides a quorum change, so
        the next sync reshards and routes every range to its new owner)."""
        if not state or state.get("leaves") is None:
            return
        self._loaded.append((state["meta"], state["leaves"]))
        self.meta = None  # force reshard at the next sync


def _like_leaf(value: np.ndarray, ref: Any) -> Any:
    """Return ``value`` with the container type/placement of ``ref``."""
    if isinstance(ref, jax.Array):
        return jax.device_put(value, ref.sharding)
    return value


def partition_leaves(
    params: Any, num_fragments: int
) -> List[List[int]]:
    """Split the flattened leaves of ``params`` into ``num_fragments``
    contiguous groups of roughly equal byte size."""
    leaves = jax.tree_util.tree_leaves(params)
    if len(leaves) < num_fragments:
        raise ValueError(
            f"cannot split {len(leaves)} leaves into {num_fragments} fragments"
        )
    sizes = [int(np.asarray(leaf).nbytes) for leaf in leaves]
    total = sum(sizes)
    target = total / max(num_fragments, 1)
    groups: List[List[int]] = [[] for _ in range(num_fragments)]
    acc, g = 0.0, 0
    for i, size in enumerate(sizes):
        groups[g].append(i)
        acc += size
        # advance AFTER placing, based on accumulated bytes including this
        # leaf, and never leave fewer leaves than remaining groups
        remaining_leaves = len(leaves) - (i + 1)
        remaining_groups = num_fragments - (g + 1)
        if g < num_fragments - 1 and (
            acc >= target * (g + 1) or remaining_leaves <= remaining_groups
        ):
            g += 1
    assert all(groups), "internal error: empty fragment"
    return groups


class LocalSGD:
    """Parameter-averaging LocalSGD (``local_sgd.py:45-172``).

    Usage::

        local_sgd = LocalSGD(manager, holder, sync_every=32)
        with local_sgd:
            for batch in data:
                ...inner optimizer step on holder...
                local_sgd.step()
    """

    def __init__(self, manager: Manager, holder: Dict[str, Any], sync_every: int) -> None:
        if sync_every < 1:
            raise ValueError("sync_every must be >= 1")
        self._manager = manager
        self._holder = holder
        self._sync_every = sync_every
        self._local_step = 0
        # streamed sync (TORCHFT_STREAM_SYNC): LocalSGD is one whole-model
        # "fragment" — the parameter average streams under the next inner
        # steps and applies at the bounded-staleness barrier (inner
        # progress during the stall is overwritten by the committed
        # average, the same semantic as DiLoCo's alpha=0 apply)
        self._stream_stall = stream_stall_for(sync_every, 0)
        self._stream_work = None

    def __enter__(self) -> "LocalSGD":
        return self

    def __exit__(self, *exc: object) -> bool:
        # drain a streamed sync submitted within the final stall window:
        # abandoning it would end the run one committed average short of
        # the blocking schedule at the same step count and leave an open
        # quorum + a dangling stream-fence entry on the Manager
        if self._stream_work is not None:
            self._apply_streamed()
        return False

    def step(self) -> Optional[bool]:
        """Call after every inner optimizer step; returns the commit decision
        on sync steps (at the staleness barrier when streaming), None
        otherwise."""
        self._local_step += 1
        committed: Optional[bool] = None
        if (
            self._stream_work is not None
            and self._local_step >= self._stream_stall
        ):
            committed = self._apply_streamed()
        if self._local_step < self._sync_every:
            return committed
        self._local_step = 0
        if self._stream_stall > 0:
            self._manager.start_quorum()
            with obs_span("stream::submit", frag=0):
                # stream=0 registers the composite work in the Manager's
                # stream-fence registry (FRAG_SUBMIT rides it)
                self._stream_work = allreduce_pytree(
                    self._manager, self._holder["params"], stream=0
                )
            return committed
        return self.sync()

    def _apply_streamed(self) -> bool:
        """Bounded-staleness barrier of a streamed parameter average: wait
        the (by now usually drained) collective, vote, and adopt the
        committed average."""
        work, self._stream_work = self._stream_work, None
        with obs_span("stream::barrier", frag=0):
            averaged = work.wait()
        committed = self._manager.should_commit()
        self._manager.stream_resolved(0, committed)
        if committed:
            self._holder["params"] = averaged
        return committed

    def sync(self) -> bool:
        """Average parameters across replicas and commit
        (``local_sgd.py:129-172``).

        Routed through ``ddp.allreduce_pytree``'s bucketed pipeline — the
        same path DiLoCo fragments ride: device→host copies start
        asynchronously up front (``copy_to_host_async``) and overlap bucket
        assembly, each bucket's ring runs while the next bucket stages, and
        the rings reduce ``in_place`` in the staging buffers (the live
        params are never aliased).  The old path shipped the whole model as
        one blocking collective with synchronous host copies."""
        self._manager.start_quorum()
        work = allreduce_pytree(self._manager, self._holder["params"])
        averaged = work.wait()
        committed = self._manager.should_commit()
        if committed:
            self._holder["params"] = averaged
        return committed


class _Fragment:
    """One streaming fragment (``_StreamingDiLoCoFragment``,
    ``local_sgd.py:175-566``): backup params, pseudogradients, outer
    optimizer state, alpha mixing."""

    def __init__(
        self,
        manager: Manager,
        holder: Dict[str, Any],
        index: int,
        leaf_idxs: List[int],
        outer_tx: Any,
        should_quantize: bool,
        fragment_update_alpha: float,
    ) -> None:
        self._manager = manager
        self._holder = holder
        self._index = index
        self._leaf_idxs = leaf_idxs
        self._outer_tx = outer_tx
        self._should_quantize = should_quantize
        self._alpha = fragment_update_alpha
        self._work = None
        self._sharded_inflight = False
        # True while a TORCHFT_STREAM_SYNC submit is in flight: the work
        # lives in the Manager's stream-fence registry and perform_sync
        # reports the FRAG_COMMIT/FRAG_ABORT outcome when it resolves
        self._stream_inflight = False

        # cache the pytree layout once: the treedef (reused for every
        # unflatten), and this fragment's per-leaf (shape, dtype, flat
        # offset) over its f32 element space — sync rounds re-read leaf
        # VALUES via tree_leaves but never re-derive structure
        leaves, self._treedef = jax.tree_util.tree_flatten(holder["params"])
        backup = [np.asarray(leaves[i]) for i in self._leaf_idxs]
        self.backup: List[np.ndarray] = [np.array(a, copy=True) for a in backup]
        self._leaf_meta: List[Tuple[int, int, tuple, Any]] = []
        off = 0
        for a in backup:
            self._leaf_meta.append((off, a.size, a.shape, a.dtype))
            off += a.size
        self._n = off
        # padded f32 scratch for pseudo-gradient / backup assembly, reused
        # across sync rounds (grown once to the sharded layout's padded
        # size; the same trick _allreduce_pipelined_sync uses)
        self._psg_scratch: Optional[np.ndarray] = None
        self._backup_scratch: Optional[np.ndarray] = None

        # full replicated outer state exists ONLY on the legacy path — in
        # sharded mode each owner's slice lives in _OuterShard and this
        # stays None (the ZeRO-1 memory division), allocated lazily if a
        # sync ever runs with TORCHFT_OUTER_SHARD=0
        self.outer_state = (
            outer_tx.init(self.backup) if _outer_shard_mode() == "0" else None
        )
        self._shard = _OuterShard(outer_tx, self._n, should_quantize)

        # fragment state rides the healing checkpoint
        # (``local_sgd.py:255-286``)
        key = f"StreamingDiLoCoFragment_{index}"
        manager.register_state_dict_fn(key, self._load_state, self._save_state)

    def _save_state(self) -> Dict[str, Any]:
        return {
            "backup": self.backup,
            "outer_state": self.outer_state,
            "outer_shard": self._shard.save_state(),
        }

    def _load_state(self, state: Dict[str, Any]) -> None:
        self.backup = [np.asarray(a) for a in state["backup"]]
        self.outer_state = state.get("outer_state")
        self._shard.load_state(state.get("outer_shard"))

    def _current_local(self) -> List[np.ndarray]:
        leaves = jax.tree_util.tree_leaves(self._holder["params"])
        return [np.asarray(leaves[i]) for i in self._leaf_idxs]

    def save_parameters(self) -> None:
        self.backup = [np.array(a, copy=True) for a in self._current_local()]

    def _sharded(self) -> bool:
        return _outer_shard_mode() != "0"

    def _scratch(self, padded: int) -> Tuple[np.ndarray, np.ndarray]:
        if self._psg_scratch is None or self._psg_scratch.size < padded:
            self._psg_scratch = np.zeros(padded, dtype=np.float32)
            self._backup_scratch = np.zeros(padded, dtype=np.float32)
        assert self._backup_scratch is not None
        return self._psg_scratch[:padded], self._backup_scratch[:padded]

    def prepare_sync(self, stream: bool = False) -> None:
        """pseudogradient = backup − local, then async average
        (``local_sgd.py:401-420``).  With ``stream=True`` the submit rides
        the Manager's stream-fence registry (and, on the sharded path, the
        fragment's rotating STREAM_OUTER tag window): inner compute
        continues against pre-sync params and the caller applies the delta
        at its bounded-staleness barrier via :meth:`perform_sync`."""
        local = self._current_local()
        assert self._work is None, "fragment already has an allreduce in flight"
        self._stream_inflight = stream
        with obs_span(
            "stream::submit" if stream else "diloco::prepare",
            frag=self._index,
        ):
            if self._sharded():
                self._prepare_sync_sharded(local, stream)
                return
            pseudograds = [b - l for b, l in zip(self.backup, local)]
            # in_place: pseudograds are freshly computed for this call and
            # only the returned average is read afterwards
            self._work = self._manager.allreduce(
                pseudograds,
                should_quantize=self._should_quantize,
                in_place=True,
                stream=self._index if stream else None,
            )

    def _prepare_sync_sharded(
        self, local: List[np.ndarray], stream: bool = False
    ) -> None:
        """Sharded outer sync: assemble the flat pseudo-gradient, (re)build
        this owner's shard for the current quorum, and hand the per-chunk
        outer update to the pipelined reduce_scatter→update→allgather."""
        from torchft_tpu.collectives import outer_shard_layout

        self._shard.maybe_reshard(self._manager)
        meta = self._shard.meta
        gsize = meta["gsize"] if meta is not None else 1
        padded, _per, _unit = outer_shard_layout(
            self._n, max(1, gsize), self._should_quantize
        )
        psg, backup_flat = self._scratch(padded)
        for (off, size, _shape, _dtype), b, l in zip(
            self._leaf_meta, self.backup, local
        ):
            seg = backup_flat[off : off + size]
            seg[:] = b.reshape(-1)
            p = psg[off : off + size]
            p[:] = seg
            p -= l.reshape(-1)
        psg[self._n :] = 0.0
        backup_flat[self._n :] = 0.0

        update_cb = (
            self._shard.make_update_cb(backup_flat)
            if meta is not None and meta["owns"]
            else _no_shard_cb
        )
        self._sharded_inflight = True
        self._work = self._manager.outer_shard_allreduce(
            psg[: self._n],
            update_cb,
            should_quantize=self._should_quantize,
            stream=self._index if stream else None,
        )

    def perform_sync(self) -> bool:
        """Wait for the result, vote, and apply the outer step
        (``local_sgd.py:422-475``).  On a streamed sync this is the
        bounded-staleness barrier: the wait is ~free when the collectives
        drained under the stalled inner steps, and the vote runs only
        after the work resolved (the Manager's stream fence would
        otherwise force it False)."""
        assert self._work is not None, "prepare_sync must run first"
        streamed = self._stream_inflight
        with obs_span(
            "stream::barrier" if streamed else "diloco::perform",
            frag=self._index,
        ):
            result = self._work.wait()
        self._work = None
        sharded = self._sharded_inflight
        self._sharded_inflight = False
        self._stream_inflight = False

        local = self._current_local()
        committed = self._manager.should_commit()
        if streamed:
            self._manager.stream_resolved(self._index, committed)

        leaves = jax.tree_util.tree_leaves(self._holder["params"])
        if committed and sharded and result is not None:
            # delta = the allgathered sharded outer update, identical bytes
            # on every replica: global = backup + delta
            delta = result
            global_params = []
            for (off, size, shape, dtype), b in zip(self._leaf_meta, self.backup):
                g = (
                    b.reshape(-1).astype(np.float32) + delta[off : off + size]
                ).astype(dtype, copy=False).reshape(shape)
                global_params.append(g)
            self._apply_global(leaves, global_params, local)
            self._shard.commit_stage()
            # hot spares: the committed delta (identical bytes on every
            # replica) feeds parked spares' shadows — warm channel (a)
            self._manager.publish_staged_outer_delta(self._index)
        elif committed and not sharded:
            import optax

            averaged = result
            if self.outer_state is None:
                self.outer_state = self._outer_tx.init(self.backup)
            updates, self.outer_state = self._outer_tx.update(
                averaged, self.outer_state, self.backup
            )
            global_params = optax.apply_updates(self.backup, updates)
            global_params = [np.asarray(g) for g in global_params]
            self._apply_global(leaves, global_params, local)
        else:
            # failed sync: reset to the last globally-consistent state so we
            # never overtrain on unsynced data (``local_sgd.py:785-790``)
            if sharded:
                self._shard.abort_stage()
            for j, i in enumerate(self._leaf_idxs):
                leaves[i] = _like_leaf(self.backup[j], leaves[i])
        self._holder["params"] = jax.tree_util.tree_unflatten(
            self._treedef, leaves
        )
        return committed

    def _apply_global(
        self,
        leaves: List[Any],
        global_params: List[np.ndarray],
        local: List[np.ndarray],
    ) -> None:
        """model = (1−α)·global + α·local (``local_sgd.py:366-384``)."""
        for j, i in enumerate(self._leaf_idxs):
            mixed = (
                global_params[j]
                if self._alpha == 0.0
                else (1.0 - self._alpha) * global_params[j]
                + self._alpha * local[j]
            ).astype(local[j].dtype)
            leaves[i] = _like_leaf(mixed, leaves[i])
        self.backup = global_params


def _no_shard_cb(lo: int, hi: int, avg: np.ndarray) -> np.ndarray:
    raise AssertionError(
        "outer update callback invoked on a replica that owns no shard"
    )


class DiLoCo:
    """(Streaming) DiLoCo (``local_sgd.py:569-795``).

    Usage::

        manager = Manager(..., use_async_quorum=False)
        diloco = DiLoCo(manager, holder, outer_tx=optax.sgd(0.7, momentum=0.9,
                        nesterov=True), sync_every=20, num_fragments=2)
        with diloco:
            for batch in data:
                ...inner optimizer step on holder...
                diloco.step()
    """

    def __init__(
        self,
        manager: Manager,
        holder: Dict[str, Any],
        outer_tx: Union[Any, List[Any]],
        sync_every: int,
        num_fragments: int = 1,
        fragments: Optional[List[List[int]]] = None,
        should_quantize: bool = False,
        fragment_sync_delay: int = 0,
        fragment_update_alpha: float = 0.0,
    ) -> None:
        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum: construct the Manager "
                "with use_async_quorum=False"
            )
        if fragments is None:
            fragments = partition_leaves(holder["params"], num_fragments)
        n = len(fragments)
        if sync_every < n:
            raise ValueError("Only 1 fragment can be synchronized at a time")
        if sync_every % n != 0:
            raise ValueError("sync_every must be divisible by the fragment count")
        self._sync_every = sync_every // n
        if fragment_sync_delay >= self._sync_every:
            raise ValueError("Fragment must be synced before it is reduced again")
        if not 0.0 <= fragment_update_alpha <= 1.0:
            raise ValueError("fragment_update_alpha must be between 0 and 1")

        self._manager = manager
        self._holder = holder
        self._local_step = 0
        self._fragment_sync_delay = fragment_sync_delay
        # streamed outer sync: the effective bounded-staleness bar (inner
        # steps between a fragment's sync point and its delta applying; 0 =
        # legacy blocking schedule).  Resolved ONCE at construction — the
        # schedule must be identical on every replica and stable for the
        # run, like the cadence itself.
        self._stream_stall = stream_stall_for(
            self._sync_every, fragment_sync_delay
        )
        # the fragment whose streamed sync is awaiting its barrier (at most
        # one: the bar is clamped below the next prepare point)
        self._stream_pending_frag: Optional[int] = None

        outer_txs = (
            outer_tx if isinstance(outer_tx, list) else [outer_tx] * n
        )
        if len(outer_txs) != n:
            raise ValueError("need one outer optimizer per fragment")
        self._fragments = [
            _Fragment(
                manager,
                holder,
                i,
                leaf_idxs,
                outer_txs[i],
                should_quantize,
                fragment_update_alpha,
            )
            for i, leaf_idxs in enumerate(fragments)
        ]

    def __enter__(self) -> "DiLoCo":
        return self

    def __exit__(self, *exc: object) -> bool:
        # drain a streamed sync whose sync step already passed but whose
        # staleness barrier hasn't fired: abandoning it would end the run
        # one committed round short of the blocking schedule and leave a
        # dangling stream-fence entry.  (A fragment merely PREPARED —
        # sync step not yet reached — is abandoned exactly like the
        # blocking schedule abandons it.)
        if self._stream_pending_frag is not None:
            frag = self._stream_pending_frag
            self._stream_pending_frag = None
            self._fragments[frag].perform_sync()
        return False

    def _current_fragment(self) -> int:
        """All replicas must prepare/sync fragments in the same order to
        avoid cross-replica deadlock (``local_sgd.py:745-763``)."""
        return self._manager.current_step() % len(self._fragments)

    def pre_step(self):
        """Guard the holder against concurrent checkpoint reads while the
        inner optimizer mutates it (the reference's inner optimizer
        pre-hook, ``local_sgd.py:716-720``).  Returns a context manager so
        the lock is released even when the inner step raises::

            with diloco.pre_step():
                ...inner optimizer step...
            diloco.step()
        """
        import contextlib

        manager = self._manager

        @contextlib.contextmanager
        def _guard():
            manager.disallow_state_dict_read()
            try:
                yield
            finally:
                manager.allow_state_dict_read()

        return _guard()

    def streaming(self) -> bool:
        """True when the streamed scheduler is engaged (TORCHFT_STREAM_SYNC
        resolved against this cadence at construction)."""
        return self._stream_stall > 0

    def step(self) -> Optional[bool]:
        """Call after every inner optimizer step (the reference's optimizer
        post-hook, ``local_sgd.py:745-795``); returns the commit decision on
        sync steps, None otherwise.

        Streamed schedule (``TORCHFT_STREAM_SYNC``): the sync step no
        longer blocks — the fragment's reduce_scatter → sharded update →
        allgather keeps draining on its background path while inner
        compute continues against pre-sync params, and the identical
        wire-format delta applies ``stall`` inner steps later at the
        bounded-staleness barrier (where the commit decision is returned).
        The barrier position is a pure function of the cadence, so every
        replica applies at the same inner step — replicas stay
        bit-identical, exactly as on the blocking path."""
        self._manager.allow_state_dict_read()
        self._local_step += 1

        committed: Optional[bool] = None
        if (
            self._stream_pending_frag is not None
            and self._local_step >= self._stream_stall
        ):
            # bounded-staleness barrier: resolve the streamed fragment
            # BEFORE this round's prepare can open a new quorum (the bar
            # is clamped strictly below the prepare point)
            frag = self._stream_pending_frag
            self._stream_pending_frag = None
            logger.info(
                "Stream barrier fragment=%d step=%d manager_step=%d",
                frag,
                self._local_step,
                self._manager.current_step(),
            )
            committed = self._fragments[frag].perform_sync()

        if self._local_step == self._sync_every - self._fragment_sync_delay:
            # quorum + overlap the pseudogradient allreduce with the next τ
            # inner steps
            self._manager.start_quorum()
            fragment = self._current_fragment()
            logger.info(
                "Preparing fragment=%d step=%d", fragment, self._local_step
            )
            self._fragments[fragment].prepare_sync(stream=self.streaming())
            if self._fragment_sync_delay > 0:
                return committed

        if self._local_step < self._sync_every:
            return committed

        assert self._local_step == self._sync_every, (
            f"local_step={self._local_step} overran sync_every={self._sync_every}"
        )
        fragment = self._current_fragment()
        if self.streaming():
            # the sync step streams: hand the fragment to the stall window
            # and keep training — perform_sync runs at the barrier above
            self._stream_pending_frag = fragment
            self._local_step = 0
            return committed
        logger.info(
            "Syncing fragment=%d step=%d manager_step=%d",
            fragment,
            self._local_step,
            self._manager.current_step(),
        )
        committed = self._fragments[fragment].perform_sync()
        self._local_step = 0
        return committed
