"""Per-replica-group Manager sidecar: barrier, recovery math, commit voting.

The reference runs a Rust ``ManagerServer`` inside the rank-0 Python process
of every replica group (``src/manager.rs:80-328``); all local ranks connect
to it with a ``ManagerClient``.  Its three jobs:

1. **Intra-group quorum barrier** (``src/manager.rs:332-402``): collect one
   ``quorum`` RPC from each of the group's ``world_size`` ranks; when the
   last arrives, forward a single request to the lighthouse (with retries and
   client re-creation, ``src/manager.rs:250-306``) and broadcast the resulting
   quorum to every parked rank.
2. **Recovery assignment** (``compute_quorum_results``,
   ``src/manager.rs:489-625``): sort participants by replica_id for a
   deterministic replica_rank; find the max-step set; pick the primary store
   owner ``group_rank % len(max_participants)``; round-robin assign each
   stale replica a healthy recovery source, offset by group_rank so different
   group ranks spread load across sources.
3. **should_commit AND-barrier** (``src/manager.rs:423-479``): collect votes
   from all local ranks; the decision is the AND of all votes; broadcast and
   reset.

It also stores per-rank checkpoint metadata for healing peers
(``src/manager.rs:404-421``), heartbeats the lighthouse every
``heartbeat_interval`` (``src/manager.rs:194-216``), and answers ``Kill``
by exiting the process (``src/manager.rs:481-487``).
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from torchft_tpu import knobs
from torchft_tpu.lighthouse import LighthouseClient
from torchft_tpu.obs import metrics as obs_metrics
from torchft_tpu.wire import (
    ROLE_ACTIVE,
    ErrCode,
    ManagerQuorumResult,
    MsgType,
    Quorum,
    QuorumMember,
    Reader,
    RpcClient,
    WireError,
    Writer,
    configure_server_socket,
    create_listener,
    raise_if_error,
    read_http_path,
    recv_frame,
    send_error,
    send_frame,
    send_http_response,
)

logger = logging.getLogger(__name__)

# Cap on how many peers serve one striped heal (0 = every up-to-date peer).
# Must be set uniformly across the job: the chunk assignment is positional
# in the source list, so a mismatched cap would desynchronize senders from
# the healer.
HEAL_MAX_SOURCES_ENV = "TORCHFT_HEAL_MAX_SOURCES"

# Spare warm channels.  The outer-delta feed ring is bounded (a slow or
# dead spare must never grow an active replica's memory): oldest entries
# drop first and a spare that fell off the ring re-syncs via the warm
# snapshot instead.
SPARE_DELTA_BUF_MB_ENV = "TORCHFT_SPARE_DELTA_BUF_MB"  # default 128
_SPARE_DELTA_MAX_ENTRIES = 64
# one warm-range response must fit a wire frame with headroom
_WARM_RANGE_MAX_BYTES = 48 << 20
# how long a warm-range handler will wait for foreground collectives to
# drain before serving anyway (idle priority, but never starvation)
_WARM_YIELD_S = 0.25


def _spare_delta_buf_bytes() -> int:
    mb = knobs.get_float(SPARE_DELTA_BUF_MB_ENV, 128.0)
    return max(1 << 20, int(mb * (1 << 20)))


def compute_quorum_results(
    replica_id: str,
    group_rank: int,
    quorum: Quorum,
    init_sync: bool,
) -> ManagerQuorumResult:
    """Derive this rank's view of a quorum (``src/manager.rs:489-625``).

    A replica listed in the quorum's SPARE tail (not its participants)
    gets the spare view: membership facts + every participant's manager
    address for the warm channels, ``is_spare=True``, no rank and no heal
    assignment — it must warm, not train."""
    participants = sorted(quorum.participants, key=lambda p: p.replica_id)
    spare_ids = sorted(s.replica_id for s in quorum.spares)

    replica_rank = next(
        (i for i, p in enumerate(participants) if p.replica_id == replica_id), None
    )
    if replica_rank is None:
        if replica_id not in spare_ids:
            raise WireError(
                ErrCode.NOT_FOUND,
                f"replica {replica_id} not participating in returned quorum",
            )
        max_step = max((p.step for p in participants), default=0)
        max_participants = [p for p in participants if p.step == max_step]
        return ManagerQuorumResult(
            quorum_id=quorum.quorum_id,
            replica_rank=-1,
            replica_world_size=len(participants),
            store_address=(
                max_participants[group_rank % len(max_participants)].store_address
                if max_participants
                else ""
            ),
            max_step=max_step,
            max_replica_rank=None,
            max_world_size=len(max_participants),
            heal=False,
            commit_failures=max(
                (p.commit_failures for p in participants), default=0
            ),
            replica_ids=[p.replica_id for p in participants],
            is_spare=True,
            spare_replica_ids=spare_ids,
            all_manager_addresses=[p.address for p in participants],
            participant_capacities=[p.capacity for p in participants],
        )

    max_step = max(p.step for p in participants)
    max_participants = [p for p in participants if p.step == max_step]
    max_replica_rank = next(
        (
            i
            for i, p in enumerate(max_participants)
            if p.replica_id == replica_id
        ),
        None,
    )

    # The primary store for communicator rendezvous this round; spreading by
    # group_rank balances rendezvous load across up-to-date replicas.
    primary = max_participants[group_rank % len(max_participants)]

    # Replicas recover if behind max_step, or on a fresh job (max_step == 0
    # with init_sync) where everyone but the primary pulls the primary's init.
    force_recover = init_sync and max_step == 0
    recover_dst = [
        i
        for i, p in enumerate(participants)
        if p.step != max_step
        or (force_recover and primary.replica_id != p.replica_id)
    ]
    recover_dst_set = set(recover_dst)
    up_to_date = [i for i in range(len(participants)) if i not in recover_dst_set]

    assignments: Dict[int, List[int]] = {}
    recover_src: Optional[int] = None
    for i, recovering in enumerate(recover_dst):
        src = up_to_date[(i + group_rank) % len(up_to_date)]
        assignments.setdefault(src, []).append(recovering)
        if recovering == replica_rank:
            recover_src = src

    heal = recover_src is not None
    if heal:
        logger.info(
            "[Replica %s] healing is required step=%d, max_step=%d, recover_src_replica_rank=%d",
            replica_id,
            participants[replica_rank].step,
            max_step,
            recover_src,
        )

    # Striped healing (wire v2): the canonical ascending source set — every
    # up-to-date replica — so a healer can fetch disjoint chunk ranges from
    # ALL of them and every source knows to stage/serve.  The list must be
    # identical on every participant (the CommTransport chunk assignment is
    # positional), so the optional cap truncates deterministically.
    striped_sources = up_to_date if recover_dst else []
    max_sources = int(os.environ.get(HEAL_MAX_SOURCES_ENV, "0") or 0)
    if max_sources > 0:
        striped_sources = striped_sources[:max_sources]

    return ManagerQuorumResult(
        quorum_id=quorum.quorum_id,
        replica_rank=replica_rank,
        replica_world_size=len(participants),
        recover_src_manager_address=(
            participants[recover_src].address if recover_src is not None else ""
        ),
        recover_src_replica_rank=recover_src,
        recover_dst_replica_ranks=assignments.get(replica_rank, []),
        store_address=primary.store_address,
        max_step=max_step,
        max_replica_rank=max_replica_rank,
        max_world_size=len(max_participants),
        heal=heal,
        commit_failures=max(p.commit_failures for p in participants),
        replica_ids=[p.replica_id for p in participants],
        recover_src_replica_ranks=striped_sources,
        recover_src_manager_addresses=[
            participants[i].address for i in striped_sources
        ],
        all_recover_dst_replica_ranks=recover_dst,
        spare_replica_ids=spare_ids,
        all_manager_addresses=(
            [p.address for p in participants] if spare_ids else []
        ),
        # degraded-mode (wire v5): per-participant capacities, aligned with
        # ``replica_ids`` (sorted participant order) — the data-shard
        # rescale and weighted-outer-reduce inputs every rank needs
        participant_capacities=[p.capacity for p in participants],
    )


class ManagerServer:
    """Threaded manager sidecar for one replica group."""

    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        hostname: str = "",
        bind: str = "0.0.0.0:0",
        store_addr: str = "",
        world_size: int = 1,
        heartbeat_interval: float = 0.1,
        connect_timeout: float = 10.0,
        quorum_retries: int = 0,
        kill_fn: Optional[Callable[[str], None]] = None,
        health_fn: Optional[Callable[[], Optional[object]]] = None,
        role: int = ROLE_ACTIVE,
        warm_fn: Optional[Callable[[], Optional[object]]] = None,
        warm_step_fn: Optional[Callable[[], int]] = None,
        capacity_fn: Optional[Callable[[], float]] = None,
        metrics_fn: Optional[Callable[[], Dict[str, float]]] = None,
    ) -> None:
        self._replica_id = replica_id
        self._lighthouse_addr = lighthouse_addr
        self._hostname = hostname or socket.gethostname()
        self._store_addr = store_addr
        self._world_size = world_size
        self._heartbeat_interval = heartbeat_interval
        self._connect_timeout = connect_timeout
        self._quorum_retries = quorum_retries
        self._kill_fn = kill_fn or self._default_kill
        # comm-health provider: each heartbeat carries its latest snapshot
        # (a wire.CommHealth or None) to the lighthouse — the straggler-
        # detection input.  Errors are swallowed: a broken probe must never
        # kill the heartbeat that keeps this replica in the quorum.
        self._health_fn = health_fn
        # quorum role (wire v3): SPARE registers as a hot spare that never
        # counts toward membership; flipped to ACTIVE at promotion (read
        # per quorum round — a plain attribute write is the handshake)
        self.role = role
        # warm-snapshot provider for spare warm fetches: returns the
        # currently staged ``(step, PytreePlan)`` or None.  Served via
        # MGR_WARM_INDEX/MGR_WARM_RANGE entirely OUTSIDE the heal path so a
        # warming spare can never clobber (or block on) a real recovery.
        self._warm_fn = warm_fn
        # spare warm watermark provider (wire v4): rides every heartbeat so
        # the lighthouse's promotion-eligibility view stays fresh at beat
        # cadence, not quorum-RPC re-registration cadence
        self._warm_step_fn = warm_step_fn
        # degraded-capacity provider (wire v5): the surviving-device
        # fraction this replica re-lowered onto (1.0 = full width).  Rides
        # the quorum registration every round and — while degraded — each
        # direct heartbeat, so the lighthouse's wound→swap→evict ladder
        # reacts at beat cadence.  Errors are swallowed like health_fn:
        # the probe must never kill the beat.
        self._capacity_fn = capacity_fn
        # /metrics provider: extra per-replica gauges from the owning
        # Manager (declared names only — obs/metrics.py enforces).  The
        # endpoint rides the same listener via HTTP sniffing and serves a
        # TTL-cached sample set (TORCHFT_METRICS_TTL_S), so a scrape storm
        # re-polls the providers at most once per TTL.
        self._metrics_fn = metrics_fn
        self._metrics_cache: Tuple[float, bytes] = (float("-inf"), b"")
        self._metrics_cache_lock = threading.Lock()
        self.metrics_rebuilds = 0
        # hierarchical coordination plane: beats route through the zone
        # aggregator named by TORCHFT_AGG_ADDR (read live each beat) and
        # fall back to direct lighthouse beats on aggregator death.
        # Counters are single-writer (the heartbeat thread); readers
        # (coord_stats) tolerate a stale snapshot.
        self._beats_via_agg = 0
        self._beats_direct = 0
        self._agg_fallbacks = 0
        # foreground-busy probe (idle-priority warm serving): when set and
        # True, warm-range responses briefly yield so spare traffic never
        # contends with a live collective on the NIC
        self.busy_fn: Optional[Callable[[], bool]] = None
        # per-chunk crc table of the staged warm plan, cached by plan
        # identity (one full materialization pass per restage, not per
        # request) — the version watermarks spares diff against
        self._warm_hash_cache: Tuple[Optional[object], List[int]] = (None, [])
        # outer-sync delta feed: committed (step, fragment, payload) blobs
        # spares subscribe to (bounded ring; identical bytes on every
        # replica by construction, so any one publisher suffices)
        self._deltas: List[Tuple[int, int, bytes]] = []
        self._deltas_bytes = 0
        # chaos hook (Failure.PARTITION): a partitioned replica loses its
        # control plane too, so the drill pauses heartbeats alongside the
        # data-plane partition mask
        self.heartbeat_paused = False
        # lighthouse-restart detection: bumped by the heartbeat loop when a
        # beat SUCCEEDS after failures (the lighthouse came back); the
        # parked quorum forwarding call is interrupted so it re-registers
        # against the fresh lighthouse instead of wedging on a dead socket
        # until the quorum timeout
        self._lh_restart_gen = 0

        self._lock = threading.Condition()
        # quorum barrier state
        self._participants: Dict[int, QuorumMember] = {}
        self._checkpoint_metadata: Dict[int, str] = {}
        self._quorum_gen = 0
        self._latest: Optional[Quorum] = None
        self._latest_err: Optional[str] = None
        # should_commit barrier state
        self._commit_votes: Set[int] = set()
        self._commit_failures: Set[int] = set()
        self._commit_gen = 0
        self._commit_decision = False

        self._shutdown = False
        # persistent lighthouse connection for quorum forwarding; rounds are
        # normally sequential, but a timed-out round can overlap the next,
        # so serialize access
        self._lh_quorum_client: Optional[LighthouseClient] = None
        self._lh_client_lock = threading.Lock()

        self._sock = create_listener(bind, backlog=64)
        self._port: int = self._sock.getsockname()[1]

        threading.Thread(
            target=self._serve, name="tpuft_manager_accept", daemon=True
        ).start()
        threading.Thread(
            target=self._run_heartbeat, name="tpuft_manager_heartbeat", daemon=True
        ).start()
        logger.info(
            "[Replica %s] Manager listening on %s", replica_id, self.address()
        )

    # -- public -------------------------------------------------------------

    @property
    def port(self) -> int:
        return self._port

    def address(self) -> str:
        return f"{self._hostname}:{self._port}"

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            self._lock.notify_all()
        # best-effort: an in-flight quorum RPC may hold the lock until its
        # deadline; don't block shutdown on it (threads are daemonized)
        if self._lh_client_lock.acquire(timeout=1.0):
            try:
                if self._lh_quorum_client is not None:
                    self._lh_quorum_client.close()
                    self._lh_quorum_client = None
            finally:
                self._lh_client_lock.release()

    @staticmethod
    def _default_kill(msg: str) -> None:
        logger.warning("got kill request: %s", msg)
        os._exit(1)

    # -- background loops ---------------------------------------------------

    def _run_heartbeat(self) -> None:
        """Heartbeat until shutdown (``src/manager.rs:194-216``), routed
        through the zone aggregator when one is configured
        (``TORCHFT_AGG_ADDR``, wire v4) and falling back to direct
        lighthouse beats whenever the aggregator is unreachable —
        aggregator death costs one beat interval of reporting, never
        membership.  Lighthouse-restart detection works on both paths: the
        direct path sees beat-success-after-failure itself; the aggregated
        path learns it from the restart counter every AGG_BEAT_RESP
        carries."""
        client: Optional[LighthouseClient] = None
        agg_client = None
        agg_down_until = 0.0
        agg_lh_restarts: Optional[int] = None
        beat_failures = 0
        while not self._shutdown:
            if self.heartbeat_paused:
                time.sleep(self._heartbeat_interval)
                continue
            health = None
            if self._health_fn is not None:
                try:
                    health = self._health_fn()
                except Exception:  # noqa: BLE001 — probe must not kill beats
                    health = None
            warm_step = -1
            if self._warm_step_fn is not None:
                try:
                    warm_step = int(self._warm_step_fn())
                except Exception:  # noqa: BLE001 — probe must not kill beats
                    warm_step = -1
            capacity = self._capacity()
            sent = False
            from torchft_tpu.wire import manager_quorum_wire_version

            agg_addr = knobs.get_str("TORCHFT_AGG_ADDR", "")
            if (
                agg_addr
                and manager_quorum_wire_version() >= 4
                and time.monotonic() >= agg_down_until
            ):
                try:
                    if agg_client is None or agg_client.addr != agg_addr:
                        if agg_client is not None:
                            agg_client.close()
                        from torchft_tpu.coord.aggregator import AggMemberClient

                        agg_client = AggMemberClient(
                            agg_addr, connect_timeout=self._connect_timeout
                        )
                    resp = agg_client.beat(
                        self._replica_id,
                        role=self.role,
                        warm_step=warm_step,
                        health=health,
                    )
                    sent = True
                    # ftlint: ignore[thread-safety] — single-writer counter
                    self._beats_via_agg += 1
                    restarts = int(resp["lh_restarts"])  # type: ignore[arg-type]
                    restart_seen = (
                        agg_lh_restarts is not None
                        and restarts > agg_lh_restarts
                    )
                    agg_lh_restarts = restarts
                    if not resp["upstream_ok"]:
                        # the aggregator itself can't reach the lighthouse
                        # (asymmetric partition: we can reach both, it can
                        # reach neither of its flushes through).  A beat
                        # parked in a dead-ended aggregator is NOT a beat —
                        # fall through to a DIRECT one this round, or the
                        # whole zone ages out together when the grace
                        # window expires.  The direct branch tracks its own
                        # failures, so restart detection (and the parked-
                        # quorum interrupt) follows whichever path actually
                        # reaches the lighthouse.
                        sent = False
                    elif beat_failures or restart_seen:
                        beat_failures = 0
                        # ftlint: ignore[thread-safety] — single-writer counter
                        self._lh_restart_gen += 1
                        self._interrupt_lh_quorum()
                except (OSError, TimeoutError, WireError) as e:
                    logger.info(
                        "[Replica %s] aggregator %s unreachable, falling "
                        "back to direct beats: %s",
                        self._replica_id,
                        agg_addr,
                        e,
                    )
                    if agg_client is not None:
                        agg_client.close()
                    agg_client = None
                    agg_lh_restarts = None
                    agg_down_until = time.monotonic() + knobs.get_float(
                        "TORCHFT_AGG_RETRY_S", 2.0
                    )
                    # ftlint: ignore[thread-safety] — single-writer counter
                    self._agg_fallbacks += 1
            if not sent:
                try:
                    if client is None:
                        client = LighthouseClient(
                            self._lighthouse_addr,
                            connect_timeout=self._connect_timeout,
                        )
                    client.heartbeat(
                        self._replica_id,
                        health=health,
                        warm_step=warm_step if warm_step >= 0 else None,
                        capacity=capacity if capacity != 1.0 else None,
                    )
                    # ftlint: ignore[thread-safety] — single-writer counter
                    self._beats_direct += 1
                    if beat_failures:
                        # the lighthouse answered after failing: it (likely)
                        # restarted with empty soft state.  A quorum RPC
                        # parked against the DEAD incarnation would wedge
                        # until its timeout; interrupt it so it re-registers
                        # (idempotent) against the fresh lighthouse
                        # immediately.
                        beat_failures = 0
                        # single-writer counter: only this heartbeat thread
                        # ever increments; readers tolerate a stale
                        # generation (they re-check next round)
                        # ftlint: ignore[thread-safety] — single-writer counter
                        self._lh_restart_gen += 1
                        self._interrupt_lh_quorum()
                except (OSError, TimeoutError, WireError) as e:
                    beat_failures += 1
                    logger.info(
                        "[Replica %s] failed to send heartbeat to lighthouse: %s",
                        self._replica_id,
                        e,
                    )
                    if client is not None:
                        client.close()
                    client = None
            time.sleep(self._heartbeat_interval)
        if client is not None:
            client.close()
        if agg_client is not None:
            agg_client.close()

    def _capacity(self) -> float:
        """This replica's current degraded-capacity fraction (1.0 when no
        provider is wired or the probe fails — full width is the safe
        default: it never triggers the swap/evict rungs)."""
        if self._capacity_fn is None:
            return 1.0
        try:
            cap = float(self._capacity_fn())
        except Exception:  # noqa: BLE001 — probe must not kill beats/quorums
            return 1.0
        return min(1.0, max(0.0, cap)) if cap > 0.0 else 1.0

    def coord_stats(self) -> Dict[str, int]:
        """Coordination-plane beat routing counters (observability: the
        manager folds them into the ``torchft_quorums`` extras)."""
        return {
            "coord_beats_via_agg": self._beats_via_agg,
            "coord_beats_direct": self._beats_direct,
            "coord_agg_fallbacks": self._agg_fallbacks,
        }

    # -- /metrics (Prometheus text; HTTP sniffed off the RPC port) ----------

    def _metrics_text(self) -> bytes:
        """TTL-cached Prometheus text: scrape storms rebuild (and re-poll
        the Manager-side providers) at most once per
        ``TORCHFT_METRICS_TTL_S``; concurrent scrapes serialize on the
        cache lock, never on the quorum barrier."""
        ttl = knobs.get_float("TORCHFT_METRICS_TTL_S", 0.5)
        now = time.monotonic()
        with self._metrics_cache_lock:
            built_ts, raw = self._metrics_cache
            if raw and now - built_ts < ttl:
                return raw
            raw = self._metrics_rebuild().encode()
            self._metrics_cache = (now, raw)
            return raw

    def _metrics_rebuild(self) -> str:
        # ftlint: ignore[thread-safety] — cache-lock-held rebuild counter
        self.metrics_rebuilds += 1
        sample = obs_metrics.metric_sample
        samples = []
        provided: Dict[str, float] = {}
        if self._metrics_fn is not None:
            try:
                provided = self._metrics_fn() or {}
            except Exception:  # noqa: BLE001 — probe must not kill a scrape
                provided = {}
        for name in sorted(provided):
            samples.append(sample(name, provided[name]))
        if "torchft_mgr_capacity" not in provided and self._capacity_fn:
            samples.append(sample("torchft_mgr_capacity", self._capacity()))
        health = None
        if self._health_fn is not None:
            try:
                health = self._health_fn()
            except Exception:  # noqa: BLE001 — probe must not kill a scrape
                health = None
        if health is not None:
            samples += [
                sample("torchft_mgr_comm_tx_bytes_total", health.tx_bytes),
                sample("torchft_mgr_comm_rx_bytes_total", health.rx_bytes),
                sample("torchft_mgr_comm_stalls_total", health.stalls),
                sample("torchft_mgr_comm_reconnects_total", health.reconnects),
                sample("torchft_mgr_comm_failovers_total", health.failovers),
                sample("torchft_mgr_comm_faults_total", health.faults),
            ]
        coord = self.coord_stats()
        samples += [
            sample(
                "torchft_mgr_beats_via_agg_total", coord["coord_beats_via_agg"]
            ),
            sample(
                "torchft_mgr_beats_direct_total", coord["coord_beats_direct"]
            ),
            sample(
                "torchft_mgr_agg_fallbacks_total", coord["coord_agg_fallbacks"]
            ),
        ]
        return obs_metrics.render(samples)

    def _handle_http(self, conn: socket.socket) -> None:
        """Answer one HTTP request on the manager port: ``/metrics`` in
        Prometheus text format (gated by ``TORCHFT_METRICS``)."""
        path = read_http_path(conn)
        if path is None:
            return
        if path == "/metrics" and knobs.get_bool("TORCHFT_METRICS", True):
            body = self._metrics_text()
            status = "200 OK"
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        else:
            body = b"not found\n"
            status, ctype = "404 Not Found", "text/plain"
        send_http_response(conn, status, ctype, body)

    def _interrupt_lh_quorum(self) -> None:
        """Sever the persistent quorum-forwarding connection WITHOUT taking
        its rpc lock (the parked call holds it): the blocked recv errors
        out and ``_run_quorum`` retries against the restarted lighthouse."""
        client = self._lh_quorum_client
        if client is not None:
            try:
                client.interrupt()
            except OSError:  # pragma: no cover — already torn down
                pass

    # -- connection handling ------------------------------------------------

    def _serve(self) -> None:
        while not self._shutdown:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            configure_server_socket(conn)
            threading.Thread(
                target=self._handle_conn,
                args=(conn,),
                name="tpuft_manager_conn",
                daemon=True,
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            # sniff HTTP vs framed RPC on one port (lighthouse pattern) —
            # but with NO idle deadline: a ManagerClient connects eagerly
            # in Manager.__init__ and may not issue its first quorum RPC
            # until after a minutes-long model build, and the pre-sniff
            # server blocked in recv_frame indefinitely for exactly that
            # reason.  The blocking MSG_PEEK preserves it; the inner loop
            # only spins between bytes 1..4 of one frame header.
            head = b""
            while len(head) < 4:
                head = conn.recv(4, socket.MSG_PEEK)
                if not head:
                    return  # peer closed before sending anything
                if len(head) < 4:
                    time.sleep(0.01)
            if head[:3] in (b"GET", b"POS", b"HEA"):
                self._handle_http(conn)
                return
            while True:
                msg_type, r = recv_frame(conn)
                if msg_type == MsgType.MGR_QUORUM_REQ:
                    self._handle_quorum(conn, r)
                elif msg_type == MsgType.MGR_CKPT_META_REQ:
                    rank = r.i64()
                    with self._lock:
                        meta = self._checkpoint_metadata.get(rank)
                    if meta is None:
                        send_error(conn, ErrCode.INVALID, "rank not found")
                    else:
                        send_frame(
                            conn,
                            MsgType.MGR_CKPT_META_RESP,
                            Writer().string(meta).payload(),
                        )
                elif msg_type == MsgType.MGR_SHOULD_COMMIT_REQ:
                    self._handle_should_commit(conn, r)
                elif msg_type == MsgType.MGR_WARM_INDEX_REQ:
                    self._handle_warm_index(conn)
                elif msg_type == MsgType.MGR_WARM_RANGE_REQ:
                    self._handle_warm_range(conn, r)
                elif msg_type == MsgType.MGR_DELTA_REQ:
                    self._handle_deltas(conn, r)
                elif msg_type == MsgType.MGR_KILL_REQ:
                    msg = r.string()
                    send_frame(conn, MsgType.MGR_KILL_RESP)
                    self._kill_fn(msg)
                else:
                    send_error(conn, ErrCode.INVALID, f"bad manager op {msg_type}")
        except (ConnectionError, OSError, WireError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- spare warm channels ------------------------------------------------

    def publish_delta(self, step: int, frag: int, payload: bytes) -> None:
        """Append one committed outer-sync delta to the feed ring.  The
        bytes are identical on every replica by construction (the sharded
        outer sync allgathers one wire-format delta), so any single
        publisher keeps every subscribed spare's shadow bit-exact."""
        if len(payload) > _WARM_RANGE_MAX_BYTES:
            # a too-big entry can never ride a wire frame: serving it
            # would fail the spare's recv on EVERY poll (the cursor never
            # advances past it), permanently killing the feed.  Refuse it
            # here — the spare's shadow demotes to chunk-store warming,
            # which chunks arbitrarily large state.
            logger.warning(
                "[Replica %s] outer delta (step %d frag %d, %d bytes) "
                "exceeds the frame budget; dropped — spares warm via "
                "snapshot chunks instead",
                self._replica_id,
                step,
                frag,
                len(payload),
            )
            return
        with self._lock:
            self._deltas.append((step, frag, payload))
            self._deltas_bytes += len(payload)
            cap = _spare_delta_buf_bytes()
            while self._deltas and (
                self._deltas_bytes > cap
                or len(self._deltas) > _SPARE_DELTA_MAX_ENTRIES
            ):
                _s, _f, old = self._deltas.pop(0)
                self._deltas_bytes -= len(old)

    def _handle_deltas(self, conn: socket.socket, r: Reader) -> None:
        """Serve feed entries strictly newer than the subscriber's
        ``(step, frag)`` cursor, oldest first, capped to one frame."""
        after_step = r.i64()
        after_frag = r.i64()
        with self._lock:
            fresh = [
                e for e in self._deltas if (e[0], e[1]) > (after_step, after_frag)
            ]
        w = Writer()
        picked: List[Tuple[int, int, bytes]] = []
        budget = _WARM_RANGE_MAX_BYTES
        for step, frag, payload in fresh:
            if picked and budget - len(payload) < 0:
                break
            picked.append((step, frag, payload))
            budget -= len(payload)
        w.u32(len(picked))
        for step, frag, payload in picked:
            w.i64(step).i64(frag).blob(payload)
        send_frame(conn, MsgType.MGR_DELTA_RESP, w.payload())

    def _warm_plan(self):
        if self._warm_fn is None:
            return None
        try:
            return self._warm_fn()
        except Exception:  # noqa: BLE001 — a broken probe must not kill
            # the connection loop; the spare just sees "nothing staged"
            logger.exception(
                "[Replica %s] warm snapshot provider failed", self._replica_id
            )
            return None

    def _warm_chunk_hashes(self, plan) -> List[int]:
        """crc32 per warm chunk (array-payload granularity — chunk keys
        are STABLE across steps for a fixed tree structure, unlike
        serialized-stream offsets whose pickled header length can drift).
        These are the per-chunk version watermarks: a spare refetches only
        chunks whose crc moved since its last pass."""
        cached_plan, cached = self._warm_hash_cache
        if cached_plan is plan:
            return cached
        import zlib

        from torchft_tpu.checkpointing.serialization import (
            array_chunk_ranges,
            as_byte_view,
            heal_chunk_bytes,
        )

        hashes = []
        for ai, lo, hi in array_chunk_ranges(
            plan.leaf_nbytes, heal_chunk_bytes()
        ):
            view = as_byte_view(plan._materialize(ai))[lo:hi]
            hashes.append(zlib.crc32(view))
        self._warm_hash_cache = (plan, hashes)
        return hashes

    def _handle_warm_index(self, conn: socket.socket) -> None:
        staged = self._warm_plan()
        if staged is None:
            send_error(conn, ErrCode.NOT_FOUND, "no warm snapshot staged")
            return
        step, plan = staged
        from torchft_tpu.checkpointing.serialization import heal_chunk_bytes

        hashes = self._warm_chunk_hashes(plan)
        w = Writer()
        w.i64(step)
        w.u64(plan.total_len)
        w.u64(len(plan.header))
        w.string(plan.header_digest())
        w.u32(len(plan.leaf_nbytes))
        for n in plan.leaf_nbytes:
            w.u64(n)
        w.u64(heal_chunk_bytes())
        w.u32(len(hashes))
        for h in hashes:
            w.u32(h)
        send_frame(conn, MsgType.MGR_WARM_INDEX_RESP, w.payload())

    def _handle_warm_range(self, conn: socket.socket, r: Reader) -> None:
        """Serve bytes [start, stop) of the warm snapshot staged at exactly
        ``step`` — a moved snapshot is NOT served (the spare's watermark
        protocol re-fetches the index rather than trusting a stale range).
        Idle priority: yields briefly while foreground collectives run."""
        step = r.i64()
        start = r.u64()
        stop = r.u64()
        staged = self._warm_plan()
        if staged is None or staged[0] != step:
            send_error(
                conn,
                ErrCode.NOT_FOUND,
                f"warm snapshot at step {step} no longer staged",
            )
            return
        _step, plan = staged
        if not 0 <= start <= stop <= plan.total_len:
            send_error(
                conn,
                ErrCode.INVALID,
                f"bad warm range [{start}, {stop}) of {plan.total_len}",
            )
            return
        if stop - start > _WARM_RANGE_MAX_BYTES:
            send_error(
                conn,
                ErrCode.INVALID,
                f"warm range too large ({stop - start} bytes)",
            )
            return
        if self.busy_fn is not None:
            yield_deadline = time.monotonic() + _WARM_YIELD_S
            while time.monotonic() < yield_deadline:
                try:
                    if not self.busy_fn():
                        break
                except Exception:  # noqa: BLE001 — probe must not block serving
                    break
                time.sleep(0.01)
        import io

        buf = io.BytesIO()
        plan.write_range(start, stop, buf)
        send_frame(
            conn,
            MsgType.MGR_WARM_RANGE_RESP,
            Writer().i64(step).blob(buf.getvalue()).payload(),
        )

    # -- quorum barrier -----------------------------------------------------

    def _handle_quorum(self, conn: socket.socket, r: Reader) -> None:
        group_rank = r.i64()
        step = r.i64()
        checkpoint_metadata = r.string()
        shrink_only = r.boolean()
        init_sync = r.boolean()
        commit_failures = r.i64()
        timeout_ms = r.u64()
        deadline = time.monotonic() + timeout_ms / 1000.0

        logger.info(
            "[Replica %s] Start quorum for group_rank %d", self._replica_id, group_rank
        )

        with self._lock:
            self._checkpoint_metadata[group_rank] = checkpoint_metadata
            member = QuorumMember(
                replica_id=self._replica_id,
                address=self.address(),
                store_address=self._store_addr,
                step=step,
                world_size=self._world_size,
                shrink_only=shrink_only,
                commit_failures=commit_failures,
                role=self.role,
                capacity=self._capacity(),
            )
            self._participants[group_rank] = member
            gen = self._quorum_gen

            if len(self._participants) == self._world_size:
                self._participants.clear()
                threading.Thread(
                    target=self._run_quorum,
                    args=(member, timeout_ms / 1000.0),
                    name="tpuft_manager_quorum",
                    daemon=True,
                ).start()

            failure: Optional[Tuple[ErrCode, str]] = None
            while self._quorum_gen == gen:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._shutdown:
                    failure = (
                        ErrCode.SHUTDOWN if self._shutdown else ErrCode.TIMEOUT,
                        f"manager quorum for group_rank {group_rank} "
                        f"{'aborted by shutdown' if self._shutdown else 'timed out'}",
                    )
                    break
                self._lock.wait(min(remaining, 0.1))
            quorum = self._latest
            quorum_err = self._latest_err

        # socket IO outside the server lock (a wedged client must not block
        # the barrier for other ranks)
        conn.settimeout(30.0)
        try:
            if failure is not None:
                send_error(conn, failure[0], failure[1])
                return

            if quorum is None:
                send_error(conn, ErrCode.UNKNOWN, quorum_err or "quorum failed")
                return

            logger.info(
                "[Replica %s] Finished quorum for group_rank %d",
                self._replica_id,
                group_rank,
            )
            try:
                reply = compute_quorum_results(
                    self._replica_id, group_rank, quorum, init_sync
                )
            except WireError as e:
                send_error(conn, e.code, str(e))
                return
            w = Writer()
            reply.encode(w)
            send_frame(conn, MsgType.MGR_QUORUM_RESP, w.payload())
        finally:
            conn.settimeout(None)

    def _run_quorum(self, requester: QuorumMember, timeout_s: float) -> None:
        """Forward the group's request to the lighthouse with retries and
        broadcast the result to every parked rank.

        Retry exhaustion is a BROADCAST FAILURE, never a silent park: after
        ``quorum_retries`` failed attempts (plus any free retries granted by
        a detected lighthouse restart, bounded only by the caller's
        deadline), ``_latest`` is cleared, ``_latest_err`` records the last
        transport error, ``_quorum_gen`` is bumped, and ``_lock`` is
        notified — so ranks blocked in the quorum wait wake immediately with
        the error instead of each burning its own full deadline.
        """
        logger.info(
            "[Replica %s] All workers joined - starting quorum", self._replica_id
        )
        quorum: Optional[Quorum] = None
        last_err = "unknown"
        deadline = time.monotonic() + timeout_s
        attempt = 0
        while attempt <= self._quorum_retries:
            if self.heartbeat_paused:
                # chaos partition: the control plane is severed — a quorum
                # rpc is an implicit lighthouse heartbeat, so forwarding it
                # would keep this "partitioned" replica looking alive
                last_err = "control plane severed (chaos partition)"
                break
            restart_gen = self._lh_restart_gen
            try:
              with self._lh_client_lock:
                # persistent connection across rounds (the reference keeps a
                # tonic channel, src/manager.rs:250-306); recreated on failure
                if self._lh_quorum_client is None:
                    self._lh_quorum_client = LighthouseClient(
                        self._lighthouse_addr, connect_timeout=self._connect_timeout
                    )
                # One in-flight lighthouse RPC on the shared persistent
                # client is this lock's purpose; a parked call is severed by
                # the heartbeat loop's interrupt() on lighthouse restart
                # (tested by the lighthouse-bounce unit test).
                # ftlint: ignore[blocking-under-lock] — serialized rpc by design
                quorum = self._lh_quorum_client.quorum(
                    replica_id=requester.replica_id,
                    timeout=max(0.1, deadline - time.monotonic()),
                    address=requester.address,
                    store_address=requester.store_address,
                    step=requester.step,
                    world_size=requester.world_size,
                    shrink_only=requester.shrink_only,
                    commit_failures=requester.commit_failures,
                    role=self.role,
                    capacity=self._capacity(),
                )
                break
            except (OSError, TimeoutError, WireError) as e:
                last_err = str(e)
                logger.info(
                    "[Replica %s] lighthouse quorum failed (attempt %d): %s",
                    self._replica_id,
                    attempt,
                    e,
                )
                if self._lh_quorum_client is not None:
                    self._lh_quorum_client.close()
                    self._lh_quorum_client = None
                if (
                    self._lh_restart_gen != restart_gen
                    and time.monotonic() < deadline
                    and not self._shutdown
                ):
                    # the heartbeat loop detected a lighthouse restart and
                    # interrupted this (now moot) parked call: re-register
                    # against the fresh lighthouse at once.  Registration is
                    # idempotent server-side and this retry is FREE (not
                    # counted against quorum_retries) — bounded only by the
                    # caller's deadline — so a default retries=0 fleet still
                    # rides out a lighthouse bounce instead of wedging until
                    # the quorum timeout.
                    continue
                attempt += 1
                if attempt <= self._quorum_retries:
                    # only back off when another attempt remains — otherwise
                    # broadcast the failure to parked ranks immediately
                    time.sleep(
                        max(0.1, timeout_s / max(self._quorum_retries + 1, 1))
                    )

        with self._lock:
            self._latest = quorum
            self._latest_err = (
                None
                if quorum is not None
                else f"lighthouse quorum failed after {self._quorum_retries} retries: {last_err}"
            )
            self._quorum_gen += 1
            self._lock.notify_all()

    # -- should_commit barrier ----------------------------------------------

    def _handle_should_commit(self, conn: socket.socket, r: Reader) -> None:
        group_rank = r.i64()
        _step = r.i64()
        should_commit = r.boolean()
        timeout_ms = r.u64()
        deadline = time.monotonic() + timeout_ms / 1000.0

        logger.info(
            "[Replica %s] should_commit request from %d should_commit=%s",
            self._replica_id,
            group_rank,
            should_commit,
        )

        with self._lock:
            if not should_commit:
                self._commit_failures.add(group_rank)
            self._commit_votes.add(group_rank)
            gen = self._commit_gen

            if len(self._commit_votes) == self._world_size:
                decision = len(self._commit_failures) == 0
                logger.info(
                    "[Replica %s] should_commit completed should_commit=%s",
                    self._replica_id,
                    decision,
                )
                self._commit_decision = decision
                self._commit_votes.clear()
                self._commit_failures.clear()
                self._commit_gen += 1
                self._lock.notify_all()

            failure: Optional[Tuple[ErrCode, str]] = None
            while self._commit_gen == gen:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._shutdown:
                    failure = (
                        ErrCode.SHUTDOWN if self._shutdown else ErrCode.TIMEOUT,
                        f"should_commit for group_rank {group_rank} "
                        f"{'aborted by shutdown' if self._shutdown else 'timed out'}",
                    )
                    break
                self._lock.wait(min(remaining, 0.1))
            decision = self._commit_decision

        conn.settimeout(30.0)
        try:
            if failure is not None:
                send_error(conn, failure[0], failure[1])
                return
            send_frame(
                conn,
                MsgType.MGR_SHOULD_COMMIT_RESP,
                Writer().boolean(decision).payload(),
            )
        finally:
            conn.settimeout(None)


class ManagerClient(RpcClient):
    """Client used by every local rank to reach its group's ManagerServer
    (pyo3 analog ``src/lib.rs:153-282``)."""

    def __init__(self, addr: str, connect_timeout: float = 10.0) -> None:
        super().__init__(addr, connect_timeout=connect_timeout)

    def _call(self, msg_type: MsgType, payload: bytes, timeout: float) -> Tuple[int, Reader]:
        return self.call(msg_type, payload, timeout)

    def _quorum(
        self,
        group_rank: int,
        step: int,
        checkpoint_metadata: str,
        shrink_only: bool,
        timeout: float,
        init_sync: bool = True,
        commit_failures: int = 0,
    ) -> ManagerQuorumResult:
        w = (
            Writer()
            .i64(group_rank)
            .i64(step)
            .string(checkpoint_metadata)
            .boolean(shrink_only)
            .boolean(init_sync)
            .i64(commit_failures)
            .u64(int(timeout * 1000))
        )
        msg_type, r = self._call(MsgType.MGR_QUORUM_REQ, w.payload(), timeout)
        raise_if_error(msg_type, r)
        return ManagerQuorumResult.decode(r)

    def _checkpoint_metadata(self, rank: int, timeout: float) -> str:
        msg_type, r = self._call(
            MsgType.MGR_CKPT_META_REQ, Writer().i64(rank).payload(), timeout
        )
        raise_if_error(msg_type, r)
        return r.string()

    def should_commit(
        self, group_rank: int, step: int, should_commit: bool, timeout: float
    ) -> bool:
        w = (
            Writer()
            .i64(group_rank)
            .i64(step)
            .boolean(should_commit)
            .u64(int(timeout * 1000))
        )
        msg_type, r = self._call(MsgType.MGR_SHOULD_COMMIT_REQ, w.payload(), timeout)
        raise_if_error(msg_type, r)
        return r.boolean()

    def kill(self, msg: str, timeout: float = 10.0) -> None:
        msg_type, r = self._call(MsgType.MGR_KILL_REQ, Writer().string(msg).payload(), timeout)
        raise_if_error(msg_type, r)

    # -- spare warm channels ------------------------------------------------

    def warm_index(self, timeout: float = 10.0) -> Dict[str, object]:
        """Chunk-addressable index of the peer's staged warm snapshot:
        ``{"step", "total_len", "header_len", "header_digest",
        "leaf_nbytes"}``.  Raises WireError(NOT_FOUND) when nothing is
        staged (the peer has no spares to feed, or just committed)."""
        msg_type, r = self._call(MsgType.MGR_WARM_INDEX_REQ, b"", timeout)
        raise_if_error(msg_type, r)
        return {
            "step": r.i64(),
            "total_len": r.u64(),
            "header_len": r.u64(),
            "header_digest": r.string(),
            "leaf_nbytes": [r.u64() for _ in range(r.u32())],
            "chunk_target_bytes": r.u64(),
            "chunk_hashes": [r.u32() for _ in range(r.u32())],
        }

    def warm_range(
        self, step: int, start: int, stop: int, timeout: float = 30.0
    ) -> bytes:
        """Bytes [start, stop) of the warm snapshot staged at ``step``;
        NOT_FOUND when the snapshot moved (refetch the index)."""
        w = Writer().i64(step).u64(start).u64(stop)
        msg_type, r = self._call(MsgType.MGR_WARM_RANGE_REQ, w.payload(), timeout)
        raise_if_error(msg_type, r)
        r.i64()  # echoed step
        return r.blob()

    def deltas(
        self, after_step: int, after_frag: int, timeout: float = 10.0
    ) -> List[Tuple[int, int, bytes]]:
        """Outer-sync delta feed entries strictly newer than the
        ``(after_step, after_frag)`` cursor, oldest first."""
        w = Writer().i64(after_step).i64(after_frag)
        msg_type, r = self._call(MsgType.MGR_DELTA_REQ, w.payload(), timeout)
        raise_if_error(msg_type, r)
        return [(r.i64(), r.i64(), r.blob()) for _ in range(r.u32())]
