"""Per-replica-group Manager sidecar: barrier, recovery math, commit voting.

The reference runs a Rust ``ManagerServer`` inside the rank-0 Python process
of every replica group (``src/manager.rs:80-328``); all local ranks connect
to it with a ``ManagerClient``.  Its three jobs:

1. **Intra-group quorum barrier** (``src/manager.rs:332-402``): collect one
   ``quorum`` RPC from each of the group's ``world_size`` ranks; when the
   last arrives, forward a single request to the lighthouse (with retries and
   client re-creation, ``src/manager.rs:250-306``) and broadcast the resulting
   quorum to every parked rank.
2. **Recovery assignment** (``compute_quorum_results``,
   ``src/manager.rs:489-625``): sort participants by replica_id for a
   deterministic replica_rank; find the max-step set; pick the primary store
   owner ``group_rank % len(max_participants)``; round-robin assign each
   stale replica a healthy recovery source, offset by group_rank so different
   group ranks spread load across sources.
3. **should_commit AND-barrier** (``src/manager.rs:423-479``): collect votes
   from all local ranks; the decision is the AND of all votes; broadcast and
   reset.

It also stores per-rank checkpoint metadata for healing peers
(``src/manager.rs:404-421``), heartbeats the lighthouse every
``heartbeat_interval`` (``src/manager.rs:194-216``), and answers ``Kill``
by exiting the process (``src/manager.rs:481-487``).
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from torchft_tpu.lighthouse import LighthouseClient
from torchft_tpu.wire import (
    ErrCode,
    ManagerQuorumResult,
    MsgType,
    Quorum,
    QuorumMember,
    Reader,
    RpcClient,
    WireError,
    Writer,
    configure_server_socket,
    create_listener,
    raise_if_error,
    recv_frame,
    send_error,
    send_frame,
)

logger = logging.getLogger(__name__)

# Cap on how many peers serve one striped heal (0 = every up-to-date peer).
# Must be set uniformly across the job: the chunk assignment is positional
# in the source list, so a mismatched cap would desynchronize senders from
# the healer.
HEAL_MAX_SOURCES_ENV = "TORCHFT_HEAL_MAX_SOURCES"


def compute_quorum_results(
    replica_id: str,
    group_rank: int,
    quorum: Quorum,
    init_sync: bool,
) -> ManagerQuorumResult:
    """Derive this rank's view of a quorum (``src/manager.rs:489-625``)."""
    participants = sorted(quorum.participants, key=lambda p: p.replica_id)

    replica_rank = next(
        (i for i, p in enumerate(participants) if p.replica_id == replica_id), None
    )
    if replica_rank is None:
        raise WireError(
            ErrCode.NOT_FOUND,
            f"replica {replica_id} not participating in returned quorum",
        )

    max_step = max(p.step for p in participants)
    max_participants = [p for p in participants if p.step == max_step]
    max_replica_rank = next(
        (
            i
            for i, p in enumerate(max_participants)
            if p.replica_id == replica_id
        ),
        None,
    )

    # The primary store for communicator rendezvous this round; spreading by
    # group_rank balances rendezvous load across up-to-date replicas.
    primary = max_participants[group_rank % len(max_participants)]

    # Replicas recover if behind max_step, or on a fresh job (max_step == 0
    # with init_sync) where everyone but the primary pulls the primary's init.
    force_recover = init_sync and max_step == 0
    recover_dst = [
        i
        for i, p in enumerate(participants)
        if p.step != max_step
        or (force_recover and primary.replica_id != p.replica_id)
    ]
    recover_dst_set = set(recover_dst)
    up_to_date = [i for i in range(len(participants)) if i not in recover_dst_set]

    assignments: Dict[int, List[int]] = {}
    recover_src: Optional[int] = None
    for i, recovering in enumerate(recover_dst):
        src = up_to_date[(i + group_rank) % len(up_to_date)]
        assignments.setdefault(src, []).append(recovering)
        if recovering == replica_rank:
            recover_src = src

    heal = recover_src is not None
    if heal:
        logger.info(
            "[Replica %s] healing is required step=%d, max_step=%d, recover_src_replica_rank=%d",
            replica_id,
            participants[replica_rank].step,
            max_step,
            recover_src,
        )

    # Striped healing (wire v2): the canonical ascending source set — every
    # up-to-date replica — so a healer can fetch disjoint chunk ranges from
    # ALL of them and every source knows to stage/serve.  The list must be
    # identical on every participant (the CommTransport chunk assignment is
    # positional), so the optional cap truncates deterministically.
    striped_sources = up_to_date if recover_dst else []
    max_sources = int(os.environ.get(HEAL_MAX_SOURCES_ENV, "0") or 0)
    if max_sources > 0:
        striped_sources = striped_sources[:max_sources]

    return ManagerQuorumResult(
        quorum_id=quorum.quorum_id,
        replica_rank=replica_rank,
        replica_world_size=len(participants),
        recover_src_manager_address=(
            participants[recover_src].address if recover_src is not None else ""
        ),
        recover_src_replica_rank=recover_src,
        recover_dst_replica_ranks=assignments.get(replica_rank, []),
        store_address=primary.store_address,
        max_step=max_step,
        max_replica_rank=max_replica_rank,
        max_world_size=len(max_participants),
        heal=heal,
        commit_failures=max(p.commit_failures for p in participants),
        replica_ids=[p.replica_id for p in participants],
        recover_src_replica_ranks=striped_sources,
        recover_src_manager_addresses=[
            participants[i].address for i in striped_sources
        ],
        all_recover_dst_replica_ranks=recover_dst,
    )


class ManagerServer:
    """Threaded manager sidecar for one replica group."""

    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        hostname: str = "",
        bind: str = "0.0.0.0:0",
        store_addr: str = "",
        world_size: int = 1,
        heartbeat_interval: float = 0.1,
        connect_timeout: float = 10.0,
        quorum_retries: int = 0,
        kill_fn: Optional[Callable[[str], None]] = None,
        health_fn: Optional[Callable[[], Optional[object]]] = None,
    ) -> None:
        self._replica_id = replica_id
        self._lighthouse_addr = lighthouse_addr
        self._hostname = hostname or socket.gethostname()
        self._store_addr = store_addr
        self._world_size = world_size
        self._heartbeat_interval = heartbeat_interval
        self._connect_timeout = connect_timeout
        self._quorum_retries = quorum_retries
        self._kill_fn = kill_fn or self._default_kill
        # comm-health provider: each heartbeat carries its latest snapshot
        # (a wire.CommHealth or None) to the lighthouse — the straggler-
        # detection input.  Errors are swallowed: a broken probe must never
        # kill the heartbeat that keeps this replica in the quorum.
        self._health_fn = health_fn
        # chaos hook (Failure.PARTITION): a partitioned replica loses its
        # control plane too, so the drill pauses heartbeats alongside the
        # data-plane partition mask
        self.heartbeat_paused = False

        self._lock = threading.Condition()
        # quorum barrier state
        self._participants: Dict[int, QuorumMember] = {}
        self._checkpoint_metadata: Dict[int, str] = {}
        self._quorum_gen = 0
        self._latest: Optional[Quorum] = None
        self._latest_err: Optional[str] = None
        # should_commit barrier state
        self._commit_votes: Set[int] = set()
        self._commit_failures: Set[int] = set()
        self._commit_gen = 0
        self._commit_decision = False

        self._shutdown = False
        # persistent lighthouse connection for quorum forwarding; rounds are
        # normally sequential, but a timed-out round can overlap the next,
        # so serialize access
        self._lh_quorum_client: Optional[LighthouseClient] = None
        self._lh_client_lock = threading.Lock()

        self._sock = create_listener(bind, backlog=64)
        self._port: int = self._sock.getsockname()[1]

        threading.Thread(
            target=self._serve, name="tpuft_manager_accept", daemon=True
        ).start()
        threading.Thread(
            target=self._run_heartbeat, name="tpuft_manager_heartbeat", daemon=True
        ).start()
        logger.info(
            "[Replica %s] Manager listening on %s", replica_id, self.address()
        )

    # -- public -------------------------------------------------------------

    @property
    def port(self) -> int:
        return self._port

    def address(self) -> str:
        return f"{self._hostname}:{self._port}"

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            self._lock.notify_all()
        # best-effort: an in-flight quorum RPC may hold the lock until its
        # deadline; don't block shutdown on it (threads are daemonized)
        if self._lh_client_lock.acquire(timeout=1.0):
            try:
                if self._lh_quorum_client is not None:
                    self._lh_quorum_client.close()
                    self._lh_quorum_client = None
            finally:
                self._lh_client_lock.release()

    @staticmethod
    def _default_kill(msg: str) -> None:
        logger.warning("got kill request: %s", msg)
        os._exit(1)

    # -- background loops ---------------------------------------------------

    def _run_heartbeat(self) -> None:
        """Heartbeat the lighthouse until shutdown (``src/manager.rs:194-216``)."""
        client: Optional[LighthouseClient] = None
        while not self._shutdown:
            if self.heartbeat_paused:
                time.sleep(self._heartbeat_interval)
                continue
            health = None
            if self._health_fn is not None:
                try:
                    health = self._health_fn()
                except Exception:  # noqa: BLE001 — probe must not kill beats
                    health = None
            try:
                if client is None:
                    client = LighthouseClient(
                        self._lighthouse_addr, connect_timeout=self._connect_timeout
                    )
                client.heartbeat(self._replica_id, health=health)
            except (OSError, TimeoutError, WireError) as e:
                logger.info(
                    "[Replica %s] failed to send heartbeat to lighthouse: %s",
                    self._replica_id,
                    e,
                )
                if client is not None:
                    client.close()
                client = None
            time.sleep(self._heartbeat_interval)
        if client is not None:
            client.close()

    # -- connection handling ------------------------------------------------

    def _serve(self) -> None:
        while not self._shutdown:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            configure_server_socket(conn)
            threading.Thread(
                target=self._handle_conn,
                args=(conn,),
                name="tpuft_manager_conn",
                daemon=True,
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                msg_type, r = recv_frame(conn)
                if msg_type == MsgType.MGR_QUORUM_REQ:
                    self._handle_quorum(conn, r)
                elif msg_type == MsgType.MGR_CKPT_META_REQ:
                    rank = r.i64()
                    with self._lock:
                        meta = self._checkpoint_metadata.get(rank)
                    if meta is None:
                        send_error(conn, ErrCode.INVALID, "rank not found")
                    else:
                        send_frame(
                            conn,
                            MsgType.MGR_CKPT_META_RESP,
                            Writer().string(meta).payload(),
                        )
                elif msg_type == MsgType.MGR_SHOULD_COMMIT_REQ:
                    self._handle_should_commit(conn, r)
                elif msg_type == MsgType.MGR_KILL_REQ:
                    msg = r.string()
                    send_frame(conn, MsgType.MGR_KILL_RESP)
                    self._kill_fn(msg)
                else:
                    send_error(conn, ErrCode.INVALID, f"bad manager op {msg_type}")
        except (ConnectionError, OSError, WireError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- quorum barrier -----------------------------------------------------

    def _handle_quorum(self, conn: socket.socket, r: Reader) -> None:
        group_rank = r.i64()
        step = r.i64()
        checkpoint_metadata = r.string()
        shrink_only = r.boolean()
        init_sync = r.boolean()
        commit_failures = r.i64()
        timeout_ms = r.u64()
        deadline = time.monotonic() + timeout_ms / 1000.0

        logger.info(
            "[Replica %s] Start quorum for group_rank %d", self._replica_id, group_rank
        )

        with self._lock:
            self._checkpoint_metadata[group_rank] = checkpoint_metadata
            member = QuorumMember(
                replica_id=self._replica_id,
                address=self.address(),
                store_address=self._store_addr,
                step=step,
                world_size=self._world_size,
                shrink_only=shrink_only,
                commit_failures=commit_failures,
            )
            self._participants[group_rank] = member
            gen = self._quorum_gen

            if len(self._participants) == self._world_size:
                self._participants.clear()
                threading.Thread(
                    target=self._run_quorum,
                    args=(member, timeout_ms / 1000.0),
                    name="tpuft_manager_quorum",
                    daemon=True,
                ).start()

            failure: Optional[Tuple[ErrCode, str]] = None
            while self._quorum_gen == gen:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._shutdown:
                    failure = (
                        ErrCode.SHUTDOWN if self._shutdown else ErrCode.TIMEOUT,
                        f"manager quorum for group_rank {group_rank} "
                        f"{'aborted by shutdown' if self._shutdown else 'timed out'}",
                    )
                    break
                self._lock.wait(min(remaining, 0.1))
            quorum = self._latest
            quorum_err = self._latest_err

        # socket IO outside the server lock (a wedged client must not block
        # the barrier for other ranks)
        conn.settimeout(30.0)
        try:
            if failure is not None:
                send_error(conn, failure[0], failure[1])
                return

            if quorum is None:
                send_error(conn, ErrCode.UNKNOWN, quorum_err or "quorum failed")
                return

            logger.info(
                "[Replica %s] Finished quorum for group_rank %d",
                self._replica_id,
                group_rank,
            )
            try:
                reply = compute_quorum_results(
                    self._replica_id, group_rank, quorum, init_sync
                )
            except WireError as e:
                send_error(conn, e.code, str(e))
                return
            w = Writer()
            reply.encode(w)
            send_frame(conn, MsgType.MGR_QUORUM_RESP, w.payload())
        finally:
            conn.settimeout(None)

    def _run_quorum(self, requester: QuorumMember, timeout_s: float) -> None:
        """Forward the group's request to the lighthouse with retries
        (``src/manager.rs:218-306``) and broadcast the result.

        Unlike the reference (which leaves waiters to hit their own deadlines
        when every retry fails — a noted TODO at ``src/manager.rs:238``), we
        broadcast the failure so parked ranks fail fast.
        """
        logger.info(
            "[Replica %s] All workers joined - starting quorum", self._replica_id
        )
        quorum: Optional[Quorum] = None
        last_err = "unknown"
        for attempt in range(self._quorum_retries + 1):
            if self.heartbeat_paused:
                # chaos partition: the control plane is severed — a quorum
                # rpc is an implicit lighthouse heartbeat, so forwarding it
                # would keep this "partitioned" replica looking alive
                last_err = "control plane severed (chaos partition)"
                break
            try:
              with self._lh_client_lock:
                # persistent connection across rounds (the reference keeps a
                # tonic channel, src/manager.rs:250-306); recreated on failure
                if self._lh_quorum_client is None:
                    self._lh_quorum_client = LighthouseClient(
                        self._lighthouse_addr, connect_timeout=self._connect_timeout
                    )
                quorum = self._lh_quorum_client.quorum(
                    replica_id=requester.replica_id,
                    timeout=timeout_s,
                    address=requester.address,
                    store_address=requester.store_address,
                    step=requester.step,
                    world_size=requester.world_size,
                    shrink_only=requester.shrink_only,
                    commit_failures=requester.commit_failures,
                )
                break
            except (OSError, TimeoutError, WireError) as e:
                last_err = str(e)
                logger.info(
                    "[Replica %s] lighthouse quorum failed (attempt %d): %s",
                    self._replica_id,
                    attempt,
                    e,
                )
                if self._lh_quorum_client is not None:
                    self._lh_quorum_client.close()
                    self._lh_quorum_client = None
                if attempt < self._quorum_retries:
                    # only back off when another attempt remains — otherwise
                    # broadcast the failure to parked ranks immediately
                    time.sleep(
                        max(0.1, timeout_s / max(self._quorum_retries + 1, 1))
                    )

        with self._lock:
            self._latest = quorum
            self._latest_err = (
                None
                if quorum is not None
                else f"lighthouse quorum failed after {self._quorum_retries} retries: {last_err}"
            )
            self._quorum_gen += 1
            self._lock.notify_all()

    # -- should_commit barrier ----------------------------------------------

    def _handle_should_commit(self, conn: socket.socket, r: Reader) -> None:
        group_rank = r.i64()
        _step = r.i64()
        should_commit = r.boolean()
        timeout_ms = r.u64()
        deadline = time.monotonic() + timeout_ms / 1000.0

        logger.info(
            "[Replica %s] should_commit request from %d should_commit=%s",
            self._replica_id,
            group_rank,
            should_commit,
        )

        with self._lock:
            if not should_commit:
                self._commit_failures.add(group_rank)
            self._commit_votes.add(group_rank)
            gen = self._commit_gen

            if len(self._commit_votes) == self._world_size:
                decision = len(self._commit_failures) == 0
                logger.info(
                    "[Replica %s] should_commit completed should_commit=%s",
                    self._replica_id,
                    decision,
                )
                self._commit_decision = decision
                self._commit_votes.clear()
                self._commit_failures.clear()
                self._commit_gen += 1
                self._lock.notify_all()

            failure: Optional[Tuple[ErrCode, str]] = None
            while self._commit_gen == gen:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._shutdown:
                    failure = (
                        ErrCode.SHUTDOWN if self._shutdown else ErrCode.TIMEOUT,
                        f"should_commit for group_rank {group_rank} "
                        f"{'aborted by shutdown' if self._shutdown else 'timed out'}",
                    )
                    break
                self._lock.wait(min(remaining, 0.1))
            decision = self._commit_decision

        conn.settimeout(30.0)
        try:
            if failure is not None:
                send_error(conn, failure[0], failure[1])
                return
            send_frame(
                conn,
                MsgType.MGR_SHOULD_COMMIT_RESP,
                Writer().boolean(decision).payload(),
            )
        finally:
            conn.settimeout(None)


class ManagerClient(RpcClient):
    """Client used by every local rank to reach its group's ManagerServer
    (pyo3 analog ``src/lib.rs:153-282``)."""

    def __init__(self, addr: str, connect_timeout: float = 10.0) -> None:
        super().__init__(addr, connect_timeout=connect_timeout)

    def _call(self, msg_type: MsgType, payload: bytes, timeout: float) -> Tuple[int, Reader]:
        return self.call(msg_type, payload, timeout)

    def _quorum(
        self,
        group_rank: int,
        step: int,
        checkpoint_metadata: str,
        shrink_only: bool,
        timeout: float,
        init_sync: bool = True,
        commit_failures: int = 0,
    ) -> ManagerQuorumResult:
        w = (
            Writer()
            .i64(group_rank)
            .i64(step)
            .string(checkpoint_metadata)
            .boolean(shrink_only)
            .boolean(init_sync)
            .i64(commit_failures)
            .u64(int(timeout * 1000))
        )
        msg_type, r = self._call(MsgType.MGR_QUORUM_REQ, w.payload(), timeout)
        raise_if_error(msg_type, r)
        return ManagerQuorumResult.decode(r)

    def _checkpoint_metadata(self, rank: int, timeout: float) -> str:
        msg_type, r = self._call(
            MsgType.MGR_CKPT_META_REQ, Writer().i64(rank).payload(), timeout
        )
        raise_if_error(msg_type, r)
        return r.string()

    def should_commit(
        self, group_rank: int, step: int, should_commit: bool, timeout: float
    ) -> bool:
        w = (
            Writer()
            .i64(group_rank)
            .i64(step)
            .boolean(should_commit)
            .u64(int(timeout * 1000))
        )
        msg_type, r = self._call(MsgType.MGR_SHOULD_COMMIT_REQ, w.payload(), timeout)
        raise_if_error(msg_type, r)
        return r.boolean()

    def kill(self, msg: str, timeout: float = 10.0) -> None:
        msg_type, r = self._call(MsgType.MGR_KILL_REQ, Writer().string(msg).payload(), timeout)
        raise_if_error(msg_type, r)
