"""Checkpoint transport over the data-plane communicator.

Twin of the reference's PGTransport (``torchft/checkpointing/pg_transport.py``):
instead of a side HTTP channel, healing weights ride the same communicator
fabric as gradients — useful when DCN bandwidth between specific peers is
provisioned for the collective fabric, and required parity for deployments
that disallow extra listening ports.

Protocol per (src → dst) pair, tags offset into a dedicated range:

1. one framed metadata blob: pickled skeleton + per-array dtype/shape
   (the reference ships a pickled ``_StateDictMeta`` first, tags 1/2)
2. one framed raw-byte payload per array (tags 3+i there; base+1+i here)

``recv_checkpoint`` can optionally receive **in place** into the numpy
buffers of an existing state dict (``pg_transport.py:235-305``), avoiding
allocation for large models.
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
from typing import Dict, List, Optional, Tuple, TypeVar

import numpy as np

from torchft_tpu.checkpointing.serialization import (
    _extract_arrays,
    _leaf_meta,
    _restore_arrays,
    _resolve_dtype,
    array_chunk_ranges,
    as_byte_view,
    balanced_shares,
    heal_chunk_bytes,
    materialize_leaf,
)
from torchft_tpu import wire
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.communicator import Communicator
from torchft_tpu.observability import HealMetrics

logger = logging.getLogger(__name__)

T = TypeVar("T")

# tag namespace distinct from collectives (1000s/2000s), broadcast (3000s),
# alltoall (4000s), allgather (5000s) — allocated centrally in wire.py
_TAG_BASE = wire.HEAL_TAG_BASE

# Striped-heal tag offsets inside one step's 10M-wide tag range.  Distinct
# from the legacy per-array tags (base + 1 + i) so a striped healer paired
# with a legacy sender fails loudly on a tag mismatch instead of
# misreading frames.
_S_META_OFF = 7_000_000  # src → dst: pickled chunk index
_S_CHUNK_OFF = 7_000_001  # src → dst: + chunk_idx, raw chunk bytes
_S_CTRL_OFF = 8_000_000  # dst → src: pickled ("need", [idx...]) / ("done",)


class CommTransport(CheckpointTransport[T]):
    """Checkpoint transport over ``Communicator.send_bytes/recv_bytes``.

    The communicator must be the manager's (re)configured one — send/recv
    pair up between the quorum's replica ranks exactly like the reference's
    PG send/recv.  Per-step tag salting keeps a late transfer from a
    previous heal from pairing with a new one.
    """

    def __init__(self, comm: Communicator, timeout: float = 60.0) -> None:
        self._comm = comm
        self._timeout = timeout
        # striped-heal bookkeeping (see HTTPTransport for the same surface):
        # metrics of the most recent striped recv, and a chaos threshold
        # (``chaos.arm_heal_source_kill``) that makes this source abort its
        # communicator after serving ~N bytes of a striped heal
        self.last_heal_metrics: Optional[HealMetrics] = None
        self.chaos_die_after_bytes: Optional[int] = None
        self.chaos_arm: Optional[threading.Event] = None
        self.chaos_fired = threading.Event()

    def metadata(self) -> str:
        return "<comm>"

    @staticmethod
    def _tags(step: int) -> int:
        # wide per-step strides: even million-leaf state dicts can't bleed
        # into the next step's tag range.  Salted by the FULL step (tags are
        # uint64 on both tiers) so a transfer stale by any number of steps
        # can never alias a newer one.
        return _TAG_BASE * 1000 + step * wire.HEAL_STEP_TAG_STRIDE

    # submission window: at most this many leaves' host copies are alive at
    # once while streaming a heal (the sends pipeline; the window caps RSS)
    _SEND_WINDOW_LEAVES = 4

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: float
    ) -> None:
        import time as _time

        arrays: List[object] = []
        skeleton = _extract_arrays(state_dict, arrays)
        meta = pickle.dumps(
            (skeleton, [_leaf_meta(a) for a in arrays]),
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        base = self._tags(step)
        deadline = _time.monotonic() + timeout
        # leaves materialize to host lazily, one at a time, and are sent
        # zero-copy from their buffer; a bounded window of in-flight sends
        # overlaps D2H of leaf k+1 with the wire of leaf k while capping
        # peak extra host RSS at ~_SEND_WINDOW_LEAVES leaves
        works: List[tuple] = []
        for dst in dst_ranks:
            works.append((self._comm.send_bytes(meta, dst, tag=base), meta))
        for i, leaf in enumerate(arrays):
            blob = as_byte_view(materialize_leaf(leaf))
            for dst in dst_ranks:
                works.append(
                    (self._comm.send_bytes(blob, dst, tag=base + 1 + i), blob)
                )
            while len(works) > self._SEND_WINDOW_LEAVES * len(dst_ranks):
                work, _keepalive = works.pop(0)
                work.wait(timeout=max(0.0, deadline - _time.monotonic()))
        for work, _keepalive in works:
            work.wait(timeout=max(0.0, deadline - _time.monotonic()))
        logger.info(
            "sent checkpoint step=%d (%d arrays) to ranks %s",
            step,
            len(arrays),
            dst_ranks,
        )

    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        into: Optional[T] = None,
    ) -> T:
        base = self._tags(step)
        meta_blob = self._comm.recv_bytes(src_rank, tag=base).wait(timeout=timeout)
        skeleton, array_meta = pickle.loads(meta_blob)

        # optional in-place landing zone: matching numpy leaves of `into`
        inplace: List[Optional[np.ndarray]] = [None] * len(array_meta)
        if into is not None:
            existing: List[np.ndarray] = []
            _extract_arrays(into, existing)
            for i, ((dtype_name, shape), arr) in enumerate(
                zip(array_meta, existing)
            ):
                if (
                    isinstance(arr, np.ndarray)
                    and arr.dtype.name == dtype_name
                    and arr.shape == tuple(shape)
                    and arr.flags.c_contiguous
                    and arr.flags.writeable
                ):
                    inplace[i] = arr

        arrays: List[np.ndarray] = []
        for i, (dtype_name, shape) in enumerate(array_meta):
            target = inplace[i]
            if target is None:
                target = np.empty(tuple(shape), dtype=_resolve_dtype(dtype_name))
            try:
                # zero-copy: land the payload straight in the target buffer
                got = self._comm.recv_bytes_into(
                    src_rank, target.reshape(-1).view(np.uint8), tag=base + 1 + i
                ).wait(timeout=timeout)
                if got != target.nbytes:
                    raise ValueError(
                        f"checkpoint array {i}: payload {got} bytes != "
                        f"expected {target.nbytes}"
                    )
            except NotImplementedError:
                blob = self._comm.recv_bytes(src_rank, tag=base + 1 + i).wait(
                    timeout=timeout
                )
                as_byte_view(target)[:] = blob
            arrays.append(target)
        logger.info(
            "received checkpoint step=%d (%d arrays) from rank %d",
            step,
            len(arrays),
            src_rank,
        )
        return _restore_arrays(skeleton, arrays)

    # ------------------------------------------------------------------
    # striped healing
    # ------------------------------------------------------------------
    #
    # Unlike the legacy per-array framing, striped mode splits the RAW
    # array payloads into a chunk-addressable index
    # (``serialization.array_chunk_ranges``): every chunk is a byte range
    # of one array's buffer, so the healer lands frames from all sources
    # DIRECTLY in the final preallocated arrays — no serialized-stream
    # reassembly or post-load pass.  Chunk→source assignment is the
    # deterministic byte-balanced ``serialization.balanced_shares`` over
    # the canonical source list, computed identically on every peer; a
    # dead source's chunks are re-requested from a survivor over the
    # dst→src control channel (pull semantics grafted onto a push fabric).

    def send_checkpoint_striped(
        self,
        dst_ranks: List[int],
        step: int,
        state_dict: T,
        timeout: float,
        source_index: int = 0,
        num_sources: int = 1,
    ) -> None:
        if num_sources <= 1:
            self.send_checkpoint(dst_ranks, step, state_dict, timeout)
            return
        arrays: List[object] = []
        skeleton = _extract_arrays(state_dict, arrays)
        array_meta = [_leaf_meta(a) for a in arrays]
        sizes = [
            _resolve_dtype(d).itemsize * int(np.prod(s, dtype=np.int64))
            for d, s in array_meta
        ]
        chunks = array_chunk_ranges(sizes, heal_chunk_bytes())
        meta_blob = pickle.dumps(
            {"skeleton": skeleton, "array_meta": array_meta, "chunks": chunks},
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        shares = balanced_shares([e - s for _, s, e in chunks], num_sources)
        own = shares[source_index]
        deadline = time.monotonic() + timeout

        def _serve_dst(dst: int) -> None:
            base = self._tags(step)
            sent_bytes = 0
            # one-array materialization memo: a share's chunks are sorted,
            # so ranges of the same array are served back to back
            memo: Dict[int, np.ndarray] = {}

            def _chunk_view(i: int) -> memoryview:
                ai, start, stop = chunks[i]
                if ai not in memo:
                    memo.clear()
                    memo[ai] = materialize_leaf(arrays[ai])
                return as_byte_view(memo[ai])[start:stop]

            def _send_chunks(indices: List[int]) -> None:
                nonlocal sent_bytes
                window: List[tuple] = []
                for i in indices:
                    # the chaos trip wire honors its arm gate: bytes served
                    # before the event is set neither count nor kill
                    armed = self.chaos_arm is None or self.chaos_arm.is_set()
                    if (
                        armed
                        and self.chaos_die_after_bytes is not None
                        and sent_bytes >= self.chaos_die_after_bytes
                    ):
                        self.chaos_fired.set()
                        self._comm.abort("chaos: heal source killed mid-transfer")
                        raise ConnectionError(
                            "chaos: heal source killed mid-transfer"
                        )
                    blob = _chunk_view(i)
                    window.append(
                        (
                            self._comm.send_bytes(
                                blob, dst, tag=base + _S_CHUNK_OFF + i
                            ),
                            blob,
                        )
                    )
                    if armed:
                        sent_bytes += len(blob)
                    while len(window) > self._SEND_WINDOW_LEAVES:
                        work, _keep = window.pop(0)
                        work.wait(timeout=max(0.0, deadline - time.monotonic()))
                for work, _keep in window:
                    work.wait(timeout=max(0.0, deadline - time.monotonic()))

            self._comm.send_bytes(meta_blob, dst, tag=base + _S_META_OFF).wait(
                timeout=max(0.0, deadline - time.monotonic())
            )
            _send_chunks(own)
            # steal-service loop: answer ("need", [...]) re-requests for a
            # dead peer source's chunks until the healer says done (or the
            # deadline passes — e.g. the healer itself died).  NB the ctrl
            # recv is an ordinary op bounded by the communicator's op
            # timeout: deployments must keep comm timeout_s >= the heal
            # timeout (the Manager constructs both from the same knob)
            while time.monotonic() < deadline:
                try:
                    ctrl = pickle.loads(
                        self._comm.recv_bytes(dst, tag=base + _S_CTRL_OFF).wait(
                            timeout=max(0.0, deadline - time.monotonic())
                        )
                    )
                except Exception as e:  # noqa: BLE001 — healer gone: stop serving
                    logger.info(
                        "striped heal: control channel to dst %d closed (%s)",
                        dst,
                        e,
                    )
                    return
                if ctrl[0] == "done":
                    return
                assert ctrl[0] == "need", ctrl
                _send_chunks(list(ctrl[1]))

        if len(dst_ranks) == 1:
            _serve_dst(dst_ranks[0])
        else:
            errors: List[BaseException] = []

            def _run_serve(dst: int) -> None:
                try:
                    _serve_dst(dst)
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    errors.append(e)

            threads = [
                threading.Thread(
                    target=_run_serve,
                    args=(dst,),
                    name=f"tpuft_heal_src_{dst}",
                    daemon=True,
                )
                for dst in dst_ranks
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            # a failed or stuck serve must surface to the manager's error
            # funnel, not masquerade as a completed heal-send
            if errors:
                raise errors[0]
            stuck = [t.name for t in threads if t.is_alive()]
            if stuck:
                raise TimeoutError(
                    f"striped serve still running at deadline: {stuck}"
                )
        logger.info(
            "served striped checkpoint step=%d share %d/%d (%d/%d chunks) to %s",
            step,
            source_index,
            num_sources,
            len(own),
            len(chunks),
            dst_ranks,
        )

    def recv_checkpoint_striped(
        self,
        sources: List[Tuple[int, Optional[str]]],
        step: int,
        timeout: float,
        into: Optional[T] = None,
    ) -> T:
        """Striped heal over the communicator fabric.

        ``sources`` must be the CANONICAL ordered source list from the
        quorum — every sender computes its chunk share positionally against
        the same list, dead entries included.  Chunk frames from all
        sources are drained CONCURRENTLY by one select-driven op
        (``Communicator.heal_drain``) straight into the final array buffers
        (``into``'s matching arrays are reused in place, like the legacy
        path); per-chunk recv ops would serialize on the op thread and cap
        the heal at one link's bandwidth."""
        if len(sources) <= 1:
            src_rank, _meta = sources[0]
            return self.recv_checkpoint(
                src_rank, "<comm>", step, timeout, into=into
            )

        base = self._tags(step)
        deadline = time.monotonic() + timeout
        t0 = time.monotonic()
        src_ranks = [r for r, _ in sources]
        num_sources = len(src_ranks)

        def _remaining() -> float:
            return max(0.0, deadline - time.monotonic())

        # meta phase: every source pushes the same chunk index first; the
        # recv OPS serialize on the communicator's op thread (a wedged-but-
        # connected source therefore stalls this phase until the op watchdog
        # aborts — the documented wedge degradation), but the FRAMES arrive
        # concurrently so the common case is one quick pass; adopt the
        # first, verify the rest, mark dead sources (closed sockets error
        # fast and fail over)
        index: Optional[dict] = None
        dead: Dict[int, BaseException] = {}
        meta_works = [
            (s_rank, self._comm.recv_bytes(s_rank, tag=base + _S_META_OFF))
            for s_rank in src_ranks
        ]
        for s_rank, work in meta_works:
            try:
                meta = pickle.loads(work.wait(timeout=_remaining()))
                if index is None:
                    index = meta
                elif (
                    meta["array_meta"] != index["array_meta"]
                    or meta["chunks"] != index["chunks"]
                ):
                    raise ValueError(
                        f"source rank {s_rank} serves a different checkpoint "
                        f"than the adopted index"
                    )
            except Exception as e:  # noqa: BLE001 — source-level failover
                logger.warning(
                    "striped heal: no index from source rank %d (%s)", s_rank, e
                )
                dead[s_rank] = e
        if index is None:
            raise next(iter(dead.values()))

        skeleton = index["skeleton"]
        array_meta = index["array_meta"]
        chunks: List[Tuple[int, int, int]] = [tuple(c) for c in index["chunks"]]

        # final landing buffers, reusing matching arrays of ``into`` in
        # place exactly like the legacy single-source path
        inplace: List[Optional[np.ndarray]] = [None] * len(array_meta)
        if into is not None:
            existing: List[np.ndarray] = []
            _extract_arrays(into, existing)
            for i, ((dtype_name, shape), arr) in enumerate(
                zip(array_meta, existing)
            ):
                if (
                    isinstance(arr, np.ndarray)
                    and arr.dtype.name == dtype_name
                    and arr.shape == tuple(shape)
                    and arr.flags.c_contiguous
                    and arr.flags.writeable
                ):
                    inplace[i] = arr
        arrays: List[np.ndarray] = [
            inplace[i]
            if inplace[i] is not None
            else np.empty(tuple(shape), dtype=_resolve_dtype(dtype_name))
            for i, (dtype_name, shape) in enumerate(array_meta)
        ]
        chunk_views = [
            as_byte_view(arrays[ai])[start:stop] for ai, start, stop in chunks
        ]

        shares = balanced_shares([e - s for _, s, e in chunks], num_sources)
        expected = {
            src_ranks[i]: shares[i]
            for i in range(num_sources)
            if src_ranks[i] not in dead
        }
        orphans = [
            c
            for i in range(num_sources)
            if src_ranks[i] in dead
            for c in shares[i]
        ]

        try:
            drain = self._comm.heal_drain(
                chunk_views,
                expected,
                orphans,
                chunk_tag=lambda i: base + _S_CHUNK_OFF + i,
                ctrl_tag=base + _S_CTRL_OFF,
                make_need=lambda idxs: pickle.dumps(("need", list(idxs))),
                done_blob=pickle.dumps(("done",)),
                timeout_s=_remaining(),
            )
        except NotImplementedError:
            # tier without a concurrent drain: degrade to the single-source
            # heal from the first live source rather than a slow serialized
            # multi-recv that cannot beat one link anyway
            alive = [r for r in src_ranks if r not in dead]
            logger.warning(
                "striped heal: communicator has no heal_drain; falling back "
                "to single-source heal from rank %s",
                alive[0] if alive else src_ranks[0],
            )
            return self.recv_checkpoint(
                alive[0] if alive else src_ranks[0],
                "<comm>",
                step,
                timeout=_remaining(),
                into=into,
            )
        res = drain.wait(timeout=_remaining())
        dead.update(res["dead"])  # type: ignore[arg-type]

        total_bytes = sum(len(v) for v in chunk_views)
        self.last_heal_metrics = HealMetrics(
            step=step,
            num_sources=num_sources,
            bytes_total=total_bytes,
            duration_s=time.monotonic() - t0,
            per_source_bytes={
                f"rank{p}": n
                for p, n in res["per_source"].items()  # type: ignore[union-attr]
                if n
            },
            failed_sources=[f"rank{p}" for p in sorted(dead)],
            stolen_chunks=int(res["stolen"]),  # type: ignore[call-overload]
        )
        logger.info(
            "striped heal step=%d: %d bytes from %d/%d sources in %.3fs",
            step,
            total_bytes,
            num_sources - len(dead),
            num_sources,
            self.last_heal_metrics.duration_s,
        )
        return _restore_arrays(skeleton, arrays)

    def disallow_checkpoint(self) -> None:
        pass

    def shutdown(self, wait: bool = True) -> None:
        pass
