"""Checkpoint transport over the data-plane communicator.

Twin of the reference's PGTransport (``torchft/checkpointing/pg_transport.py``):
instead of a side HTTP channel, healing weights ride the same communicator
fabric as gradients — useful when DCN bandwidth between specific peers is
provisioned for the collective fabric, and required parity for deployments
that disallow extra listening ports.

Protocol per (src → dst) pair, tags offset into a dedicated range:

1. one framed metadata blob: pickled skeleton + per-array dtype/shape
   (the reference ships a pickled ``_StateDictMeta`` first, tags 1/2)
2. one framed raw-byte payload per array (tags 3+i there; base+1+i here)

``recv_checkpoint`` can optionally receive **in place** into the numpy
buffers of an existing state dict (``pg_transport.py:235-305``), avoiding
allocation for large models.
"""

from __future__ import annotations

import logging
import pickle
from typing import List, Optional, TypeVar

import numpy as np

from torchft_tpu.checkpointing.serialization import (
    _extract_arrays,
    _leaf_meta,
    _restore_arrays,
    _resolve_dtype,
    as_byte_view,
    materialize_leaf,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.communicator import Communicator

logger = logging.getLogger(__name__)

T = TypeVar("T")

# tag namespace distinct from collectives (1000s/2000s), broadcast (3000s),
# alltoall (4000s), allgather (5000s)
_TAG_BASE = 9000


class CommTransport(CheckpointTransport[T]):
    """Checkpoint transport over ``Communicator.send_bytes/recv_bytes``.

    The communicator must be the manager's (re)configured one — send/recv
    pair up between the quorum's replica ranks exactly like the reference's
    PG send/recv.  Per-step tag salting keeps a late transfer from a
    previous heal from pairing with a new one.
    """

    def __init__(self, comm: Communicator, timeout: float = 60.0) -> None:
        self._comm = comm
        self._timeout = timeout

    def metadata(self) -> str:
        return "<comm>"

    @staticmethod
    def _tags(step: int) -> int:
        # wide per-step strides: even million-leaf state dicts can't bleed
        # into the next step's tag range.  Salted by the FULL step (tags are
        # uint64 on both tiers) so a transfer stale by any number of steps
        # can never alias a newer one.
        return _TAG_BASE * 1000 + step * 10_000_000

    # submission window: at most this many leaves' host copies are alive at
    # once while streaming a heal (the sends pipeline; the window caps RSS)
    _SEND_WINDOW_LEAVES = 4

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: float
    ) -> None:
        import time as _time

        arrays: List[object] = []
        skeleton = _extract_arrays(state_dict, arrays)
        meta = pickle.dumps(
            (skeleton, [_leaf_meta(a) for a in arrays]),
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        base = self._tags(step)
        deadline = _time.monotonic() + timeout
        # leaves materialize to host lazily, one at a time, and are sent
        # zero-copy from their buffer; a bounded window of in-flight sends
        # overlaps D2H of leaf k+1 with the wire of leaf k while capping
        # peak extra host RSS at ~_SEND_WINDOW_LEAVES leaves
        works: List[tuple] = []
        for dst in dst_ranks:
            works.append((self._comm.send_bytes(meta, dst, tag=base), meta))
        for i, leaf in enumerate(arrays):
            blob = as_byte_view(materialize_leaf(leaf))
            for dst in dst_ranks:
                works.append(
                    (self._comm.send_bytes(blob, dst, tag=base + 1 + i), blob)
                )
            while len(works) > self._SEND_WINDOW_LEAVES * len(dst_ranks):
                work, _keepalive = works.pop(0)
                work.wait(timeout=max(0.0, deadline - _time.monotonic()))
        for work, _keepalive in works:
            work.wait(timeout=max(0.0, deadline - _time.monotonic()))
        logger.info(
            "sent checkpoint step=%d (%d arrays) to ranks %s",
            step,
            len(arrays),
            dst_ranks,
        )

    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        into: Optional[T] = None,
    ) -> T:
        base = self._tags(step)
        meta_blob = self._comm.recv_bytes(src_rank, tag=base).wait(timeout=timeout)
        skeleton, array_meta = pickle.loads(meta_blob)

        # optional in-place landing zone: matching numpy leaves of `into`
        inplace: List[Optional[np.ndarray]] = [None] * len(array_meta)
        if into is not None:
            existing: List[np.ndarray] = []
            _extract_arrays(into, existing)
            for i, ((dtype_name, shape), arr) in enumerate(
                zip(array_meta, existing)
            ):
                if (
                    isinstance(arr, np.ndarray)
                    and arr.dtype.name == dtype_name
                    and arr.shape == tuple(shape)
                    and arr.flags.c_contiguous
                    and arr.flags.writeable
                ):
                    inplace[i] = arr

        arrays: List[np.ndarray] = []
        for i, (dtype_name, shape) in enumerate(array_meta):
            target = inplace[i]
            if target is None:
                target = np.empty(tuple(shape), dtype=_resolve_dtype(dtype_name))
            try:
                # zero-copy: land the payload straight in the target buffer
                got = self._comm.recv_bytes_into(
                    src_rank, target.reshape(-1).view(np.uint8), tag=base + 1 + i
                ).wait(timeout=timeout)
                if got != target.nbytes:
                    raise ValueError(
                        f"checkpoint array {i}: payload {got} bytes != "
                        f"expected {target.nbytes}"
                    )
            except NotImplementedError:
                blob = self._comm.recv_bytes(src_rank, tag=base + 1 + i).wait(
                    timeout=timeout
                )
                as_byte_view(target)[:] = blob
            arrays.append(target)
        logger.info(
            "received checkpoint step=%d (%d arrays) from rank %d",
            step,
            len(arrays),
            src_rank,
        )
        return _restore_arrays(skeleton, arrays)

    def disallow_checkpoint(self) -> None:
        pass

    def shutdown(self, wait: bool = True) -> None:
        pass
