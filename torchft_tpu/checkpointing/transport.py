"""Checkpoint transport interface for live peer-to-peer healing.

Mirror of the reference ABC (``torchft/checkpointing/transport.py:14-68``):
a transport advertises ``metadata()`` (carried through the manager quorum so
peers can find it), serves the current state dict to recovering destination
ranks, and fetches a peer's state dict when this replica heals.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")


class CheckpointTransport(ABC, Generic[T]):
    """Live peer-to-peer checkpoint channel: serve the current state dict
    to recovering replicas and fetch a peer's when healing
    (``torchft/checkpointing/transport.py:14-68``)."""

    @abstractmethod
    def metadata(self) -> str:
        """Opaque metadata handed to recovering peers (e.g. a URL)."""

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: float
    ) -> None:
        """Make ``state_dict`` available to ``dst_ranks`` for ``step``."""

    def disallow_checkpoint(self) -> None:
        """Called after the quorum; the staged checkpoint may be dropped."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> T:
        """Fetch the checkpoint for ``step`` from the peer at ``metadata``."""

    # -- striped healing (multi-source recovery) ---------------------------
    #
    # A striped heal fetches disjoint chunk ranges of the SAME serialized
    # checkpoint from every healthy peer concurrently, reassigning a dead or
    # slow source's remaining chunks to survivors (the heal must survive
    # losing all but one source).  The base-class defaults degrade to the
    # single-peer methods so transports opt in incrementally.

    def send_checkpoint_striped(
        self,
        dst_ranks: List[int],
        step: int,
        state_dict: T,
        timeout: float,
        source_index: int = 0,
        num_sources: int = 1,
    ) -> None:
        """Serve this peer's share of a striped heal: chunk ``chunk_idx %
        num_sources == source_index`` of the canonical chunk index.  Pull
        transports (HTTP) ignore the share and simply stage; push transports
        send their share and then answer steal requests."""
        self.send_checkpoint(dst_ranks, step, state_dict, timeout)

    def recv_checkpoint_striped(
        self,
        sources: List[Tuple[int, Optional[str]]],
        step: int,
        timeout: float,
        **kwargs: object,
    ) -> T:
        """Fetch from ``sources`` — ordered (replica_rank, metadata) pairs;
        metadata None marks a source whose metadata could not be fetched
        (kept in the list so positional chunk assignments stay consistent
        across peers).  Default: single-source fallback on the first usable
        source."""
        src_rank, metadata = next(
            ((r, m) for r, m in sources if m is not None), sources[0]
        )
        return self.recv_checkpoint(src_rank, metadata or "", step, timeout, **kwargs)

    def shutdown(self, wait: bool = True) -> None:
        """Release resources (called from Manager.shutdown)."""
