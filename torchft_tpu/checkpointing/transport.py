"""Checkpoint transport interface for live peer-to-peer healing.

Mirror of the reference ABC (``torchft/checkpointing/transport.py:14-68``):
a transport advertises ``metadata()`` (carried through the manager quorum so
peers can find it), serves the current state dict to recovering destination
ranks, and fetches a peer's state dict when this replica heals.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Generic, List, TypeVar

T = TypeVar("T")


class CheckpointTransport(ABC, Generic[T]):
    """Live peer-to-peer checkpoint channel: serve the current state dict
    to recovering replicas and fetch a peer's when healing
    (``torchft/checkpointing/transport.py:14-68``)."""

    @abstractmethod
    def metadata(self) -> str:
        """Opaque metadata handed to recovering peers (e.g. a URL)."""

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: float
    ) -> None:
        """Make ``state_dict`` available to ``dst_ranks`` for ``step``."""

    def disallow_checkpoint(self) -> None:
        """Called after the quorum; the staged checkpoint may be dropped."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> T:
        """Fetch the checkpoint for ``step`` from the peer at ``metadata``."""

    def shutdown(self, wait: bool = True) -> None:
        """Release resources (called from Manager.shutdown)."""
