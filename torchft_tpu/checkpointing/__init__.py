"""Live peer-to-peer checkpoint transports (reference: ``torchft/checkpointing/``)."""

_LAZY = {
    "CheckpointTransport": ("torchft_tpu.checkpointing.transport", "CheckpointTransport"),
    "HTTPTransport": ("torchft_tpu.checkpointing.http_transport", "HTTPTransport"),
    "CommTransport": ("torchft_tpu.checkpointing.comm_transport", "CommTransport"),
}

__all__ = list(_LAZY)


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)
