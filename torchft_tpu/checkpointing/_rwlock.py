"""Readers-writer lock with timeouts.

Guards the live state dict while it is being served to healing peers, the
same role as the reference's two-mutex RWLock
(``torchft/checkpointing/_rwlock.py:46-136``): many concurrent checkpoint
readers, one exclusive writer (the train loop mutating weights), and every
acquire bounded by a timeout so a stuck peer can never wedge training.

This implementation is a single condition variable over reader/writer counts
(writer-preferring, so a steady stream of readers can't starve the train
loop).
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class RWLock:
    def __init__(self, timeout: float = 60.0) -> None:
        self._timeout = timeout
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def _acquire(self, as_writer: bool, timeout: Optional[float]) -> None:
        budget = self._timeout if timeout is None else timeout
        deadline = time.monotonic() + budget
        with self._cond:
            if as_writer:
                self._writers_waiting += 1
                try:
                    while self._writer or self._readers > 0:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise TimeoutError(
                                f"could not acquire write lock in {budget}s"
                            )
                        # a timed-out wait falls through to re-check the
                        # guard once more before the deadline check raises —
                        # a notify racing the deadline must not lose
                        self._cond.wait(remaining)
                    self._writer = True
                finally:
                    self._writers_waiting -= 1
                    if not self._writer:
                        # timed out: wake readers parked on writers_waiting>0
                        self._cond.notify_all()
            else:
                while self._writer or self._writers_waiting > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(f"could not acquire read lock in {budget}s")
                    self._cond.wait(remaining)
                self._readers += 1

    def r_lock(self, timeout: Optional[float] = None) -> "_Guard":
        self._acquire(as_writer=False, timeout=timeout)
        return _Guard(self, writer=False)

    def w_lock(self, timeout: Optional[float] = None) -> "_Guard":
        self._acquire(as_writer=True, timeout=timeout)
        return _Guard(self, writer=True)

    def r_release(self) -> None:
        with self._cond:
            assert self._readers > 0, "release without acquire"
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def w_release(self) -> None:
        with self._cond:
            assert self._writer, "release without acquire"
            self._writer = False
            self._cond.notify_all()


class _Guard:
    def __init__(self, lock: RWLock, writer: bool) -> None:
        self._lock = lock
        self._writer = writer

    def __enter__(self) -> "_Guard":
        return self

    def __exit__(self, *exc: object) -> None:
        if self._writer:
            self._lock.w_release()
        else:
            self._lock.r_release()
