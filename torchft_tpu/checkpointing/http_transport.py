"""HTTP checkpoint transport: per-replica HTTP server streaming live weights.

Twin of the reference transport (``torchft/checkpointing/http_transport.py``):
every worker runs a threading HTTP server; ``metadata()`` is its URL; healing
peers fetch ``/checkpoint/{step}/full`` (or ``/checkpoint/{step}/{i}`` chunks
in parallel); the RWLock freezes the state dict while it is being serialized
so the train loop can't mutate weights mid-transfer
(``http_transport.py:181-202``).

Divergence from the reference: the staged state is serialized once into
chunk buffers at ``send_checkpoint`` time (jax arrays must be device_get
anyway, so "staging to CPU" and "serializing" collapse into one step);
serving threads then just stream bytes, holding no lock against training.
"""

from __future__ import annotations

import io
import logging
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, TypeVar
from urllib.request import urlopen

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing.serialization import (
    dumps_pytree,
    load_pytree,
    loads_pytree,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport

logger = logging.getLogger(__name__)

T = TypeVar("T")


class HTTPTransport(CheckpointTransport[T]):
    """Serve/fetch live checkpoints over HTTP.

    Args:
        timeout: default deadline for fetches.
        num_chunks: >0 splits the serialized state into N byte-ranges fetched
            by parallel threads (``http_transport.py:219-241``); 0 streams
            one ``full`` payload.
    """

    def __init__(self, timeout: float = 60.0, num_chunks: int = 0) -> None:
        self._timeout = timeout
        self._num_chunks = num_chunks
        self._lock = RWLock(timeout=timeout)
        self._staged: Optional[Dict[str, object]] = None  # step, chunks
        self._allowed = threading.Event()

        transport = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: object) -> None:
                logger.debug("http_transport: " + fmt, *args)

            def do_GET(self) -> None:
                parts = [p for p in self.path.split("/") if p]
                # /checkpoint/{step}/{full|i}
                if len(parts) != 3 or parts[0] != "checkpoint":
                    self.send_error(404, "unknown path")
                    return
                # Wait for a checkpoint to be staged rather than 404ing a
                # peer that raced ahead (the quorum guarantees it's coming).
                if not transport._allowed.wait(timeout=transport._timeout):
                    self.send_error(503, "no checkpoint staged")
                    return
                with transport._lock.r_lock():
                    staged = transport._staged
                    if staged is None:
                        self.send_error(503, "no checkpoint staged")
                        return
                    step = int(parts[1])
                    if staged["step"] != step:
                        self.send_error(
                            404,
                            f"staged step {staged['step']} != requested {step}",
                        )
                        return
                    chunks: List[bytes] = staged["chunks"]  # type: ignore[assignment]
                    if parts[2] == "full":
                        payload = b"".join(chunks)
                    else:
                        idx = int(parts[2])
                        if idx >= len(chunks):
                            self.send_error(404, f"no chunk {idx}")
                            return
                        payload = chunks[idx]
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(payload)))
                self.send_header("X-Num-Chunks", str(len(chunks)))
                self.end_headers()
                self.wfile.write(payload)

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server(("0.0.0.0", 0), _Handler)
        self._port: int = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpuft_http_transport",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._port

    def metadata(self) -> str:
        return f"http://{socket.gethostname()}:{self._port}"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: float
    ) -> None:
        """Serialize once under the write lock, then serve lock-free."""
        blob = dumps_pytree(state_dict)
        if self._num_chunks > 0:
            n = self._num_chunks
            size = max(1, (len(blob) + n - 1) // n)
            chunks = [blob[i : i + size] for i in range(0, len(blob), size)] or [b""]
        else:
            chunks = [blob]
        with self._lock.w_lock(timeout=timeout):
            self._staged = {"step": step, "chunks": chunks}
        self._allowed.set()

    def disallow_checkpoint(self) -> None:
        self._allowed.clear()
        with self._lock.w_lock():
            self._staged = None

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> T:
        base = f"{metadata}/checkpoint/{step}"
        with urlopen(f"{base}/full" if self._num_chunks == 0 else f"{base}/0", timeout=timeout) as resp:
            if self._num_chunks == 0:
                return load_pytree(resp)  # type: ignore[return-value]
            first = resp.read()
            total = int(resp.headers.get("X-Num-Chunks", "1"))

        chunks: List[Optional[bytes]] = [None] * total
        chunks[0] = first
        errors: List[BaseException] = []

        def _fetch(i: int) -> None:
            try:
                with urlopen(f"{base}/{i}", timeout=timeout) as r:
                    chunks[i] = r.read()
            except BaseException as e:  # noqa: BLE001 — re-raised on the caller
                errors.append(e)

        threads = [
            threading.Thread(target=_fetch, args=(i,)) for i in range(1, total)
        ]
        deadline = time.monotonic() + timeout
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if errors:
            # a real fetch failure (404/refused) must not masquerade as a
            # timeout
            raise errors[0]
        if any(c is None for c in chunks):
            raise TimeoutError("chunked checkpoint fetch timed out")
        return loads_pytree(b"".join(chunks))  # type: ignore[arg-type]

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
