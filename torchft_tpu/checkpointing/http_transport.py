"""HTTP checkpoint transport: per-replica HTTP server streaming live weights.

Twin of the reference transport (``torchft/checkpointing/http_transport.py``):
every worker runs a threading HTTP server; ``metadata()`` is its URL; healing
peers fetch ``/checkpoint/{step}/full`` (or ``/checkpoint/{step}/{i}`` chunks
in parallel); the RWLock freezes the state dict while it is being serialized
so the train loop can't mutate weights mid-transfer
(``http_transport.py:181-202``).

Divergence from the reference: staging stores a serialization *plan* (the
tree skeleton + references to the immutable jax leaves; mutable numpy
leaves are snapshotted), and serving threads materialize one leaf at a time
while streaming it to the socket (the reference's incremental-save analog,
``_serialization.py:14-39``).  Peak extra host RSS during a heal send is
one leaf, not 1-2× the model; chunked fetches stream the byte range they
own the same way.  jax leaves are snapshotted on device at staging time so
a donating jit (e.g. HSDPTrainer's update) can't invalidate them while a
peer is still fetching.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from collections import deque
from io import BufferedWriter, RawIOBase
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple, TypeVar
from urllib.request import urlopen

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing.serialization import (
    PytreePlan,
    ViewReader as _ViewReader,
    load_pytree,
    plan_pytree,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.observability import HealMetrics

logger = logging.getLogger(__name__)

T = TypeVar("T")

# Per-request stall bound during a striped heal: a source that stops
# answering is declared dead after this long and its chunks are stolen by
# the surviving sources (the overall heal deadline still applies).
HEAL_SOURCE_TIMEOUT_ENV = "TORCHFT_HEAL_SOURCE_TIMEOUT_S"


def _heal_source_timeout(overall: float) -> float:
    raw = os.environ.get(HEAL_SOURCE_TIMEOUT_ENV)
    per_source = float(raw) if raw else 30.0
    return max(0.1, min(per_source, overall))


def _read_stream_into(resp, view: memoryview) -> None:
    """Drain exactly ``len(view)`` bytes from a response into ``view``."""
    off = 0
    while off < len(view):
        n = resp.readinto(view[off:])
        if not n:
            raise EOFError("truncated checkpoint response")
        off += n


class _RawSocketWriter(RawIOBase):
    """Adapts the handler's socket file to io.BufferedWriter."""

    def __init__(self, wfile) -> None:
        super().__init__()
        self._wfile = wfile

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        # honor the RawIOBase short-write contract: BufferedWriter retries
        # any remainder only if we report what was actually written
        return self._wfile.write(b)


class _ChaosWriter(RawIOBase):
    """Serving-path fault injector: counts bytes served across the whole
    transport and, when the armed hook trips, kills the server (the chaos
    drill's "heal source dies mid-transfer") and aborts this response."""

    def __init__(self, inner: RawIOBase, transport: "HTTPTransport") -> None:
        super().__init__()
        self._inner = inner
        self._transport = transport

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        transport = self._transport
        hook = transport.chaos_serve_hook
        with transport._bytes_served_lock:
            transport._bytes_served += len(b)
            served = transport._bytes_served
        if hook is not None and hook(served):
            # shut down off-thread: shutdown() joins the serve loop, and this
            # handler must die NOW with a torn connection, mid-payload
            threading.Thread(
                target=transport.shutdown, name="tpuft_chaos_kill", daemon=True
            ).start()
            raise ConnectionError("chaos: heal source killed mid-transfer")
        return self._inner.write(b)


# _ViewReader moved to serialization.ViewReader (shared with CommTransport)


class HTTPTransport(CheckpointTransport[T]):
    """Serve/fetch live checkpoints over HTTP.

    Args:
        timeout: default deadline for fetches.
        num_chunks: >0 splits the serialized state into N byte-ranges fetched
            by parallel threads (``http_transport.py:219-241``); 0 streams
            one ``full`` payload.
    """

    def __init__(
        self,
        timeout: float = 60.0,
        num_chunks: int = 0,
        heal_chunk_bytes: Optional[int] = None,
    ) -> None:
        self._timeout = timeout
        self._num_chunks = num_chunks
        self._heal_chunk_bytes = heal_chunk_bytes
        self._lock = RWLock(timeout=timeout)
        self._staged: Optional[Dict[str, object]] = None  # step, chunks
        self._allowed = threading.Event()
        # striped-heal bookkeeping: metrics of the most recent striped recv,
        # and a chaos hook (``chaos.arm_heal_source_kill``) that can make
        # this source die mid-serve to drill mid-heal failover
        self.last_heal_metrics: Optional[HealMetrics] = None
        self.chaos_serve_hook: Optional[Callable[[int], bool]] = None
        # count only striped (range) serving toward the chaos trip wire:
        # killing a single-source /full transfer has no survivor to fail
        # over to, which tests a different (fatal) scenario
        self.chaos_striped_only = False
        self._bytes_served = 0
        self._bytes_served_lock = threading.Lock()

        transport = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: object) -> None:
                logger.debug("http_transport: " + fmt, *args)

            def do_GET(self) -> None:
                parts = [p for p in self.path.split("/") if p]
                # /checkpoint/{step}/{full|index|i} or
                # /checkpoint/{step}/range/{start}/{stop}
                if (
                    len(parts) not in (3, 5)
                    or parts[0] != "checkpoint"
                    or (len(parts) == 5 and parts[2] != "range")
                ):
                    self.send_error(404, "unknown path")
                    return
                # Wait for a checkpoint to be staged rather than 404ing a
                # peer that raced ahead (the quorum guarantees it's coming).
                if not transport._allowed.wait(timeout=transport._timeout):
                    self.send_error(503, "no checkpoint staged")
                    return
                # the lock is only held to grab the plan reference — the
                # plan's leaves are self-contained snapshots, so streaming
                # happens lock-free and a concurrent disallow_checkpoint
                # (write lock, taken in the commit path) never waits on a
                # slow healer's socket
                with transport._lock.r_lock():
                    staged = transport._staged
                    plan: Optional[PytreePlan] = (
                        staged["plan"] if staged is not None else None  # type: ignore[assignment,index]
                    )
                    staged_step = staged["step"] if staged is not None else None
                if plan is None:
                    self.send_error(503, "no checkpoint staged")
                    return
                step = int(parts[1])
                if staged_step != step:
                    self.send_error(
                        404,
                        f"staged step {staged_step} != requested {step}",
                    )
                    return
                if parts[2] == "index":
                    # chunk-addressable index for striped healers: stable
                    # boundaries at array-payload granularity, identical on
                    # every peer serving the same step
                    body = json.dumps(
                        {
                            "total_len": plan.total_len,
                            "header_digest": plan.header_digest(),
                            "chunks": plan.chunk_ranges(
                                transport._heal_chunk_bytes
                            ),
                        }
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.send_header("X-Total-Len", str(plan.total_len))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                num_chunks = max(1, transport._num_chunks)
                chunk_size = -(-plan.total_len // num_chunks)
                if parts[2] == "range":
                    start, stop = int(parts[3]), int(parts[4])
                    if not 0 <= start <= stop <= plan.total_len:
                        self.send_error(
                            416, f"bad range [{start}, {stop}) of {plan.total_len}"
                        )
                        return
                elif parts[2] == "full":
                    start, stop = 0, plan.total_len
                else:
                    idx = int(parts[2])
                    if idx >= num_chunks:
                        self.send_error(404, f"no chunk {idx}")
                        return
                    start = idx * chunk_size
                    stop = min(plan.total_len, start + chunk_size)
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(stop - start))
                self.send_header("X-Num-Chunks", str(num_chunks))
                self.send_header("X-Total-Len", str(plan.total_len))
                self.send_header("X-Header-Digest", plan.header_digest())
                self.end_headers()
                # streams leaf by leaf: only leaves overlapping [start, stop)
                # are ever materialized on host.  The handler's wfile is an
                # unbuffered socket writer; batching the plan's small frame
                # headers with the payloads into 1 MB writes avoids
                # per-frame syscalls
                raw = _RawSocketWriter(self.wfile)
                if transport.chaos_serve_hook is not None and (
                    not transport.chaos_striped_only or parts[2] == "range"
                ):
                    raw = _ChaosWriter(raw, transport)
                buffered = BufferedWriter(raw, buffer_size=1 << 20)
                plan.write_range(start, stop, buffered)
                buffered.flush()

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        # dual-stack like the reference's checkpoint server
        # (torchft/http.py:11-13): bind [::] with v6only off where the
        # kernel allows, so v4 and v6 healers both reach us
        v6_server = None
        try:
            _Server.address_family = socket.AF_INET6
            v6_server = _Server(("::", 0), _Handler, bind_and_activate=False)
            v6_server.socket.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_V6ONLY, 0
            )
            v6_server.server_bind()
            v6_server.server_activate()
            self._server = v6_server
        except OSError:
            if v6_server is not None:
                v6_server.server_close()
            _Server.address_family = socket.AF_INET
            self._server = _Server(("0.0.0.0", 0), _Handler)
        self._port: int = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpuft_http_transport",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._port

    def metadata(self) -> str:
        return f"http://{socket.gethostname()}:{self._port}"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: float
    ) -> None:
        """Stage a streaming plan under the write lock; serving threads
        materialize leaves lazily (bytes are generated per-request, never
        staged)."""
        plan = plan_pytree(state_dict, snapshot=True)
        with self._lock.w_lock(timeout=timeout):
            self._staged = {"step": step, "plan": plan}
        self._allowed.set()

    def disallow_checkpoint(self) -> None:
        self._allowed.clear()
        with self._lock.w_lock():
            self._staged = None

    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        leaf_hook=None,
    ) -> T:
        """Fetch and deserialize a peer's live checkpoint.

        Default (num_chunks=0) is fully streaming: array payloads are read
        straight off the socket into preallocated arrays, and ``leaf_hook``
        (e.g. a ``jax.device_put`` with the healing replica's sharding) maps
        each leaf on arrival so its host copy dies immediately."""
        base = f"{metadata}/checkpoint/{step}"
        if self._num_chunks == 0:
            with urlopen(f"{base}/full", timeout=timeout) as resp:
                return load_pytree(resp, leaf_hook=leaf_hook)  # type: ignore[return-value]

        # chunked mode: parallel range fetches landing in one preallocated
        # buffer (no per-chunk bytes objects, no join copy)
        with urlopen(f"{base}/0", timeout=timeout) as resp:
            total = int(resp.headers.get("X-Num-Chunks", "1"))
            total_len = int(resp.headers["X-Total-Len"])
            chunk_size = -(-total_len // max(1, total))
            buf = bytearray(total_len)
            view = memoryview(buf)
            _read_stream_into(resp, view[: min(chunk_size, total_len)])

        done = [False] * total
        done[0] = True
        errors: List[BaseException] = []

        def _fetch(i: int) -> None:
            try:
                start = i * chunk_size
                stop = min(total_len, start + chunk_size)
                with urlopen(f"{base}/{i}", timeout=timeout) as r:
                    _read_stream_into(r, view[start:stop])
                done[i] = True
            except BaseException as e:  # noqa: BLE001 — re-raised on the caller
                errors.append(e)

        threads = [
            threading.Thread(target=_fetch, args=(i,)) for i in range(1, total)
        ]
        deadline = time.monotonic() + timeout
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if errors:
            # a real fetch failure (404/refused) must not masquerade as a
            # timeout
            raise errors[0]
        if not all(done):
            raise TimeoutError("chunked checkpoint fetch timed out")
        return load_pytree(_ViewReader(view), leaf_hook=leaf_hook)  # type: ignore[return-value]

    def recv_checkpoint_striped(
        self,
        sources: List[Tuple[int, Optional[str]]],
        step: int,
        timeout: float,
        leaf_hook=None,
    ) -> T:
        """Striped multi-source heal: fetch disjoint chunk ranges of the
        serialized checkpoint from every source concurrently into one
        preallocated buffer.

        One worker per source pulls chunks from a shared queue (natural work
        stealing: a fast source simply takes more chunks).  A source that
        errors or stalls past the per-request bound is declared dead, its
        in-flight chunk is requeued for the survivors, and the heal degrades
        all the way down to today's single-peer transfer before failing."""
        live = [(rank, meta) for rank, meta in sources if meta]
        if len(live) <= 1:
            return super().recv_checkpoint_striped(
                sources, step, timeout, leaf_hook=leaf_hook
            )

        deadline = time.monotonic() + timeout
        per_req_timeout = _heal_source_timeout(timeout)
        t0 = time.monotonic()

        # chunk index from the first source that answers
        index: Optional[dict] = None
        failed: List[str] = []
        for rank, meta in list(live):
            try:
                with urlopen(
                    f"{meta}/checkpoint/{step}/index", timeout=per_req_timeout
                ) as resp:
                    index = json.loads(resp.read())
                break
            except Exception as e:  # noqa: BLE001 — source-level failover
                logger.warning("striped heal: index fetch from %s failed: %s", meta, e)
                failed.append(meta)
                live.remove((rank, meta))
        if index is None:
            raise ConnectionError(
                f"striped heal: no source answered the chunk index ({failed})"
            )

        total_len = int(index["total_len"])
        digest = index.get("header_digest")
        chunks: deque = deque(tuple(c) for c in index["chunks"])
        num_chunks = len(chunks)
        buf = bytearray(total_len)
        view = memoryview(buf)

        lock = threading.Lock()
        state = {"done": 0, "stolen": 0}
        per_source_bytes: Dict[str, int] = {meta: 0 for _, meta in live}
        errors: List[BaseException] = []

        def _worker(meta: str) -> None:
            while True:
                with lock:
                    if state["done"] >= num_chunks:
                        return
                    job = chunks.popleft() if chunks else None
                if job is None:
                    # the remaining chunk(s) are in flight on ANOTHER worker
                    # — whose source may yet die and requeue them; staying
                    # available is what makes "survives losing P-1 sources"
                    # true for the last chunk too
                    if time.monotonic() > deadline:
                        return
                    time.sleep(0.02)
                    continue
                start, stop = job
                try:
                    if time.monotonic() > deadline:
                        raise TimeoutError("striped heal deadline exceeded")
                    with urlopen(
                        f"{meta}/checkpoint/{step}/range/{start}/{stop}",
                        timeout=per_req_timeout,
                    ) as r:
                        if int(r.headers["X-Total-Len"]) != total_len:
                            raise ValueError(
                                f"source {meta} serves a different checkpoint "
                                f"({r.headers['X-Total-Len']} != {total_len} bytes)"
                            )
                        if digest and r.headers.get("X-Header-Digest") not in (
                            None,
                            digest,
                        ):
                            raise ValueError(
                                f"source {meta} skeleton digest mismatch"
                            )
                        _read_stream_into(r, view[start:stop])
                    with lock:
                        state["done"] += 1
                        per_source_bytes[meta] += stop - start
                except BaseException as e:  # noqa: BLE001 — reassign + record
                    with lock:
                        chunks.appendleft((start, stop))
                        state["stolen"] += 1
                        failed.append(meta)
                        errors.append(e)
                    logger.warning(
                        "striped heal: source %s died mid-heal (%s); "
                        "reassigning its chunks",
                        meta,
                        e,
                    )
                    return

        threads = [
            threading.Thread(
                target=_worker, args=(meta,), name=f"tpuft_heal_{i}", daemon=True
            )
            for i, (_, meta) in enumerate(live)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if state["done"] != num_chunks:
            if errors:
                raise errors[0]
            raise TimeoutError(
                f"striped heal fetched {state['done']}/{num_chunks} chunks "
                f"before the deadline"
            )

        self.last_heal_metrics = HealMetrics(
            step=step,
            num_sources=len(sources),
            bytes_total=total_len,
            duration_s=time.monotonic() - t0,
            per_source_bytes={
                m: n for m, n in per_source_bytes.items() if n
            },
            failed_sources=failed,
            stolen_chunks=state["stolen"],
        )
        return load_pytree(_ViewReader(view), leaf_hook=leaf_hook)  # type: ignore[return-value]

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
